#!/bin/bash
# SUPERSEDED by run_round4.sh — it batches every pending
# measurement (including these) for one relay window; run that instead.
# The round-2 pending real-chip measurements (BASELINE.md / docs/PARITY.md
# known-gaps list), batched so one relay window covers them all.
#
# Run ONLY when the TPU relay is up:
#   ss -tln | grep -E ':(808[0-9]|81[01][0-9]) '
# and with NOTHING else dialing the relay (one python process at a time —
# a concurrent dial wedges the single-chip session; see the verify skill's
# environment notes). Never SIGKILL a run mid-compile: the watchdogged
# bench exits on its own, and a SIGKILLed dialer can take the relay down
# for hours.
#
# Results append to $OUT (one JSON line each, tagged by config).
set -u
cd "$(dirname "$0")/.."
OUT=${OUT:-/tmp/pending_measurements.jsonl}

# Refuse to dial a down relay (a wedged dial can take it down for hours).
if ! ss -tln | grep -qE ':(808[2-9]|809[0-9]|810[0-9]|811[0-7]) '; then
  echo "TPU relay ports 8082-8117 not listening; aborting before any dial" >&2
  exit 1
fi
# Match real python dialers only: a python argv[0] plus an argv token that
# IS the script path. pgrep -f would also match supervisor processes that
# merely mention these script names inside a long quoted argument.
busy=""
for cmd in /proc/[0-9]*/cmdline; do
  busy=$(tr '\0' '\n' <"$cmd" 2>/dev/null | awk '
    NR==1 && $0 !~ /python[0-9.]*$/ { exit }
    NR>1 && /(^|\/)(real_chip|bench)\.py$/ { print "busy"; exit }')
  [ -n "$busy" ] && break
done
if [ -n "$busy" ]; then
  echo "another benchmark process is already running (one dialer at a time)" >&2
  exit 1
fi

run() {
  echo "=== $* ===" >&2
  timeout 900 "$@" | tee -a "$OUT"
  echo >&2
}

# 1. end-to-end bench.py with the bf16-moment default (BENCH_r02 headline)
run python bench.py

# 2. ResNet-50 with the round-2 bf16 BN-normalize fix (was 15.8% MFU).
#    --profile captures a jax.profiler trace of 5 post-timing steps so
#    the remaining MFU gap can be attacked from evidence, not guesses
#    (VERDICT round-2 item 2).
run python benchmarks/real_chip.py --config resnet50 \
  --profile "${PROFILE_DIR:-/tmp/resnet50_profile}"

# 3. Inception-v3 — the reference's headline scaling model
run python benchmarks/real_chip.py --config inception_v3

# 4. seq-4096 training with chunked CE (flash attention + remat)
run python benchmarks/real_chip.py --config llama1b --seq 4096 \
  --logit-chunk 512 --moments bf16

# 5. int8 weight-only decode (expect up to ~2x tokens/sec: decode is
#    weight-read-bound)
run python benchmarks/real_chip.py --config llama1b_decode --quantize
run python benchmarks/real_chip.py --config llama1b_decode

# 6. self-speculative decode: int8 draft of the same model proposes 4
#    tokens per bf16 verification — output identical to plain greedy,
#    REAL acceptance profile (int8 argmax mostly agrees with bf16)
run python benchmarks/real_chip.py --config llama1b_decode --spec-k 4

echo "all pending measurements attempted; results in $OUT" >&2
