#!/bin/bash
# SUPERSEDED by run_round4.sh — it batches every pending
# measurement (including these) for one relay window; run that instead.
# Round-3 second-window measurements: the fused-statistics BatchNorm
# A/Bs and the clean seq-4096 comparison (the first window's chunked-CE
# number shared the host with a CPU test suite — re-measure idle).
#
# Same discipline as run_pending.sh: run ONLY when the relay is up,
# ONE dialer at a time, never SIGKILL a run mid-compile, idle host.
set -u
cd "$(dirname "$0")/.."
OUT=${OUT:-/tmp/round3b_measurements.jsonl}

if ! ss -tln | grep -qE ':(808[2-9]|809[0-9]|810[0-9]|811[0-7]) '; then
  echo "TPU relay ports 8082-8117 not listening; aborting before any dial" >&2
  exit 1
fi
busy=""
for cmd in /proc/[0-9]*/cmdline; do
  busy=$(tr '\0' '\n' <"$cmd" 2>/dev/null | awk '
    NR==1 && $0 !~ /python[0-9.]*$/ { exit }
    NR>1 && /(^|\/)(real_chip|bench)\.py$/ { print "busy"; exit }')
  [ -n "$busy" ] && break
done
if [ -n "$busy" ]; then
  echo "another benchmark process is already running (one dialer at a time)" >&2
  exit 1
fi

run() {
  echo "=== $* ===" >&2
  timeout 900 "$@" | tee -a "$OUT"
  echo >&2
}

# 1. ResNet-50 with FusedBatchNorm (was 16.1% with flax BN; the profile
#    put 48% of the step in separate stats passes). Re-profile so the
#    next gap is also evidence-backed.
run python benchmarks/real_chip.py --config resnet50 \
  --profile "${PROFILE_DIR:-/tmp/resnet50_fusedbn_profile}"

# 2. Inception-v3 with FusedBatchNorm (was 18.2% with flax BN)
run python benchmarks/real_chip.py --config inception_v3

# 3. seq-4096 A/B on an idle host: unchunked vs chunked CE, same
#    bf16-moment optimizer (first-window chunked number was 37.8% but
#    host-polluted; round-1 unchunked was 40.0% with a different optimizer)
run python benchmarks/real_chip.py --config llama1b --seq 4096 --moments bf16
run python benchmarks/real_chip.py --config llama1b --seq 4096 \
  --logit-chunk 512 --moments bf16

# 4. Profile the headline config: where do the non-MXU 43% of the
#    llama1b step go? (step 417 ms vs ~238 ms compute floor at 57% MFU)
run python benchmarks/real_chip.py --config llama1b --moments bf16 \
  --profile "${PROFILE_DIR_LLAMA:-/tmp/llama1b_profile}"

# 5. Continuous-batching engine at full occupancy vs the plain batch
#    decode (the same-batch delta is the token-granular scheduling tax)
run python benchmarks/real_chip.py --config llama1b_engine --steps 3
run python benchmarks/real_chip.py --config llama1b_engine --steps 3 --quantize

echo "round-3b measurements attempted; results in $OUT" >&2
