#!/bin/bash
# Resume of run_round4.sh after the 2026-08-01 window wedge (items 1-2
# were captured; inception timed out and wedged the session). Ordering
# is now risk-based: programs that have compiled on this chip before run
# first; brand-new compiles (prefix caching, kv-quantize, windowed
# flash, the Pallas-BN conv nets) run LAST, because a first-time compile
# can wedge the remote helper (verify skill: "Remote-compile quirks")
# and a wedge kills every subsequent dial in the window.
#
# Discipline (BASELINE.md / verify skill): ONE dialer at a time; nothing
# else may even START a bare python while this runs (interpreter boot
# dials the relay — blank PALLAS_AXON_POOL_IPS for any concurrent
# tooling); idle host; SIGTERM only. On ANY timeout (rc=124) this script
# STOPS — the session is assumed wedged and further dials would hang.
set -u
cd "$(dirname "$0")/.."
OUT=${OUT:-benchmarks/results/round4_window1.jsonl}

if ! ss -tln | grep -qE ':(808[2-9]|809[0-9]|810[0-9]|811[0-7]) '; then
  echo "TPU relay ports 8082-8117 not listening; aborting before any dial" >&2
  exit 1
fi

run() {
  local t="$1"; shift
  echo "=== $* ===" >&2
  timeout "$t" "$@" | tee -a "$OUT"
  local rc=${PIPESTATUS[0]}
  if [ "$rc" = 124 ]; then
    echo "TIMED OUT after ${t}s: $* — session likely wedged; stopping" >&2
    exit 124
  fi
  echo >&2
}

# -- known-compiled programs (ran in a previous window) --
# 4. seq-4096 A/B on an idle host: unchunked vs chunked CE, same
#    bf16-moment optimizer
run 900 python benchmarks/real_chip.py --config llama1b --seq 4096 --moments bf16
run 900 python benchmarks/real_chip.py --config llama1b --seq 4096 \
  --logit-chunk 512 --moments bf16
# coarser chunk: round 3 saw chunk=512 COST ~2 MFU points (the scan
# serializes the logits matmul); 1024 halves the serialization while
# still bounding logits memory at 1/4 of the full (B,S,V) tensor
run 900 python benchmarks/real_chip.py --config llama1b --seq 4096 \
  --logit-chunk 1024 --moments bf16

# 5. Profile the headline config: where do the non-MXU 43% go?
#    (--remat none: bench.py's 57.5% headline config, NOT the 45% full-
#    remat default)
run 900 python benchmarks/real_chip.py --config llama1b --moments bf16 \
  --remat none --profile "${PROFILE_DIR_LLAMA:-/tmp/llama1b_profile}"

# 6. Continuous-batching engine vs plain batch decode
run 900 python benchmarks/real_chip.py --config llama1b_engine --steps 3
run 900 python benchmarks/real_chip.py --config llama1b_engine --steps 3 --quantize

# 8a. int8-KV A/B baseline leg (plain decode compiled before)
run 900 python benchmarks/real_chip.py --config llama1b_decode --seq 2048 --new-tokens 64

# -- new programs (first-ever chip compile; each may wedge) --
# 7. prefix-caching TTFT
run 900 python benchmarks/real_chip.py --config llama1b_prefix --steps 16

# 8b/c. int8 KV cache, then composed with int8 weights
run 900 python benchmarks/real_chip.py --config llama1b_decode --seq 2048 --new-tokens 64 --kv-quantize
run 900 python benchmarks/real_chip.py --config llama1b_decode --seq 2048 --new-tokens 64 --kv-quantize --quantize

# 9. sliding-window training at long seq
run 900 python benchmarks/real_chip.py --config llama1b --seq 4096 --moments bf16 --window 1024

# 0'. Pallas-BN smoke first: a 30 s standalone compile of the new
#     kernels + an XLA-vs-Pallas reduce-rate A/B on ResNet-shaped
#     activations. If the kernels wedge the helper, we learn it here,
#     not via a 15-min ResNet timeout.
run 600 python benchmarks/pallas_bn_smoke.py

# 2'. ResNet-50 with the round-4 Pallas-streamed BN stats kernels
#     (16.1% flax BN, 15.8% custom-VJP XLA stats — the A/B this kernel
#     exists for), plus a trace to confirm the reduce time moved.
run 1200 python benchmarks/real_chip.py --config resnet50 \
  --profile "${PROFILE_DIR:-/tmp/resnet50_pallasbn_profile}"

# 10. ZeRO cross-replica weight update A/B (ISSUE 14): zero_sharding
#     on vs off at fixed batch, committing
#     benchmarks/results/zero_weight_update.json (step_time_ms, MFU,
#     optimizer-span ms per leg). NOTE single-chip expectation: data=1
#     makes the partition inert — this leg documents "off reproduces
#     current numbers"; the span win needs a multi-chip pod.
run 900 python bench.py --zero

# 3'. Inception-v3 with Pallas-BN. LAST: its fused-BN compile is the
#     suspected wedge of both the round-3 and round-4 windows.
run 1800 python benchmarks/real_chip.py --config inception_v3

echo "round-4 resume attempted; results in $OUT" >&2
