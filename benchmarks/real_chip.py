"""Single-chip benchmark runner for the BASELINE.md configs.

Runs ONE config per process invocation (the TPU relay in this environment
tolerates exactly one dialing process), entirely in the main process, and
prints one JSON line: step time, examples/sec(/chip), and MFU.

MFU accounting: transformers use the standard 6*P*T model-flops rule
(fwd+bwd, no attention or remat term); ResNet uses 3x its 4.1 GFLOP
forward. Peak defaults to v5e bf16 (197 TFLOP/s); override with
--peak-tflops (v4: 275, v5p: 459).

Usage::

    python benchmarks/real_chip.py --config resnet50 [--steps 30] ...

Configs map to BASELINE.md rows: mnist, resnet50, bert_base, llama1b,
llama1b_decode (KV-cache decode; --new-tokens sets the decode length,
step_time_ms is one single-token step, examples_per_sec is tokens/sec).
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), ".."))
)

import argparse
import json
import time

# Set by --profile: after each config's timed loop, a few extra steps run
# under jax.profiler.trace so the relay window yields a trace to attack
# the MFU gap with (VERDICT round-2 weak #1: ResNet needs on-chip
# profiling, not blind dtype fixes), without polluting the timed numbers.
_PROFILE_DIR = None


def _maybe_trace(run_steps) -> None:
    """Trace a short post-timing window; ``run_steps(n)`` must execute n
    steps and end with a host-fetch barrier."""
    if not _PROFILE_DIR:
        return
    import jax

    with jax.profiler.trace(_PROFILE_DIR):
        run_steps(5)
    # stderr: stdout is the machine-readable JSONL stream (tee'd into
    # benchmarks/results/ artifacts by the relay-window scripts).
    print(f"profile trace written to {_PROFILE_DIR}", file=_sys.stderr, flush=True)


def _bench_step(step, state, make_batch, steps: int, warmup: int = 3):
    """Time `steps` executions of step(state, batch); return (state, dt).

    Synchronization is a host fetch of the loss scalar, NOT
    ``block_until_ready``: on the tunneled TPU backend in this environment
    block_until_ready returns before the computation actually finishes,
    which silently times dispatch instead of execution. The batch is put
    on device once and reused so the timing measures the train step, not
    host->device transfer over the tunnel.
    """
    batch = make_batch()  # device-resident, reused every step
    for _ in range(warmup):
        state, loss = step(state, batch)
    float(loss)  # host fetch = real barrier
    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step(state, batch)
    loss = float(loss)
    dt = time.perf_counter() - t0

    def run_steps(n):
        # thread the live state (step may donate its input buffers);
        # the returned float loss stays untouched
        nonlocal state
        for _ in range(n):
            state, l = step(state, batch)
        float(l)

    _maybe_trace(run_steps)
    return state, dt, loss


def bench_mnist(args):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.models import mnist

    mesh = make_mesh({"data": len(jax.devices())})
    b = args.batch_size or 1024
    model = mnist.CNN()
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.random((b, 28, 28, 1), dtype=np.float32),
        "label": rng.integers(0, 10, size=b).astype(np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), batch["image"][:2])["params"]
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    step = build_train_step(mnist.loss_fn(model.apply), tx, mesh)
    make_batch = lambda: shard_batch(mesh, batch)
    state, dt, loss = _bench_step(step, state, make_batch, args.steps)
    return dict(examples=b, dt=dt, loss=loss, flops_fallback=None)


def _bench_bn_model(model, loss_fn, tx, batch, steps, flops_of=None):
    """Shared warm/time loop for BatchNorm models (carried batch_stats).

    Same sync rules as _bench_step: device-resident batch, host-fetch
    barrier. ``flops_of(step_fn, state, stats, dev_batch)`` may supply a
    FLOP count (e.g. XLA cost analysis); None means caller's fallback.
    """
    import jax
    import optax

    from tensorflowonspark_tpu.compute import TrainState
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch

    mesh = make_mesh({"data": len(jax.devices())})
    variables = model.init(jax.random.PRNGKey(0), batch["image"][:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    state = TrainState.create(params, tx)

    @jax.jit
    def step(state, stats, batch):
        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, stats, batch
        )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            ),
            new_stats,
            loss,
        )

    dev_batch = shard_batch(mesh, batch)
    flops = flops_of(step, state, batch_stats, dev_batch) if flops_of else None
    for _ in range(3):
        state, batch_stats, loss = step(state, batch_stats, dev_batch)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, batch_stats, loss = step(state, batch_stats, dev_batch)
    loss = float(loss)  # host fetch = timing barrier
    dt = time.perf_counter() - t0

    def run_steps(n):
        nonlocal state, batch_stats
        for _ in range(n):
            state, batch_stats, l = step(state, batch_stats, dev_batch)
        float(l)

    _maybe_trace(run_steps)
    return dt, loss, flops


def bench_resnet50(args):
    import numpy as np
    import optax

    from tensorflowonspark_tpu.models import resnet

    b = args.batch_size or 256
    model = resnet.ResNet(resnet.ResNetConfig.resnet50())
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.random((b, 224, 224, 3), dtype=np.float32),
        "label": rng.integers(0, 1000, size=b).astype(np.int32),
    }
    dt, loss, _ = _bench_bn_model(
        model, resnet.loss_fn(model), optax.sgd(0.1, momentum=0.9),
        batch, args.steps,
    )
    # ResNet-50 training ≈ 3x forward (4.1 GFLOPs) per image
    return dict(
        examples=b, dt=dt, loss=loss, flops_fallback=3 * 4.1e9 * b
    )


def bench_inception_v3(args):
    """Inception-v3 (the reference's headline scaling-chart model)."""
    import numpy as np
    import optax

    from tensorflowonspark_tpu.models import inception

    b = args.batch_size or 128
    model = inception.InceptionV3(inception.InceptionConfig.v3())
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.random((b, 299, 299, 3), dtype=np.float32),
        "label": rng.integers(0, 1000, size=b).astype(np.int32),
    }

    def flops_of(step, state, stats, dev_batch):
        # honest FLOP count from XLA's own cost analysis (covers the
        # SAME-padding grid variant exactly). cost_analysis reports the
        # per-device SPMD module, so scale by chip count to match the
        # global-batch flops convention of the other configs (main()
        # divides by n_chips for the per-chip MFU).
        import jax

        try:
            cost = step.lower(state, stats, dev_batch).compile().cost_analysis()
            return float(cost.get("flops", 0.0)) * len(jax.devices()) or None
        except Exception:
            return None

    dt, loss, flops = _bench_bn_model(
        model, inception.loss_fn(model), optax.sgd(0.045, momentum=0.9),
        batch, args.steps, flops_of=flops_of,
    )
    # fallback: the classic 3x5.7 GF/img training estimate
    return dict(
        examples=b, dt=dt, loss=loss, flops_fallback=flops or 3 * 5.7e9 * b
    )


def bench_bert_base(args):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.models import bert

    mesh = make_mesh({"data": len(jax.devices())})
    b = args.batch_size or 64
    seq = args.seq or 128
    cfg = bert.BertConfig(vocab_size=30522, max_seq_len=seq)
    model = bert.BertForMLM(cfg)
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, cfg.vocab_size, size=(b, seq)).astype(
            np.int32
        ),
        "targets": rng.integers(0, cfg.vocab_size, size=(b, seq)).astype(
            np.int32
        ),
    }
    params = model.init(jax.random.PRNGKey(0), batch["tokens"][:2])["params"]
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    tx = optax.adamw(1e-4)
    state = TrainState.create(params, tx)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["tokens"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["targets"]
        ).mean()

    step = build_train_step(loss_fn, tx, mesh)
    make_batch = lambda: shard_batch(mesh, batch)
    state, dt, loss = _bench_step(step, state, make_batch, args.steps)
    return dict(
        examples=b,
        dt=dt,
        loss=loss,
        flops_fallback=6 * n_params * b * seq,
    )


def bench_llama1b(args):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.models.llama import (
        Llama,
        LlamaConfig,
        llama_loss_fn,
        llama_param_shardings,
    )
    from tensorflowonspark_tpu.parallel import use_mesh
    import jax.numpy as jnp

    # mesh_axis="data" puts the bench in the pure data-parallel regime
    # (replicated params, replicated optimizer pre-ZeRO) — the
    # bench.py --zero A/B leg's configuration, where the cross-replica
    # sharded weight update (zero_sharding, arXiv 2004.13336) is the
    # variable under test. The default stays the FSDP headline config.
    mesh_axis = getattr(args, "mesh_axis", "fsdp")
    mesh = make_mesh({mesh_axis: len(jax.devices())})
    zero_sharding = getattr(args, "zero_sharding", True)
    b = args.batch_size or 8
    seq = args.seq or 1024
    # model_scale="tiny" swaps in the smoke-test decoder so the WHOLE
    # bench flow (state build, sharded step, timing, JSON assembly) can
    # run on CPU in seconds — bench.py's BENCH_SMOKE de-risk path
    scale = getattr(args, "model_scale", "1b")
    make_cfg = LlamaConfig.tiny if scale == "tiny" else LlamaConfig.llama_1b
    cfg = make_cfg(
        max_seq_len=seq,
        remat=getattr(args, "remat", "full") != "none",
        remat_policy=getattr(args, "remat", "full"),
        attention_impl=args.attention,
        sliding_window=getattr(args, "window", None),
    )
    model = Llama(cfg)
    rng = np.random.default_rng(0)
    tokens0 = np.zeros((2, seq + 1), np.int32)
    with use_mesh(mesh):
        params = model.init(jax.random.PRNGKey(0), tokens0[:, :-1])["params"]
    # HBM-footprint knobs (see compute/optim.py): on a 16 GB chip the
    # fp32-everything state is what caps MFU, not the matmuls.
    precision = getattr(args, "precision", "fp32")
    moments = getattr(args, "moments", "fp32")
    moment_dtype = jnp.bfloat16 if moments == "bf16" else None
    if precision == "mixed":
        from tensorflowonspark_tpu.compute import mixed_precision_adamw

        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        tx = mixed_precision_adamw(1e-4, moment_dtype=moment_dtype)
    elif moment_dtype is not None:
        from tensorflowonspark_tpu.compute import optim

        tx = optim.adamw(1e-4, moment_dtype=moment_dtype)
    else:
        tx = optax.adamw(1e-4)
    psh = llama_param_shardings(params, mesh)
    params = jax.tree.map(jax.device_put, params, psh)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    # shard_state (not bare create): commits the optimizer tree to the
    # layout-table shardings — with zero_sharding on, the Adam moments
    # land data-partitioned at init instead of being resharded by the
    # first jitted step
    from tensorflowonspark_tpu.compute import shard_state

    state = shard_state(
        TrainState.create(params, tx), mesh, psh, zero_sharding=zero_sharding
    )
    token_loss = llama_loss_fn(
        model, logit_chunk=getattr(args, "logit_chunk", None)
    )
    step = build_train_step(
        lambda p, bt: token_loss(p, bt["tokens"]), tx, mesh,
        param_shardings=psh, zero_sharding=zero_sharding,
    )
    batch = {
        "tokens": rng.integers(0, cfg.vocab_size, size=(b, seq + 1)).astype(
            np.int32
        )
    }
    make_batch = lambda: shard_batch(mesh, batch)
    with use_mesh(mesh):
        state, dt, loss = _bench_step(step, state, make_batch, args.steps)
    res = dict(
        examples=b,
        dt=dt,
        loss=loss,
        flops_fallback=6 * n_params * b * seq,
        n_params=n_params,
        tokens=b * seq,
    )
    if getattr(args, "params_digest", False):
        res["params_digest"] = _params_digest(state.params)
    if getattr(args, "measure_update", False):
        # LAST: the update-only timing loop donates `state`
        res["weight_update_ms"] = _time_weight_update(
            tx, mesh, psh, state, zero_sharding, args.steps
        )
    return res


def _params_digest(params) -> str:
    """sha256 over the host bytes of every param leaf, in tree-leaf
    order — the byte-identity currency of the --zero A/B gates."""
    import hashlib

    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()


def _time_weight_update(tx, mesh, psh, state, zero_sharding, steps):
    """Isolated optimizer-update time (ms/step): the weight update alone
    against fixed pre-placed gradients (each step consumes the previous
    step's donated state, so the chain serializes; one host fetch at the
    end is the timing barrier) — the 'optimizer-span ms' column of the
    bench.py --zero A/B artifact.
    Also feeds the train_weight_update_seconds histogram +
    train.weight_update span via build_update_step."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.compute import (
        build_update_step,
        zero_update_shardings,
    )

    upd = build_update_step(
        tx, mesh, param_shardings=psh, zero_sharding=zero_sharding
    )
    gsh = zero_update_shardings(state.params, mesh, psh) if zero_sharding else psh
    grads = jax.tree.map(
        lambda p, s: jax.device_put(
            jnp.full(p.shape, 1e-4, jnp.float32), s
        ),
        state.params,
        gsh,
    )
    state = upd(state, grads)  # compile + warm
    state = upd(state, grads)
    np.asarray(state.step)  # barrier
    n = max(2, int(steps))
    t0 = time.perf_counter()
    for _ in range(n):
        state = upd(state, grads)
    np.asarray(state.step)  # host fetch: the honest end-of-work barrier
    return round((time.perf_counter() - t0) / n * 1e3, 3)


def update_ab_digests(ns, k: int = 4):
    """Byte-identity probe for the bench.py --zero smoke gate: K
    IDENTICAL-gradient weight updates through the ZeRO-sharded and the
    replicated update step, from the same initial state; returns the
    two final-param sha256 digests. The sharded Adam/decay/lr
    arithmetic is elementwise per leaf, so the cross-replica
    decomposition must be byte-exact here — unlike the full train legs,
    whose gradient REDUCTION order legitimately differs
    (reduce-scatter vs all-reduce summation grouping, ~1 ulp on the
    embedding grad after a few steps)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.compute import (
        TrainState,
        build_update_step,
        shard_state,
        zero_update_shardings,
    )
    from tensorflowonspark_tpu.compute import optim
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.models.llama import (
        Llama,
        LlamaConfig,
        llama_param_shardings,
    )
    from tensorflowonspark_tpu.parallel import use_mesh

    mesh = make_mesh({getattr(ns, "mesh_axis", "data"): len(jax.devices())})
    scale = getattr(ns, "model_scale", "tiny")
    make_cfg = LlamaConfig.tiny if scale == "tiny" else LlamaConfig.llama_1b
    cfg = make_cfg(max_seq_len=ns.seq, remat=False)
    model = Llama(cfg)
    with use_mesh(mesh):
        params = model.init(
            jax.random.PRNGKey(0), np.zeros((2, ns.seq), np.int32)
        )["params"]
    tx = optim.adamw(1e-4, moment_dtype=jnp.bfloat16)
    psh = llama_param_shardings(params, mesh)
    rng = np.random.default_rng(7)
    grads_host = jax.tree.map(
        lambda p: (rng.standard_normal(p.shape) * 1e-2).astype(np.float32),
        params,
    )
    digests = {}
    for zero in (True, False):
        state = shard_state(
            TrainState.create(jax.tree.map(jnp.array, params), tx),
            mesh, psh, zero_sharding=zero,
        )
        gsh = zero_update_shardings(params, mesh, psh) if zero else psh
        grads = jax.tree.map(jax.device_put, grads_host, gsh)
        upd = build_update_step(
            tx, mesh, param_shardings=psh, zero_sharding=zero
        )
        for _ in range(k):
            state = upd(state, grads)
        digests["on" if zero else "off"] = _params_digest(state.params)
    return digests


def _llama1b_decode_setup(args, prompt_len: int | None = None):
    """Shared config/model/prompt build for the decode-side llama1b
    benches — ``llama1b_decode`` and ``llama1b_engine`` are read as a
    same-configuration pair (their delta is the engine's scheduling
    tax), so they must not drift. ``--seq`` overrides the prompt length
    (the KV-traffic knob: at long prompts the per-step cache read
    rivals the weight read, which is what ``--kv-quantize`` halves)."""
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.models.llama import Llama, LlamaConfig

    b = args.batch_size or 8
    if prompt_len is None:
        prompt_len = args.seq or 128
    new_tokens = args.new_tokens
    # speculative verification scratches up to spec_k slots past the
    # emitted text
    max_seq = prompt_len + new_tokens + (getattr(args, "spec_k", 0) or 0)
    if getattr(args, "model_scale", "1b") == "tiny":
        # CPU smoke path (--model-scale tiny): the full bench flow in
        # seconds, same shape logic — mirrors bench_llama1b's scale knob
        cfg = LlamaConfig.tiny(
            max_seq_len=max_seq,
            remat=False,
            attention_impl="xla",
            kv_cache_dtype=(
                "int8" if getattr(args, "kv_quantize", False) else "model"
            ),
        )
    else:
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=2048,
            intermediate_size=5632,
            num_layers=16,
            num_heads=16,
            num_kv_heads=16,
            max_seq_len=max_seq,
            dtype=jnp.bfloat16,
            remat=False,
            attention_impl="xla",  # decode is single-token; flash n/a
            kv_cache_dtype=(
                "int8" if getattr(args, "kv_quantize", False) else "model"
            ),
        )
    model = Llama(cfg)
    rng = np.random.default_rng(0)
    prompt_np = rng.integers(
        0, cfg.vocab_size, size=(b, prompt_len)
    ).astype(np.int32)
    return b, new_tokens, cfg, model, prompt_np


def bench_llama1b_decode(args):
    """KV-cache autoregressive decode: tokens/sec at batch 8."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.models.llama import generate

    b, new_tokens, cfg, model, prompt_np = _llama1b_decode_setup(args)
    prompt = jnp.asarray(prompt_np)
    from tensorflowonspark_tpu.ops.quant import quantize_tree

    spec_k = getattr(args, "spec_k", 0) or 0
    if spec_k and getattr(args, "quantize", False):
        # int8 target + int8 draft would be the SAME tree: acceptance
        # trivially 100%, the number would measure nothing
        raise SystemExit("--spec-k measures a bf16 target with an int8 "
                         "draft; drop --quantize")
    raw_params = model.init(jax.random.PRNGKey(0), prompt[:2])["params"]
    params = raw_params
    if getattr(args, "quantize", False):
        # int8 weight-only decode: weights consumed as int8 by the model
        params = quantize_tree(params)
        # the bf16 tree must actually free — this benchmark is HBM-bound
        # by construction (spec_k needs it for the draft; the combo with
        # --quantize is rejected above)
        raw_params = None
    params = jax.tree.map(jax.device_put, params)
    if spec_k:
        # SELF-speculation: the draft is the SAME weights quantized to
        # int8 — it mostly agrees with the bf16 target's argmax (high
        # acceptance) at roughly half the weight-read cost, so this
        # measures speculative decoding with a REAL acceptance profile
        # (a random independent draft would accept ~never).
        from tensorflowonspark_tpu.models.speculative import (
            speculative_generate,
        )

        draft_params = jax.tree.map(
            jax.device_put, quantize_tree(raw_params)
        )

        def decode():
            return speculative_generate(
                model, params, model, draft_params, prompt, new_tokens,
                k=spec_k,
            )

    else:

        def decode():
            return generate(model, params, prompt, new_tokens)

    out = decode()  # compile + warm
    np.asarray(out[0, :1])
    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = decode()
        np.asarray(out[0, :1])  # host fetch = real barrier
    dt = time.perf_counter() - t0

    def run_steps(n):
        for _ in range(n):
            np.asarray(decode()[0, :1])

    _maybe_trace(run_steps)
    # Reported so that step_time_ms is ONE single-token decode step and
    # examples_per_sec is new tokens/sec: examples = batch rows, dt
    # rescaled by tokens-per-generate.
    return dict(examples=b, dt=dt / new_tokens, loss=0.0)


def bench_llama1b_engine(args):
    """Continuous-batching engine throughput at full occupancy: the same
    1B decode as ``llama1b_decode`` but scheduled by
    ``serving.ContinuousBatcher`` (per-token host sync + slot
    scheduling). The delta vs ``llama1b_decode`` at the same batch IS
    the scheduling tax of token-granular admission; the win it buys —
    no convoying, immediate slot reuse — doesn't show in a
    full-occupancy steady-state number, so read the pair together."""
    import threading

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.serving import ContinuousBatcher

    b, new_tokens, cfg, model, prompts = _llama1b_decode_setup(args)
    prompt_len = prompts.shape[1]
    params = model.init(
        jax.random.PRNGKey(0), jnp.asarray(prompts[:2])
    )["params"]
    if getattr(args, "quantize", False):
        from tensorflowonspark_tpu.ops.quant import quantize_tree

        params = quantize_tree(params)
    params = jax.tree.map(jax.device_put, params)
    engine = ContinuousBatcher(
        model, params, slots=b, prompt_widths=(prompt_len,)
    )

    def fire_all(n_tokens):
        # Ferry worker-thread failures: a dead engine answers every
        # submit instantly with an error, and a swallowed exception
        # would let a microseconds-long round masquerade as a
        # measurement in the teed artifact.
        errors = [None] * b

        def one(i):
            try:
                engine.submit(prompts[i].tolist(), n_tokens)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors[i] = e

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(b)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for e in errors:
            if e is not None:
                raise e

    fire_all(4)  # compile prefill + admit + step, warm the loop
    t0 = time.perf_counter()
    for _ in range(args.steps):
        fire_all(new_tokens)
    dt = time.perf_counter() - t0
    engine.close()
    # Same reporting convention as llama1b_decode (dt rescaled by
    # tokens-per-round): step_time_ms is one single-token engine step at
    # full occupancy, examples_per_sec is tokens/sec across the batch.
    return dict(examples=b, dt=dt / new_tokens, loss=0.0)


def bench_llama1b_prefix(args):
    """Prefix-caching TTFT: requests share a long system prefix (7/8 of
    the prompt) with unique tails. Headline step_time_ms is the WARM
    per-request prefill latency (prefix resumed from the LRU);
    ttft_cold_ms in the same line is the first, miss-path request —
    their ratio is what `--gen-prefix-cache` buys a shared-system-prompt
    workload. Budget is 1 token, isolating prefill + admission."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.serving import ContinuousBatcher

    import dataclasses

    from tensorflowonspark_tpu.models.llama import Llama

    prompt_len = args.seq or 512
    shared_len = prompt_len * 7 // 8
    _, _, cfg, model, _ = _llama1b_decode_setup(args, prompt_len)
    # Every request here decodes 1 token, so the decode setup's
    # prompt+new_tokens KV sizing would inflate every slot AND every
    # prefix-store entry (each a full-max_seq_len single-row cache) by
    # ~50% at defaults — size the cache to this workload instead.
    cfg = dataclasses.replace(cfg, max_seq_len=prompt_len + 8)
    model = Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((2, 8), jnp.int32),
    )["params"]
    params = jax.tree.map(jax.device_put, params)
    rng = np.random.default_rng(0)
    shared = rng.integers(0, cfg.vocab_size, size=shared_len).tolist()
    tails = rng.integers(
        0, cfg.vocab_size, size=(args.steps + 2, prompt_len - shared_len)
    ).tolist()
    engine = ContinuousBatcher(
        model,
        params,
        slots=4,
        prompt_widths=(prompt_len,),
        prefill_chunk=min(128, cfg.max_seq_len),
        prefix_cache=8,
    )
    try:
        # warm the compiled programs on an unrelated prompt (chunk,
        # sample, admit, step) so cold-vs-warm isolates the PREFIX
        # reuse, not XLA compilation
        engine.submit(
            rng.integers(0, cfg.vocab_size, size=prompt_len).tolist(), 1
        )
        t0 = time.perf_counter()
        engine.submit(shared + tails[0], 1)  # miss: full prefill
        cold = time.perf_counter() - t0
        # Prime the store with the system prefix ITSELF (the documented
        # server-startup pattern): its full-prompt entry lets every
        # warm request resume at shared_len exactly, rather than at the
        # nearest exponential chunk boundary.
        engine.submit(shared, 1)
        hits_before = engine.stats()["prefix_hits"]
        t0 = time.perf_counter()
        for i in range(args.steps):
            engine.submit(shared + tails[i + 1], 1)  # hits: resume
        dt = time.perf_counter() - t0
        stats = engine.stats()
        # Delta, not total: the prime request can itself hit a
        # chunk-boundary entry from the cold request, which would mask
        # a warm-loop miss in a >= total check.
        if stats["prefix_hits"] - hits_before != args.steps:
            raise RuntimeError(
                f"prefix bench expected {args.steps} warm hits, got "
                f"{stats['prefix_hits'] - hits_before} — a warm request "
                f"missed; the headline would include a cold prefill"
            )
    finally:
        engine.close()
    return dict(
        examples=1,
        dt=dt,
        loss=0.0,
        extra={
            "ttft_cold_ms": round(cold * 1e3, 2),
            "prompt_len": prompt_len,
            "shared_len": shared_len,
            "prefix_hits": stats["prefix_hits"],
            "prefix_tokens_saved": stats["prefix_tokens_saved"],
        },
    )


V5E_PEAK_TFLOPS = 197.0  # per-chip bf16 peak (shared with bench.py)

CONFIGS = {
    "mnist": bench_mnist,
    "resnet50": bench_resnet50,
    "inception_v3": bench_inception_v3,
    "bert_base": bench_bert_base,
    "llama1b": bench_llama1b,
    "llama1b_decode": bench_llama1b_decode,
    "llama1b_engine": bench_llama1b_engine,
    "llama1b_prefix": bench_llama1b_prefix,
}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--config", choices=sorted(CONFIGS), required=True)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--attention", default="auto")
    p.add_argument(
        "--remat", choices=("full", "dots", "none"), default="full"
    )
    p.add_argument(
        "--precision",
        choices=("fp32", "mixed"),
        default="fp32",
        help="llama1b: param storage (mixed = bf16 params + fp32 master)",
    )
    p.add_argument(
        "--moments",
        choices=("fp32", "bf16"),
        default="fp32",
        help="llama1b: Adam moment storage dtype",
    )
    p.add_argument(
        "--logit-chunk",
        type=int,
        default=None,
        help="llama1b: chunked-CE chunk length (skips (B,S,V) logits)",
    )
    p.add_argument(
        "--new-tokens",
        type=int,
        default=256,
        help="decode length for llama1b_decode/llama1b_engine",
    )
    p.add_argument(
        "--quantize",
        action="store_true",
        help="llama1b_decode/llama1b_engine: int8 weight-only decode "
        "(ops/quant.py)",
    )
    p.add_argument(
        "--window",
        type=int,
        default=None,
        help="llama1b: sliding-window attention width (the flash "
        "kernel's window-restricted grids make the step O(S*W) — A/B "
        "against full attention at --seq 4096)",
    )
    p.add_argument(
        "--kv-quantize",
        action="store_true",
        help="llama decode configs: int8 KV cache "
        "(kv_cache_dtype='int8' — halves cache HBM footprint and "
        "per-step cache reads; composes with --quantize)",
    )
    p.add_argument(
        "--spec-k",
        type=int,
        default=0,
        help="llama1b_decode: self-speculative decoding with an int8 "
        "draft of the same model proposing K tokens per verification "
        "(0 = off); output identical to plain greedy",
    )
    p.add_argument(
        "--peak-tflops",
        type=float,
        default=V5E_PEAK_TFLOPS,
        help="per-chip bf16 peak",
    )
    p.add_argument(
        "--model-scale",
        choices=("1b", "tiny"),
        default="1b",
        help="llama configs: 'tiny' swaps in the smoke-test decoder so "
        "the full bench flow runs on CPU in seconds",
    )
    p.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="after the timed loop, trace 5 extra steps with "
        "jax.profiler into DIR (TensorBoard-readable; does not touch "
        "the timed numbers)",
    )
    args = p.parse_args(argv)
    global _PROFILE_DIR
    _PROFILE_DIR = args.profile

    import jax

    res = CONFIGS[args.config](args)
    n_chips = len(jax.devices())
    step_time = res["dt"] / args.steps
    eps = res["examples"] / step_time
    out = {
        "config": args.config,
        "backend": jax.default_backend(),
        "chips": n_chips,
        "step_time_ms": round(step_time * 1e3, 2),
        "examples_per_sec": round(eps, 1),
        "examples_per_sec_per_chip": round(eps / n_chips, 1),
        "final_loss": round(res["loss"], 4),
    }
    if res.get("tokens"):
        out["tokens_per_sec_per_chip"] = round(
            res["tokens"] / step_time / n_chips
        )
    if res.get("flops_fallback"):
        mfu = res["flops_fallback"] / step_time / n_chips / (
            args.peak_tflops * 1e12
        )
        out["mfu_pct"] = round(mfu * 100, 1)
    if res.get("n_params"):
        out["n_params_m"] = round(res["n_params"] / 1e6)
    out.update(res.get("extra", {}))
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
