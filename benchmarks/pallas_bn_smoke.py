"""Chip smoke + rate A/B for the Pallas BN stats kernels.

Two jobs, in ~a minute of chip time on a deliberately SMALL program:

1. De-risk: both relay windows wedged during fused-BN conv-net compiles
   (PARITY.md "Known gaps"); this compiles the round-4 Pallas stats
   kernels (`ops/bn_kernels.py`) standalone — if THEY wedge the remote
   compile helper, we learn it on a 30 s program, not a 15-minute
   ResNet-50 timeout that kills the window.

2. Evidence: the round-4 ResNet finding is that XLA's
   `convert_reduce_fusion` runs at ~20-30% of streaming bandwidth. This
   prints the per-pass effective GB/s of the XLA reduce pair vs the
   Pallas kernel on the same ResNet-shaped activations, so the kernel's
   premise is measured directly, not inferred from a full-model trace.

Output: one JSON line per shape on stdout (machine-readable, tee-able
into benchmarks/results/), human notes on stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))


def _bench(fn, *args, iters: int = 20):
    import jax

    # Timing barrier = host fetch of one element per output leaf:
    # block_until_ready on the tunneled backend returns before execution
    # finishes (BASELINE.md note).
    def fetch(o):
        return [float(x.ravel()[0]) for x in jax.tree.leaves(o)]

    out = fn(*args)
    jax.block_until_ready(out)
    fetch(out)
    t0 = time.perf_counter()
    for _i in range(iters):
        out = fn(*args)
    fetch(out)
    return (time.perf_counter() - t0) / iters


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.ops import bn_kernels
    from tensorflowonspark_tpu.ops.batch_norm import fused_batch_norm

    backend = jax.default_backend()
    rng = np.random.default_rng(0)

    # ResNet-50 b=256 layer shapes: early (big spatial, narrow C), late
    # (small spatial, wide C) — the two extremes the reduce must handle —
    # PLUS the narrow/non-128-aligned channel counts the adopting models
    # actually have (Inception-v3 BN at C=32/48/80, ResNet stem C=64):
    # sub-128-lane column blocks are where Mosaic tiling constraints
    # bite, so de-risk them here on a 30 s program, not in the conv-net
    # compile that burns the relay window.
    shapes = [
        (256 * 56 * 56, 256),
        (256 * 14 * 14, 1024),
        (256 * 112 * 112, 32),  # Inception stem
        (256 * 56 * 56, 48),  # Inception narrow branch
        (256 * 28 * 28, 80),  # Inception 5b input
        (256 * 56 * 56, 64),  # ResNet stem
    ]
    if backend != "tpu":
        # CPU flow-check only: interpreter-mode kernels on tiny shapes
        # (rates are meaningless off-chip); keep a narrow-lane and a
        # non-aligned case in the flow-check too.
        bn_kernels.INTERPRET = True
        shapes = [(1030, 65), (515, 48)]
    for rows, cols in shapes:
        x = jnp.asarray(rng.standard_normal((rows, cols), np.float32), jnp.bfloat16)
        dy = jnp.asarray(rng.standard_normal((rows, cols), np.float32), jnp.bfloat16)
        stream_gb = rows * cols * 2 / 1e9

        xla_pair = jax.jit(
            lambda a: (
                jnp.sum(a.astype(jnp.float32), 0),
                jnp.sum(a.astype(jnp.float32) ** 2, 0),
            )
        )
        pallas_pair = jax.jit(bn_kernels.pair_stats)
        pallas_cross = jax.jit(bn_kernels.cross_stats)

        t_xla = _bench(xla_pair, x)
        t_pl = _bench(pallas_pair, x)
        t_cr = _bench(pallas_cross, dy, x)
        print(
            json.dumps(
                {
                    "config": "pallas_bn_smoke",
                    "backend": backend,
                    "rows": rows,
                    "cols": cols,
                    "xla_pair_ms": round(t_xla * 1e3, 3),
                    "pallas_pair_ms": round(t_pl * 1e3, 3),
                    "pallas_cross_ms": round(t_cr * 1e3, 3),
                    "xla_pair_gbps": round(stream_gb / t_xla, 1),
                    "pallas_pair_gbps": round(stream_gb / t_pl, 1),
                    "pallas_cross_gbps": round(2 * stream_gb / t_cr, 1),
                }
            ),
            flush=True,
        )

    # Full fwd+bwd through the custom VJP (the program ResNet will run).
    # impl="pallas" explicitly: on CPU, "auto" would silently take the
    # XLA branch and never exercise the kernel wiring this smoke is for
    # (interpret mode is already on there).
    fb_shape = (64, 28, 28, 256) if backend == "tpu" else (2, 5, 5, 8)
    x4 = jnp.asarray(rng.standard_normal(fb_shape, np.float32), jnp.bfloat16)
    g = jnp.ones((fb_shape[-1],), jnp.float32)
    b = jnp.zeros((fb_shape[-1],), jnp.float32)

    @jax.jit
    def fwd_bwd(x, g, b):
        def loss(x, g, b):
            y = fused_batch_norm(x, g, b, 1e-5, impl="pallas")
            return jnp.sum(y.astype(jnp.float32))

        return jax.grad(loss, argnums=(0, 1, 2))(x, g, b)

    t_fb = _bench(fwd_bwd, x4, g, b, iters=10)
    print(
        json.dumps(
            {
                "config": "pallas_bn_smoke_fwdbwd",
                "backend": backend,
                "shape": list(x4.shape),
                "fwd_bwd_ms": round(t_fb * 1e3, 3),
            }
        ),
        flush=True,
    )
    print("pallas BN smoke complete", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
