"""Summarize a jax.profiler trace directory into a top-ops cost table.

The profiler (`benchmarks/real_chip.py --profile DIR`) writes a
TensorBoard-readable run under ``DIR/plugins/profile/<run>/`` containing a
Chrome-trace export ``*.trace.json.gz``. TensorBoard isn't part of this
environment's loop, so this tool answers the question the trace was
captured for — "where does the step time go?" — directly in the terminal:

    python benchmarks/trace_summary.py /tmp/resnet50_profile [--top 30]

It aggregates complete events (`ph == "X"`) by name within each process
lane ("pid"), reporting per-lane totals and the top ops by summed
duration. Device lanes (TPU/XLA op activity) are what matters for MFU
analysis; host lanes show dispatch/infeed overhead. Events that overlap
hierarchically within one thread (XLA module > fusion > op) would
double-count if summed naively, so per-(tid) self-time is computed by
subtracting child durations nested inside a parent event.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys


def find_trace_files(root: str) -> list[str]:
    pats = [
        os.path.join(root, "**", "*.trace.json.gz"),
        os.path.join(root, "**", "*.trace.json"),
    ]
    out: list[str] = []
    for p in pats:
        out.extend(glob.glob(p, recursive=True))
    return sorted(out)


def load_events(path: str) -> dict:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        return json.load(f)


def self_times(events: list[dict]) -> "collections.Counter[tuple]":
    """Per-(pid, tid) nesting-aware self time, keyed by (pid, name).

    Chrome-trace complete events within one thread nest like a call stack.
    Sort by (start, -dur); maintain a stack of open intervals; an event's
    self time is its duration minus the durations of its direct children.
    """
    per_thread: dict = collections.defaultdict(list)
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        per_thread[(e.get("pid"), e.get("tid"))].append(e)

    self_us: "collections.Counter[tuple]" = collections.Counter()
    for (pid, _tid), evs in per_thread.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list[dict] = []  # open events, each with _child_us accumulator
        for e in evs:
            ts, dur = e["ts"], e["dur"]
            while stack and ts >= stack[-1]["ts"] + stack[-1]["dur"]:
                done = stack.pop()
                self_us[(pid, done["name"])] += done["dur"] - done["_child_us"]
            if stack:
                stack[-1]["_child_us"] += dur
            e = dict(e, _child_us=0)
            stack.append(e)
        while stack:
            done = stack.pop()
            self_us[(pid, done["name"])] += done["dur"] - done["_child_us"]
    return self_us


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="trace_summary")
    ap.add_argument("trace_dir", help="directory passed to --profile")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument(
        "--lane",
        default=None,
        help="only lanes whose name contains this substring (e.g. 'TPU')",
    )
    args = ap.parse_args(argv)

    files = find_trace_files(args.trace_dir)
    if not files:
        print(f"no *.trace.json[.gz] under {args.trace_dir}", file=sys.stderr)
        return 1

    for path in files:
        data = load_events(path)
        events = data.get("traceEvents", [])
        pid_names: dict = {}
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pid_names[e.get("pid")] = e.get("args", {}).get("name", "")

        self_us = self_times(events)

        lane_total: "collections.Counter" = collections.Counter()
        for (pid, _name), us in self_us.items():
            lane_total[pid] += us

        print(f"== {os.path.relpath(path, args.trace_dir)}")
        for pid, total in lane_total.most_common():
            lname = pid_names.get(pid, str(pid))
            if args.lane and args.lane.lower() not in lname.lower():
                continue
            print(f"\n-- lane pid={pid} {lname!r}: total self-time {total/1e3:.2f} ms")
            ops = [(n, us) for (p, n), us in self_us.items() if p == pid]
            ops.sort(key=lambda kv: -kv[1])
            for name, us in ops[: args.top]:
                pct = 100.0 * us / total if total else 0.0
                print(f"  {us/1e3:10.3f} ms  {pct:5.1f}%  {name[:120]}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
