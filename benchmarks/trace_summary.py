"""Shim: relocated to :mod:`tensorflowonspark_tpu.obs.trace_report`.

The trace summarizer grew an op classifier and a JSON report artifact
and moved into the package proper (the ``obs/`` observability layer) so
``bench.py`` and the serving/runtime code can import it; this file
keeps the old entry point and import path working::

    python benchmarks/trace_summary.py /tmp/profile [--top 30]

New code should use ``python -m tensorflowonspark_tpu.tools.trace_report``.
"""

try:
    from tensorflowonspark_tpu.obs.trace_report import (  # noqa: F401
        attribution,
        build_report,
        classify_op,
        find_trace_files,
        load_events,
        main,
        self_times,
        write_report,
    )
except ImportError:
    # Direct script/benchmarks-dir use where the repo root is not yet
    # importable; only THEN mutate sys.path (an unconditional insert
    # would reorder resolution for every process that merely imports
    # this shim).
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), ".."))
    )
    from tensorflowonspark_tpu.obs.trace_report import (  # noqa: E402,F401
        attribution,
        build_report,
        classify_op,
        find_trace_files,
        load_events,
        main,
        self_times,
        write_report,
    )

if __name__ == "__main__":
    raise SystemExit(main())
