"""Measure the SPARK-mode push-feed plane's throughput ceiling (CPU).

VERDICT round-2 weak #3: ALL partition data in InputMode.SPARK flows from
the single driver process to the node managers (shm ring when co-located,
TCP otherwise) — the reference's feed tasks ran *on the executors* with
HDFS locality, so its driver shipped closures, not bytes. This bench
quantifies that design's ceiling so DESIGN.md can state when to switch to
pull mode (InputMode.TENSORFLOW + grain/tf.data sharding).

What it measures, per (node count, path, wire): wall time from the
start of ``cluster.train(close_feed=True)`` until ``shutdown()``
returns — i.e. until every node has DRAINED its feed into
``{tensor: ndarray}`` batches through an ``input_mapping`` (the shape a
train step consumes), not merely until the driver buffered it into
rings — for a fixed payload of DISTINCT uint8-array records. (Distinct
matters: identical record objects would let pickle's memoizer collapse
a whole chunk to a few bytes and the row leg would measure nothing.)

Paths:
- ``shm``: the co-located fast path (``native/shmring.cc``).
- ``tcp``: the manager-proxy path every remote node uses (forced by
  disabling the driver-side ring lookup; the node-side ring still
  exists but no producer attaches).
- ``manifest``: node-side feeders (``feed/manifest.py``) — the driver
  ships one FileManifest per node and each node streams its file
  locally; driver traffic is O(files), so this path's number is the
  node-local read rate, not a driver ceiling.

Wires (ISSUE 5): ``columnar`` ships each chunk as one CRC-framed
column frame (``feed/columnar.py``; scatter-pushed zero-copy on shm,
one bytes payload on tcp, 64-aligned frame files on manifest);
``row`` pins the legacy row-pickle wire (``columnar=False`` /
lines-format manifests) — the before/after pair the results artifact
records.

Usage::

    python benchmarks/feed_plane.py [--nodes 1,2,4,8] [--mb-per-node 64]
        [--record-kb 64] [--paths shm,tcp] [--wire columnar,row]
        [--json out.jsonl]

Prints one JSON line per configuration.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def drain_fn(args, ctx):
    """Consume the feed into mapped column batches as fast as possible;
    count records. The mapping is the point: the row wire pays
    ``columnize_rows`` (np.stack) per batch here, the columnar wire
    slices zero-copy views. (The lines-format manifest leg drains raw
    rows — text lines have no column mapping.)"""
    batch = int(args["batch"])
    n = 0
    if args.get("manifest"):
        from tensorflowonspark_tpu.feed.manifest import ManifestFeed

        feed = ManifestFeed(ctx.get_data_feed())
        if args.get("columnar"):
            for cols in feed.batch_stream(batch, 1, input_mapping={"x": "x"}):
                n += len(cols["x"])
        else:
            while not feed.should_stop():
                n += len(feed.next_batch(batch))
    else:
        feed = ctx.get_data_feed(input_mapping={"x": "x"})
        while not feed.should_stop():
            cols = feed.next_batch(batch)
            if cols:
                n += len(cols["x"])
    print(f"node {ctx.worker_num}: drained {n} records", flush=True)


def _run_config(n_nodes: int, path: str, mb_per_node: int, record_kb: int,
                batch: int, wire: str = "columnar") -> dict:
    from tensorflowonspark_tpu.cluster import node as tfnode_runtime
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    import tempfile

    import numpy as np

    columnar = wire == "columnar"
    record_len = record_kb * 1024
    per_node = (mb_per_node * 1024 * 1024) // record_len
    tmpdir = None
    if path == "manifest":
        # Node-side feeders: the driver ships ONE FileManifest per node;
        # each node streams its file locally (feed/manifest.py). File
        # creation is setup, not part of the timed window. The columnar
        # wire reads 64-aligned frame files zero-copy over one mmap; the
        # row wire streams text lines.
        from tensorflowonspark_tpu.feed.manifest import FileManifest

        tmpdir = tempfile.TemporaryDirectory(prefix="feed_plane_")
        partitions = []
        for i in range(n_nodes):
            if columnar:
                from tensorflowonspark_tpu.feed.columnar import write_frames

                fp = f"{tmpdir.name}/node{i}.colf"
                arr = np.full((per_node, record_len), 120, np.uint8)
                write_frames(
                    fp,
                    ((row,) for row in arr),
                    records_per_frame=512,
                )
                partitions.append([FileManifest(fp, format="columnar")])
            else:
                fp = f"{tmpdir.name}/node{i}.txt"
                line = "x" * (record_len - 1)
                with open(fp, "w") as f:
                    for _ in range(per_node):
                        f.write(line + "\n")
                partitions.append([FileManifest(fp, format="lines")])
    else:
        # DISTINCT per-record arrays (views over one allocation): pickle
        # must move every byte, as it would for real data
        partitions = [
            [
                (row,)
                for row in np.full((per_node, record_len), 120, np.uint8)
            ]
            for _ in range(n_nodes)
        ]
    total_mb = n_nodes * per_node * record_len / 1e6

    real_node_ring = tfnode_runtime._node_ring
    if path == "tcp":
        # Driver-side only: pretend no ring is advertised, forcing every
        # chunk through the TCP manager proxy (what any remote node gets).
        tfnode_runtime._node_ring = lambda node: None
    try:
        cluster = tfcluster.run(
            drain_fn,
            {
                "batch": batch,
                "manifest": path == "manifest",
                "columnar": columnar,
            },
            num_executors=n_nodes,
            input_mode=InputMode.SPARK,
            reservation_timeout=120,
            env=cpu_only_env(),
            columnar=columnar,
        )
        t0 = time.perf_counter()
        cluster.train(partitions, close_feed=True)
        cluster.shutdown(timeout=600)
        secs = time.perf_counter() - t0
    finally:
        tfnode_runtime._node_ring = real_node_ring
        if tmpdir is not None:
            tmpdir.cleanup()
    return {
        "bench": "feed_plane",
        "nodes": n_nodes,
        "path": path,
        "wire": wire,
        "record_kb": record_kb,
        "mb_total": round(total_mb, 1),
        "secs": round(secs, 3),
        "mb_per_s": round(total_mb / secs, 1),
        "mb_per_s_per_node": round(total_mb / secs / n_nodes, 1),
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--nodes", default="1,2,4,8")
    p.add_argument("--mb-per-node", type=int, default=64)
    p.add_argument("--record-kb", type=int, default=64)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--paths", default="shm,tcp")
    p.add_argument("--wire", default="columnar,row",
                   help="comma list of wire formats: columnar,row")
    p.add_argument("--json", default=None, help="also append JSONL here")
    args = p.parse_args(argv)

    out = open(args.json, "a") if args.json else None
    try:
        for n in [int(x) for x in args.nodes.split(",") if x.strip()]:
            for path in [x.strip() for x in args.paths.split(",") if x.strip()]:
                for wire in [w.strip() for w in args.wire.split(",") if w.strip()]:
                    row = _run_config(
                        n, path, args.mb_per_node, args.record_kb,
                        args.batch, wire,
                    )
                    line = json.dumps(row)
                    print(line, flush=True)
                    if out:
                        out.write(line + "\n")
    finally:
        if out:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
