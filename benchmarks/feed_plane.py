"""Measure the SPARK-mode push-feed plane's throughput ceiling (CPU).

VERDICT round-2 weak #3: ALL partition data in InputMode.SPARK flows from
the single driver process to the node managers (shm ring when co-located,
TCP otherwise) — the reference's feed tasks ran *on the executors* with
HDFS locality, so its driver shipped closures, not bytes. This bench
quantifies that design's ceiling so DESIGN.md can state when to switch to
pull mode (InputMode.TENSORFLOW + grain/tf.data sharding).

What it measures, per (node count, path): wall time from the start of
``cluster.train(close_feed=True)`` until ``shutdown()`` returns — i.e.
until every node has DRAINED its feed, not merely until the driver
buffered it into rings — for a fixed payload of pickled byte records.

Paths:
- ``shm``: the co-located fast path (``native/shmring.cc``).
- ``tcp``: the manager-proxy path every remote node uses (forced by
  disabling the driver-side ring lookup; the node-side ring still
  exists but no producer attaches).
- ``manifest``: node-side feeders (``feed/manifest.py``) — the driver
  ships one FileManifest per node and each node streams its file
  locally; driver traffic is O(files), so this path's number is the
  node-local read rate, not a driver ceiling.

Usage::

    python benchmarks/feed_plane.py [--nodes 1,2,4,8] [--mb-per-node 64]
        [--record-kb 64] [--paths shm,tcp] [--json out.jsonl]

Prints one JSON line per configuration.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def drain_fn(args, ctx):
    """Consume the feed as fast as possible; count records."""
    feed = ctx.get_data_feed()
    if args.get("manifest"):
        from tensorflowonspark_tpu.feed.manifest import ManifestFeed

        feed = ManifestFeed(feed)
    n = 0
    while not feed.should_stop():
        rows = feed.next_batch(int(args["batch"]))
        n += len(rows)
    print(f"node {ctx.worker_num}: drained {n} records", flush=True)


def _run_config(n_nodes: int, path: str, mb_per_node: int, record_kb: int,
                batch: int) -> dict:
    from tensorflowonspark_tpu.cluster import node as tfnode_runtime
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    import tempfile

    record = b"x" * (record_kb * 1024)
    per_node = (mb_per_node * 1024 * 1024) // len(record)
    tmpdir = None
    if path == "manifest":
        # Node-side feeders: the driver ships ONE FileManifest per node;
        # each node streams its file locally (feed/manifest.py). File
        # creation is setup, not part of the timed window.
        from tensorflowonspark_tpu.feed.manifest import FileManifest

        tmpdir = tempfile.TemporaryDirectory(prefix="feed_plane_")
        line = "x" * (record_kb * 1024 - 1)
        partitions = []
        for i in range(n_nodes):
            fp = f"{tmpdir.name}/node{i}.txt"
            with open(fp, "w") as f:
                for _ in range(per_node):
                    f.write(line + "\n")
            partitions.append([FileManifest(fp, format="lines")])
    else:
        partitions = [[record] * per_node for _ in range(n_nodes)]
    total_mb = n_nodes * per_node * len(record) / 1e6

    real_node_ring = tfnode_runtime._node_ring
    if path == "tcp":
        # Driver-side only: pretend no ring is advertised, forcing every
        # chunk through the TCP manager proxy (what any remote node gets).
        tfnode_runtime._node_ring = lambda node: None
    try:
        cluster = tfcluster.run(
            drain_fn,
            {"batch": batch, "manifest": path == "manifest"},
            num_executors=n_nodes,
            input_mode=InputMode.SPARK,
            reservation_timeout=120,
            env=cpu_only_env(),
        )
        t0 = time.perf_counter()
        cluster.train(partitions, close_feed=True)
        cluster.shutdown(timeout=600)
        secs = time.perf_counter() - t0
    finally:
        tfnode_runtime._node_ring = real_node_ring
        if tmpdir is not None:
            tmpdir.cleanup()
    return {
        "bench": "feed_plane",
        "nodes": n_nodes,
        "path": path,
        "record_kb": record_kb,
        "mb_total": round(total_mb, 1),
        "secs": round(secs, 3),
        "mb_per_s": round(total_mb / secs, 1),
        "mb_per_s_per_node": round(total_mb / secs / n_nodes, 1),
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--nodes", default="1,2,4,8")
    p.add_argument("--mb-per-node", type=int, default=64)
    p.add_argument("--record-kb", type=int, default=64)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--paths", default="shm,tcp")
    p.add_argument("--json", default=None, help="also append JSONL here")
    args = p.parse_args(argv)

    out = open(args.json, "a") if args.json else None
    try:
        for n in [int(x) for x in args.nodes.split(",") if x.strip()]:
            for path in [x.strip() for x in args.paths.split(",") if x.strip()]:
                row = _run_config(
                    n, path, args.mb_per_node, args.record_kb, args.batch
                )
                line = json.dumps(row)
                print(line, flush=True)
                if out:
                    out.write(line + "\n")
    finally:
        if out:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
