"""Measure the SPARK-mode push-feed plane's throughput ceiling (CPU).

VERDICT round-2 weak #3: ALL partition data in InputMode.SPARK flows from
the single driver process to the node managers (shm ring when co-located,
TCP otherwise) — the reference's feed tasks ran *on the executors* with
HDFS locality, so its driver shipped closures, not bytes. This bench
quantifies that design's ceiling so DESIGN.md can state when to switch to
pull mode (InputMode.TENSORFLOW + grain/tf.data sharding).

What it measures, per (node count, path, wire): wall time from the
start of ``cluster.train(close_feed=True)`` until ``shutdown()``
returns — i.e. until every node has DRAINED its feed into
``{tensor: ndarray}`` batches through an ``input_mapping`` (the shape a
train step consumes), not merely until the driver buffered it into
rings — for a fixed payload of DISTINCT uint8-array records. (Distinct
matters: identical record objects would let pickle's memoizer collapse
a whole chunk to a few bytes and the row leg would measure nothing.)

Paths:
- ``shm``: the co-located fast path (``native/shmring.cc``).
- ``tcp``: the manager-proxy path every remote node uses (forced by
  disabling the driver-side ring lookup; the node-side ring still
  exists but no producer attaches).
- ``manifest``: node-side feeders (``feed/manifest.py``) — the driver
  ships one FileManifest per node and each node streams its file
  locally; driver traffic is O(files), so this path's number is the
  node-local read rate, not a driver ceiling.
- ``pull``: the driverless pull plane (ISSUE 8; ``feed/ingest.py``) —
  ``InputMode.TENSORFLOW``, the driver publishes only the shard plan
  (``assign_shards``) and every node's executor-local reader drains
  its columnar shard with NO driver process in the data loop. Each
  node self-times its drain (first batch → last batch) and reports
  per-node MB/s beside the wall-clock aggregate.

Wires (ISSUE 5): ``columnar`` ships each chunk as one CRC-framed
column frame (``feed/columnar.py``; scatter-pushed zero-copy on shm,
one bytes payload on tcp, 64-aligned frame files on manifest);
``row`` pins the legacy row-pickle wire (``columnar=False`` /
lines-format manifests) — the before/after pair the results artifact
records. The pull leg is columnar-only (the frame files ARE its wire).

Scaling sweep (ISSUE 8): ``--nodes 1,2,4,8 --paths shm,pull`` produces
the push-columnar vs pull-sharded legs per node count. Because every
bench node is co-located on ONE host, wall-clock aggregate is bounded
by host cores for BOTH legs once nodes exceed them; ``--pull-mode
staggered`` additionally serializes the pull drains (a driver-side
turn token: one node's shard plan is published only after the previous
node reported its stats), measuring each node's UNCONTENDED rate at
every cluster size — the number that transfers to one-node-per-host
deployments, since pull nodes share no driver-side component (the push
legs have no analogous projection: their shared component IS the
driver). Both modes land in the artifact.

Usage::

    python benchmarks/feed_plane.py [--nodes 1,2,4,8] [--mb-per-node 64]
        [--record-kb 64] [--paths shm,tcp,pull] [--wire columnar,row]
        [--pull-mode coscheduled,staggered] [--json out.jsonl]

Prints one JSON line per configuration.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def drain_fn(args, ctx):
    """Consume the feed into mapped column batches as fast as possible;
    count records. The mapping is the point: the row wire pays
    ``columnize_rows`` (np.stack) per batch here, the columnar wire
    slices zero-copy views. (The lines-format manifest leg drains raw
    rows — text lines have no column mapping.)"""
    batch = int(args["batch"])
    n = 0
    if args.get("manifest"):
        from tensorflowonspark_tpu.feed.manifest import ManifestFeed

        feed = ManifestFeed(ctx.get_data_feed())
        if args.get("columnar"):
            for cols in feed.batch_stream(batch, 1, input_mapping={"x": "x"}):
                n += len(cols["x"])
        else:
            while not feed.should_stop():
                n += len(feed.next_batch(batch))
    else:
        feed = ctx.get_data_feed(input_mapping={"x": "x"})
        while not feed.should_stop():
            cols = feed.next_batch(batch)
            if cols:
                n += len(cols["x"])
    print(f"node {ctx.worker_num}: drained {n} records", flush=True)


def pull_drain_fn(args, ctx):
    """Pull-plane map_fun: drain this node's shard through
    ``ctx.get_ingest_feed`` (executor-local columnar reader, mapped
    column batches — the same consuming shape as ``drain_fn``),
    self-timing first→last batch, and report stats via the manager KV
    so the driver can collect per-node rates."""
    import time as _time

    feed = ctx.get_ingest_feed(
        input_mapping={"x": "x"}, timeout=float(args.get("timeout", 600))
    )
    batch = int(args["batch"])
    n = 0
    nbytes = 0
    t0 = None
    for cols in feed.batch_stream(batch):
        if t0 is None:
            t0 = _time.perf_counter()
        n += len(cols["x"])
        nbytes += cols["x"].nbytes
    secs = 0.0 if t0 is None else _time.perf_counter() - t0
    ctx.mgr.set(
        "ingest_stats", {"records": n, "bytes": nbytes, "secs": secs}
    )
    print(f"node {ctx.worker_num}: drained {n} records", flush=True)


def _collect_ingest_stats(worker, timeout: float = 600.0) -> dict:
    from tensorflowonspark_tpu.cluster import node as tfnode_runtime

    deadline = time.perf_counter() + timeout
    mgr = tfnode_runtime.connect_manager(worker)
    while time.perf_counter() < deadline:
        stats = mgr.get("ingest_stats")
        if stats is not None:
            return stats
        time.sleep(0.1)
    raise TimeoutError(
        f"node {worker['executor_id']} never reported ingest stats"
    )


def _run_pull_config(
    n_nodes: int,
    mb_per_node: int,
    record_kb: int,
    batch: int,
    staggered: bool = False,
) -> dict:
    from tensorflowonspark_tpu.cluster import node as tfnode_runtime
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.feed.columnar import write_frames
    from tensorflowonspark_tpu.feed.manifest import FileManifest
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    import tempfile

    import numpy as np

    record_len = record_kb * 1024
    per_node = (mb_per_node * 1024 * 1024) // record_len
    # records per frame sized so one frame is ~4 MB: big enough to
    # amortize header decode, small enough that batch slicing stays
    # fine-grained
    rpf = max(1, (4 << 20) // record_len)
    tmpdir = tempfile.TemporaryDirectory(prefix="feed_plane_pull_")
    manifests = []
    for i in range(n_nodes):
        fp = f"{tmpdir.name}/node{i}.colf"
        arr = np.full((per_node, record_len), 120, np.uint8)
        write_frames(fp, ((row,) for row in arr), records_per_frame=rpf)
        manifests.append(FileManifest(fp, format="columnar"))
    total_mb = n_nodes * per_node * record_len / 1e6
    cluster = None
    try:
        cluster = tfcluster.run(
            pull_drain_fn,
            # staggered mode publishes node i's plan only after i-1
            # finished draining, so a later node's plan-fetch wait must
            # outlast ALL earlier drains — scale the timeout with the
            # cluster size instead of trusting the 600s default
            {"batch": batch, "timeout": 600.0 * max(1, n_nodes)},
            num_executors=n_nodes,
            input_mode=InputMode.TENSORFLOW,
            reservation_timeout=120,
            env=cpu_only_env(),
        )
        workers = cluster.workers
        t0 = time.perf_counter()
        per_node_stats = []
        if staggered:
            # turn token: node i's plan is published only after node
            # i-1 reported — each drain runs uncontended on this host
            for i, w in enumerate(workers):
                tfnode_runtime.publish_ingest_plan(
                    tfnode_runtime.connect_manager(w),
                    [manifests[i]],
                    shard_index=i,
                    num_shards=n_nodes,
                )
                per_node_stats.append(_collect_ingest_stats(w))
        else:
            cluster.assign_shards(manifests)
            per_node_stats = [_collect_ingest_stats(w) for w in workers]
        secs = time.perf_counter() - t0
        cluster.shutdown(timeout=600)
    finally:
        # teardown BEFORE deleting the frame files: live readers still
        # mmap them, and yanking the files would bury the real error
        # under FileNotFoundError noise from every surviving node
        if cluster is not None and not cluster._shutdown_done:
            try:
                cluster.launcher.terminate()
                cluster.server.stop()
            except Exception:
                pass
        tmpdir.cleanup()
    rates = [
        s["bytes"] / s["secs"] / 1e6 for s in per_node_stats if s["secs"] > 0
    ]
    # staggered aggregate = sum of uncontended per-node rates (pull
    # nodes share nothing driver-side); co-scheduled aggregate = real
    # wall clock on this host
    aggregate = sum(rates) if staggered else total_mb / secs
    return {
        "bench": "feed_plane",
        "leg": "pull-sharded",
        "nodes": n_nodes,
        "path": "pull",
        "wire": "columnar",
        "mode": "staggered" if staggered else "coscheduled",
        "record_kb": record_kb,
        "mb_total": round(total_mb, 1),
        "secs": round(secs, 3),
        "mb_per_s": round(aggregate, 1),
        "mb_per_s_per_node": round(
            (sum(rates) / len(rates)) if rates else 0.0, 1
        ),
        "per_node_mb_per_s": [round(r, 1) for r in rates],
    }


def _run_config(n_nodes: int, path: str, mb_per_node: int, record_kb: int,
                batch: int, wire: str = "columnar") -> dict:
    from tensorflowonspark_tpu.cluster import node as tfnode_runtime
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    import tempfile

    import numpy as np

    columnar = wire == "columnar"
    record_len = record_kb * 1024
    per_node = (mb_per_node * 1024 * 1024) // record_len
    tmpdir = None
    if path == "manifest":
        # Node-side feeders: the driver ships ONE FileManifest per node;
        # each node streams its file locally (feed/manifest.py). File
        # creation is setup, not part of the timed window. The columnar
        # wire reads 64-aligned frame files zero-copy over one mmap; the
        # row wire streams text lines.
        from tensorflowonspark_tpu.feed.manifest import FileManifest

        tmpdir = tempfile.TemporaryDirectory(prefix="feed_plane_")
        partitions = []
        for i in range(n_nodes):
            if columnar:
                from tensorflowonspark_tpu.feed.columnar import write_frames

                fp = f"{tmpdir.name}/node{i}.colf"
                arr = np.full((per_node, record_len), 120, np.uint8)
                write_frames(
                    fp,
                    ((row,) for row in arr),
                    records_per_frame=512,
                )
                partitions.append([FileManifest(fp, format="columnar")])
            else:
                fp = f"{tmpdir.name}/node{i}.txt"
                line = "x" * (record_len - 1)
                with open(fp, "w") as f:
                    for _ in range(per_node):
                        f.write(line + "\n")
                partitions.append([FileManifest(fp, format="lines")])
    else:
        # DISTINCT per-record arrays (views over one allocation): pickle
        # must move every byte, as it would for real data
        partitions = [
            [
                (row,)
                for row in np.full((per_node, record_len), 120, np.uint8)
            ]
            for _ in range(n_nodes)
        ]
    total_mb = n_nodes * per_node * record_len / 1e6

    real_node_ring = tfnode_runtime._node_ring
    if path == "tcp":
        # Driver-side only: pretend no ring is advertised, forcing every
        # chunk through the TCP manager proxy (what any remote node gets).
        tfnode_runtime._node_ring = lambda node: None
    try:
        cluster = tfcluster.run(
            drain_fn,
            {
                "batch": batch,
                "manifest": path == "manifest",
                "columnar": columnar,
            },
            num_executors=n_nodes,
            input_mode=InputMode.SPARK,
            reservation_timeout=120,
            env=cpu_only_env(),
            columnar=columnar,
        )
        t0 = time.perf_counter()
        cluster.train(partitions, close_feed=True)
        cluster.shutdown(timeout=600)
        secs = time.perf_counter() - t0
    finally:
        tfnode_runtime._node_ring = real_node_ring
        if tmpdir is not None:
            tmpdir.cleanup()
    return {
        "bench": "feed_plane",
        "leg": f"push-{wire}",
        "nodes": n_nodes,
        "path": path,
        "wire": wire,
        "record_kb": record_kb,
        "mb_total": round(total_mb, 1),
        "secs": round(secs, 3),
        "mb_per_s": round(total_mb / secs, 1),
        "mb_per_s_per_node": round(total_mb / secs / n_nodes, 1),
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--nodes", default="1,2,4,8")
    p.add_argument("--mb-per-node", type=int, default=64)
    p.add_argument("--record-kb", type=int, default=64)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--paths", default="shm,tcp")
    p.add_argument("--wire", default="columnar,row",
                   help="comma list of wire formats: columnar,row")
    p.add_argument(
        "--pull-mode",
        default="coscheduled,staggered",
        help="comma list for the pull path: coscheduled (wall-clock "
        "aggregate; core-bounded on one host), staggered (serialized "
        "drains; uncontended per-node rates)",
    )
    p.add_argument("--json", default=None, help="also append JSONL here")
    args = p.parse_args(argv)

    out = open(args.json, "a") if args.json else None
    try:
        for n in [int(x) for x in args.nodes.split(",") if x.strip()]:
            for path in [x.strip() for x in args.paths.split(",") if x.strip()]:
                if path == "pull":
                    rows = [
                        _run_pull_config(
                            n, args.mb_per_node, args.record_kb,
                            args.batch, staggered=mode == "staggered",
                        )
                        for mode in [
                            m.strip()
                            for m in args.pull_mode.split(",")
                            if m.strip()
                        ]
                    ]
                else:
                    rows = [
                        _run_config(
                            n, path, args.mb_per_node, args.record_kb,
                            args.batch, wire,
                        )
                        for wire in [
                            w.strip()
                            for w in args.wire.split(",")
                            if w.strip()
                        ]
                    ]
                for row in rows:
                    line = json.dumps(row)
                    print(line, flush=True)
                    if out:
                        out.write(line + "\n")
    finally:
        if out:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
