#!/bin/bash
# Round-4 relay-window measurements, in priority order. Supersedes
# run_round3b.sh (all of its pending items are here) and adds the
# round-4 serving measurements.
#
# Discipline (BASELINE.md / verify skill): run ONLY when the relay is
# up, ONE dialer at a time, never SIGKILL a run mid-compile, idle host
# (no concurrent pytest — it pollutes step timings).
set -u
cd "$(dirname "$0")/.."
OUT=${OUT:-/tmp/round4_measurements.jsonl}

if ! ss -tln | grep -qE ':(808[2-9]|809[0-9]|810[0-9]|811[0-7]) '; then
  echo "TPU relay ports 8082-8117 not listening; aborting before any dial" >&2
  exit 1
fi
busy=""
for cmd in /proc/[0-9]*/cmdline; do
  busy=$(tr '\0' '\n' <"$cmd" 2>/dev/null | awk '
    NR==1 && $0 !~ /python[0-9.]*$/ { exit }
    NR>1 && /(^|\/)(real_chip|bench)\.py$/ { print "busy"; exit }')
  [ -n "$busy" ] && break
done
if [ -n "$busy" ]; then
  echo "another benchmark process is already running (one dialer at a time)" >&2
  exit 1
fi

run() {
  echo "=== $* ===" >&2
  timeout 900 "$@" | tee -a "$OUT"
  echo >&2
}

# 1. THE DRIVER ARTIFACT FIRST: a green bench.py headline has never
#    been captured by the driver (relay down at every end-of-round).
#    Running it here banks the measurement in this window's jsonl even
#    if the relay dies again before the driver's end-of-round run.
run python bench.py

# 2. ResNet-50 with FusedBatchNorm (16.1% with flax BN; the round-3
#    profile put 48% of the step in separate stats passes). Re-profile
#    so the next gap is also evidence-backed.
run python benchmarks/real_chip.py --config resnet50 \
  --profile "${PROFILE_DIR:-/tmp/resnet50_fusedbn_profile}"

# 3. Inception-v3 with FusedBatchNorm (was 18.2% with flax BN)
run python benchmarks/real_chip.py --config inception_v3

# 4. seq-4096 A/B on an idle host: unchunked vs chunked CE, same
#    bf16-moment optimizer (first-window chunked number was 37.8% but
#    host-polluted; round-1 unchunked was 40.0% with a different
#    optimizer)
run python benchmarks/real_chip.py --config llama1b --seq 4096 --moments bf16
run python benchmarks/real_chip.py --config llama1b --seq 4096 \
  --logit-chunk 512 --moments bf16

# 5. Profile the headline config: where do the non-MXU 43% of the
#    llama1b step go? (step 417 ms vs ~238 ms compute floor at 57% MFU)
run python benchmarks/real_chip.py --config llama1b --moments bf16 \
  --profile "${PROFILE_DIR_LLAMA:-/tmp/llama1b_profile}"

# 6. Continuous-batching engine at full occupancy vs plain batch decode
#    (same-batch delta = token-granular scheduling tax)
run python benchmarks/real_chip.py --config llama1b_engine --steps 3
run python benchmarks/real_chip.py --config llama1b_engine --steps 3 --quantize

# 7. NEW round 4: prefix-caching TTFT — warm (resume at shared_len=448
#    of 512) vs cold full prefill
run python benchmarks/real_chip.py --config llama1b_prefix --steps 16

# 8. NEW round 4: int8 KV cache at long context — the per-step cache
#    read rivals the weight read at prompt 2048, which is what
#    kv_cache_dtype="int8" halves. A/B at the same shape, then composed
#    with int8 weights (both halvings together).
run python benchmarks/real_chip.py --config llama1b_decode --seq 2048 --new-tokens 64
run python benchmarks/real_chip.py --config llama1b_decode --seq 2048 --new-tokens 64 --kv-quantize
run python benchmarks/real_chip.py --config llama1b_decode --seq 2048 --new-tokens 64 --kv-quantize --quantize

# 9. NEW round 4: sliding-window training at long seq — the flash
#    kernel's window-restricted grids should make the windowed step
#    approach (W/S)x the full-attention attention cost
run python benchmarks/real_chip.py --config llama1b --seq 4096 --moments bf16 --window 1024

echo "round-4 measurements attempted; results in $OUT" >&2
