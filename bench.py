"""Benchmark entry: prints ONE JSON line with the headline metric.

Headline: **training MFU of a 1B-param Llama decoder** on the local
chip(s) — the metric BASELINE.md's north star is written in ("MNIST and
a Llama fine-tune complete from the launcher at >=40% MFU"), and the one
that is hardware-bound rather than tunnel-bound in this environment.
``vs_baseline`` is measured MFU / the 40% target. The model/mesh/timing
code is shared with ``benchmarks/real_chip.py`` (one implementation, one
set of barrier workarounds).

Secondary fields in the same line: MNIST CNN examples/sec end-to-end
through the framework's own data plane (producer -> manager queue ->
DataFeed -> DevicePrefetcher -> jit step), i.e. the BASELINE.md "MNIST
InputMode.SPARK" config. That number is bounded by host->device
transfer (~35 MB/s through this environment's TPU tunnel), so it is
reported but not the headline.

Synchronization note: on the tunneled TPU backend, block_until_ready
returns before execution finishes; all timing barriers here are host
fetches of a scalar.

A watchdog prints whatever has been measured so far (plus an error
marker) and exits if the run wedges — this environment's TPU relay is
fragile, and a partial line beats silence.

``--trace`` (DEFAULT ON for real-chip runs): after the timed llama
loop, a few extra steps run under ``jax.profiler.trace`` and the trace
is distilled into ``benchmarks/results/*_trace_report.json`` via
``tensorflowonspark_tpu.obs.trace_report`` — per-lane self-time plus
the MXU/vector/copy/infeed/host attribution table — so every scored
run commits the evidence for its own MFU number instead of leaving the
trace unread in /tmp (the round-5 failure mode). On CPU backends this
degrades to a no-op warning (no MXU to attribute; set
``BENCH_TRACE_CPU=1`` to force a host-lane capture anyway).
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

WATCHDOG_SECS = 510  # fire before any outer ~600s kill, so a JSON line
# still reaches the driver when backend init or a compile wedges
MFU_TARGET = 0.40  # BASELINE.md acceptance threshold

# The tunneled TPU backend in this environment dials a loopback relay on
# these ports; when the relay is down, jax backend init blocks forever in
# epoll. Two-stage gate: (1) a purely passive /proc/net/tcp LISTEN scan
# (milliseconds) catches a DOWN relay; (2) since a WEDGED session keeps
# its ports listening while every dial hangs, a single short-lived
# subprocess dial (_relay_dial_probe) then distinguishes healthy from
# wedged. The relay tolerates exactly ONE dialer at a time — the probe
# is safe because it runs sequentially and exits before the main process
# dials (the same one-after-another pattern the relay-window scripts
# use); CONCURRENT dials are what wedge a session.
RELAY_PORTS = range(8082, 8118)
RELAY_MARKER = "/root/.relay.py"  # present only in the tunneled-TPU image

# Where to send the driver when this run can't measure: the banked
# relay-window captures and the script that re-runs everything pending.
BANKED_POINTER = (
    "Driver-format capture from the round-4 window: 57.5% MFU "
    "(benchmarks/results/round4_window1.jsonl; round-3 window concurred "
    "at 57.0%). benchmarks/run_round4_resume.sh batches every "
    "still-pending measurement for the next healthy window."
)


def _relay_ports_listening() -> int:
    wanted = set(RELAY_PORTS)
    found: set[int] = set()
    for path in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(path) as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        for line in lines:
            parts = line.split()
            if len(parts) > 3 and parts[3] == "0A":  # TCP_LISTEN
                try:
                    addr, port_hex = parts[1].rsplit(":", 1)
                    port = int(port_hex, 16)
                except (ValueError, IndexError):
                    continue
                # Count loopback and wildcard listeners. The relay binds
                # loopback, but wildcard stays accepted: a false negative
                # (refusing a healthy relay that rebinds 0.0.0.0) costs
                # the whole bench run, while a false positive (unrelated
                # wildcard service on these ports) merely reverts to the
                # watchdog path. IPv4 loopback is 0100007F (little-endian
                # per 32-bit group).
                is_local = (
                    addr == "0100007F"  # 127.0.0.1
                    or set(addr) == {"0"}  # 0.0.0.0 / ::
                    or addr == "0" * 24 + "01000000"  # ::1
                    or addr.endswith("0100007F")  # ::ffff:127.0.0.1
                )
                if port in wanted and is_local:
                    found.add(port)
    return len(found)

_result_printed = threading.Event()
_partial: dict = {}  # results land here as they finish, for the watchdog


def _emit(fields: dict) -> None:
    print(json.dumps(fields), flush=True)
    _result_printed.set()


def _results_dir() -> str:
    """Destination for evidence artifacts. The committed baselines in
    benchmarks/results/ are scored on a quiet single-chip host; any run
    that is NOT a deliberate regeneration (the pytest e2e smoke tests
    in particular) must set TFOS_BENCH_RESULTS_DIR to a scratch dir so
    a contended-host run can never overwrite the committed evidence."""
    return os.environ.get("TFOS_BENCH_RESULTS_DIR") or os.path.join(
        "benchmarks", "results"
    )


def _watchdog():
    if not _result_printed.wait(WATCHDOG_SECS):
        _emit(
            {
                "metric": "llama1b_train_mfu",
                "value": _partial.get("mfu_pct", 0),
                "unit": "%",
                "vs_baseline": round(
                    _partial.get("mfu_pct", 0) / (MFU_TARGET * 100), 3
                ),
                "error": f"watchdog: incomplete after {WATCHDOG_SECS}s "
                "(backend init or compile wedged? a relay whose ports "
                "listen but whose remote orchestrator is down wedges "
                "the first backend touch). " + BANKED_POINTER,
                **{k: v for k, v in _partial.items() if k != "mfu_pct"},
            }
        )
        os._exit(2)


def _bench_llama(steps: int = 10, smoke: bool = False) -> None:
    """1B Llama train step (shared impl: benchmarks/real_chip.py)."""
    import jax

    from benchmarks import real_chip

    # remat off: the 1B state+activations fit a single chip's HBM, and
    # skipping the recompute is worth ~5 MFU points. bf16 Adam moments:
    # frees 3.8 GB of HBM, which un-spills XLA's schedule on this 16 GB
    # chip (measured 49.8% -> 57.3% MFU; see compute/optim.py).
    ns = argparse.Namespace(
        steps=2 if smoke else steps,
        # the batch must shard over the fsdp mesh axis: 8 works for the
        # device counts this runs on (1 real chip; 1/2/4/8 virtual CPU
        # devices in CI) — a forced mesh wider than 8 would need more
        batch_size=8,
        seq=64 if smoke else 1024,
        attention="auto", remat="none",
        precision="fp32", moments="bf16",
        # BENCH_SMOKE: tiny decoder so the FULL flow (sharded step,
        # timing barriers, JSON assembly) runs on CPU in seconds —
        # exercised by tests/test_bench_smoke.py so the one
        # driver-scored artifact has CI coverage beyond the relay gate
        model_scale="tiny" if smoke else "1b",
    )
    if smoke:
        _partial["smoke"] = True
    res = real_chip.bench_llama1b(ns)
    n_chips = len(jax.devices())
    step_time = res["dt"] / ns.steps
    tflops_per_chip = res["flops_fallback"] / step_time / n_chips / 1e12
    peak = (
        real_chip.V5E_PEAK_TFLOPS
        if jax.default_backend() == "tpu"
        else None
    )
    _partial.update(
        step_time_ms=round(step_time * 1e3, 1),
        tokens_per_sec_per_chip=round(res["tokens"] / step_time / n_chips),
        n_params=res["n_params"],
        final_loss=round(res["loss"], 4),
        model_tflops_per_sec_per_chip=round(tflops_per_chip, 1),
    )
    if peak is not None and not smoke:
        # never under the headline metric name: a tiny smoke model's
        # near-zero MFU must not look like a scored llama1b result
        _partial["mfu_pct"] = tflops_per_chip / peak * 100


def _bench_zero_ab(smoke: bool, legs: list) -> None:
    """``--zero``: the cross-replica sharded weight update A/B.

    Runs the llama train bench at a FIXED batch on a pure
    data-parallel mesh (``mesh_axis='data'`` — replicated params, the
    regime where the pre-ZeRO optimizer update is computed redundantly
    on every replica) once per requested ``zero_sharding`` setting, and
    commits one artifact with step time, MFU (TPU only), and the
    isolated optimizer-span ms per leg. A smoke run additionally runs
    the byte-identity gate (``tests/test_bench_smoke.py``): the
    weight-update decomposition on identical gradients must be
    byte-exact (``update_params_match`` — elementwise math, only
    placement changes), while the full train legs' digests are reported
    beside it (they may differ by gradient-reduction summation order,
    ~1 ulp). Artifact:
    ``benchmarks/results/zero_weight_update.json`` (``_<backend>_smoke``
    suffixed for smoke runs so CI can never clobber chip evidence).
    """
    import jax

    from benchmarks import real_chip

    results: dict = {}
    for leg in legs:
        ns = argparse.Namespace(
            steps=4 if smoke else 10,
            batch_size=8,
            seq=64 if smoke else 1024,
            attention="auto",
            remat="none",
            precision="fp32",
            moments="bf16",
            model_scale="tiny" if smoke else "1b",
            mesh_axis="data",
            zero_sharding=(leg == "on"),
            measure_update=True,
            # digesting 1B fp32 params off-device is smoke-only; the
            # real-chip A/B trusts the CI byte-identity gate
            params_digest=smoke,
        )
        res = real_chip.bench_llama1b(ns)
        n_chips = len(jax.devices())
        step_time = res["dt"] / ns.steps
        tflops = res["flops_fallback"] / step_time / n_chips / 1e12
        entry = {
            "step_time_ms": round(step_time * 1e3, 1),
            "weight_update_ms": res["weight_update_ms"],
            "final_loss": round(res["loss"], 4),
        }
        if jax.default_backend() == "tpu" and not smoke:
            entry["mfu_pct"] = round(
                tflops / real_chip.V5E_PEAK_TFLOPS * 100, 1
            )
        if "params_digest" in res:
            entry["params_digest"] = res["params_digest"]
        results[f"zero_{leg}"] = entry

    if smoke:
        _partial["smoke"] = True
        # The byte-identity gate: the weight-update DECOMPOSITION must
        # be byte-exact on identical gradients (elementwise math, only
        # placement changes). The full train legs' digests may differ
        # by gradient-reduction summation order (reduce-scatter vs
        # all-reduce grouping) — reported, not gated.
        ab = real_chip.update_ab_digests(
            argparse.Namespace(seq=16, model_scale="tiny", mesh_axis="data")
        )
        _partial["update_params_match"] = ab["on"] == ab["off"]
    out = {
        "metric": "zero_weight_update",
        # the headline: replicated-optimizer span ÷ ZeRO-sharded span
        # (>1 = the cross-replica partition pays)
        "value": round(
            results.get("zero_off", {}).get("weight_update_ms", 0)
            / max(
                results.get("zero_on", {}).get("weight_update_ms", 1e-9),
                1e-9,
            ),
            3,
        )
        if {"zero_on", "zero_off"} <= set(results)
        else 0,
        "unit": "x",
        "vs_baseline": round(
            results.get("zero_off", {}).get("step_time_ms", 0)
            / max(results.get("zero_on", {}).get("step_time_ms", 1e-9), 1e-9),
            3,
        )
        if {"zero_on", "zero_off"} <= set(results)
        else 0,
        "backend": jax.default_backend(),
        "chips": len(jax.devices()),
        "batch": 8,
        "seq": 64 if smoke else 1024,
        **results,
        **_partial,
    }
    if {"zero_on", "zero_off"} <= set(results) and smoke:
        out["train_params_match"] = (
            results["zero_on"]["params_digest"]
            == results["zero_off"]["params_digest"]
        )
    if {"zero_on", "zero_off"} <= set(results):
        path = os.path.join(
            _results_dir(),
            "zero_weight_update"
            + (f"_{jax.default_backend()}_smoke" if smoke else "")
            + ".json",
        )
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(out, f, indent=2, sort_keys=True)
                f.write("\n")
            out["artifact"] = path
        except OSError as e:
            out["artifact_error"] = str(e)
    else:
        # a single-leg quick look must never clobber the committed
        # two-leg A/B evidence the BASELINE row reads
        out["artifact_skipped"] = "partial legs; artifact needs on AND off"
    _emit(out)


def _bench_mnist_feed(steps: int = 40) -> None:
    """MNIST end-to-end through the data plane: columnar wire frames →
    sliced column batches → staged ``DevicePrefetcher.from_feed`` H2D —
    the default feed loop — with feed MB/s recorded beside MFU."""
    import secrets

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tensorflowonspark_tpu.cluster import manager as tf_manager
    from tensorflowonspark_tpu.cluster.marker import EndOfFeed
    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.feed import DataFeed, DevicePrefetcher
    from tensorflowonspark_tpu.feed import columnar as col
    from tensorflowonspark_tpu.models import mnist

    mesh = make_mesh({"data": len(jax.devices())})
    batch_size = 1024
    warmup = 3
    total = steps + warmup

    model = mnist.CNN()
    rng = np.random.default_rng(0)
    # uint8 records: what a real MNIST pipeline ships; normalize on device
    images = (rng.random((batch_size, 28, 28, 1)) * 255).astype(np.uint8)
    labels = rng.integers(0, 10, size=batch_size).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), images[:2].astype(np.float32)
    )["params"]
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    base_loss = mnist.loss_fn(model.apply)

    def loss(p, b):
        img = b["image"].astype(jnp.float32) / 255.0
        return base_loss(p, {"image": img, "label": b["label"]})

    step = build_train_step(loss, tx, mesh)

    mgr = tf_manager.start(secrets.token_bytes(8), mode="local", maxsize=64)

    # what one record costs on the wire: the uint8 image + int32 label
    record_bytes = images[0].nbytes + labels[:1].nbytes

    # Aggregator overhead leg: scrape this process's /metrics on the
    # production cadence WHILE training (the driver would, via
    # TFCluster.cluster_stats) and report scrape wall-time as a % of
    # train wall-time — the obs plane must cost < 1% of train.step.
    from tensorflowonspark_tpu.cluster import node as tf_node
    from tensorflowonspark_tpu.obs import cluster as obs_cluster

    agg = None
    metrics_port = tf_node._maybe_start_metrics_server("127.0.0.1")
    if metrics_port:
        agg = obs_cluster.MetricsAggregator(
            lambda: {0: f"http://127.0.0.1:{metrics_port}/metrics"},
            interval=2.0,
        )
        agg.start()

    def produce():
        # the production wire shape: each chunk columnized ONCE into a
        # CRC-framed ColumnarFrame (feed/columnar.py), no row pickles
        q = mgr.get_queue("input")
        chunk = col.columnize_records(list(zip(images, labels)))
        for seq in range(total):
            q.put(
                col.ColumnarFrame(
                    col.frame_bytes(chunk, stream="bench", seq=seq)
                )
            )
        q.put(EndOfFeed())

    threading.Thread(target=produce, daemon=True).start()
    feed = DataFeed(mgr, input_mapping={"image": "image", "label": "label"})

    n = 0
    t0 = None
    pf = DevicePrefetcher.from_feed(feed, batch_size, mesh, depth=2)
    with pf:
        for dev_batch in pf:
            state, loss_v = step(state, dev_batch)
            n += 1
            if n == warmup:
                float(loss_v)
                t0 = time.perf_counter()
    final = float(loss_v)
    dt = time.perf_counter() - t0
    mgr.stop()
    timed = n - warmup
    _partial.update(
        mnist_examples_per_sec=round(timed * batch_size / dt, 1),
        mnist_step_time_ms=round(dt / timed * 1e3, 2),
        # feed plane MB/s beside MFU: wire bytes drained per wall second
        # while training (columnar frames -> sliced batches -> staged H2D)
        mnist_feed_mb_s=round(timed * batch_size * record_bytes / dt / 1e6, 1),
        mnist_final_loss=round(final, 4),
    )
    if agg is not None:
        agg.stop()
        rounds = max(
            1, int(agg.registry.counter("cluster_scrape_total").value())
        )
        if agg.total_scrape_cpu_s == 0.0:
            # run shorter than one cadence: measure one round and
            # amortize it over the production interval
            agg.scrape_once()
            denom = agg.interval
        else:
            denom = max(dt, rounds * agg.interval)
        # CPU seconds the scrape thread consumed, NOT its wall time —
        # on a saturated host wall is mostly GIL/IO waits that steal
        # nothing from train.step
        _partial["mnist_aggregator_overhead_pct"] = round(
            100.0 * agg.total_scrape_cpu_s / denom, 4
        )


def _bench_serve(smoke: bool) -> None:
    """``--serve``: the serving engine tax as ONE committed JSON line.

    ``engine_tax`` = raw single-stream ``llama.generate`` tokens/sec ÷
    continuous-engine tokens/sec on the SAME params — the round-5
    VERDICT's "57× serving engine tax" as a first-class bench metric
    instead of a hand-derived ratio of two separate runs. The engine
    leg runs at ``pipeline_depth`` 1 (the pre-overlap serial scheduler)
    AND 2 (the shipped default) so the dispatch-ahead win is measured
    in the same artifact; the depth-2 engine's span ring is distilled
    through ``obs.trace_report`` into
    ``benchmarks/results/serve_*_trace_report.json`` — the engine's
    non-MXU/host residual as a committed artifact, per-phase
    (dispatch/fetch/sweep/prefill) self-time included.
    """
    import tempfile
    import threading as _threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.real_chip import _llama1b_decode_setup
    from tensorflowonspark_tpu.models.llama import generate
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    ns = argparse.Namespace(
        batch_size=4 if smoke else 8,
        seq=16 if smoke else 128,
        new_tokens=24 if smoke else 256,
        spec_k=0,
        model_scale="tiny" if smoke else "1b",
        kv_quantize=False,
    )
    if smoke:
        _partial["smoke"] = True
    b, new_tokens, cfg, model, prompts = _llama1b_decode_setup(ns)
    params = jax.tree.map(
        jax.device_put,
        model.init(
            jax.random.PRNGKey(0), jnp.asarray(prompts[:2])
        )["params"],
    )
    reps = 2 if smoke else 3

    # Raw single-stream floor: ONE row through generate() — the "how
    # fast can these params decode with zero scheduling" reference.
    raw_prompt = jnp.asarray(prompts[:1])
    np.asarray(generate(model, params, raw_prompt, new_tokens)[0, :1])
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(
            generate(model, params, raw_prompt, new_tokens)[0, :1]
        )
    raw_tps = reps * new_tokens / (time.perf_counter() - t0)
    _partial["raw_single_stream_tokens_per_sec"] = round(raw_tps, 1)

    def engine_leg(depth: int):
        eng = ContinuousBatcher(
            model,
            params,
            slots=b,
            prompt_widths=(prompts.shape[1],),
            pipeline_depth=depth,
        )

        def fire_all(n_tokens: int) -> None:
            # ferry worker-thread failures (same pattern as
            # benchmarks/real_chip.py bench_llama1b_engine): a dead
            # engine answers instantly and would fake a measurement
            errors: list = [None] * b
            def one(i):
                try:
                    eng.submit(prompts[i].tolist(), n_tokens)
                except BaseException as e:  # noqa: BLE001
                    errors[i] = e
            threads = [
                _threading.Thread(target=one, args=(i,))
                for i in range(b)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for e in errors:
                if e is not None:
                    raise e

        fire_all(4)  # compile prefill + admit + block, warm the loop
        tok0 = eng.tokens_emitted
        t0 = time.perf_counter()
        for _ in range(reps):
            fire_all(new_tokens)
        dt = time.perf_counter() - t0
        timed_tokens = eng.tokens_emitted - tok0
        st = eng.stats()
        # scheduler-loop host cost per emitted token: the PR-1 phase
        # spans' dispatch+fetch totals over the whole engine lifetime
        # (warm included — identical across legs, so the DELTA between
        # depths is the dispatch-ahead win)
        host_ms = sum(
            st["phase_ms"].get(ph, {}).get("total_ms", 0.0)
            for ph in ("dispatch", "fetch")
        )
        leg = dict(
            tokens_per_sec=round(timed_tokens / dt, 1),
            dispatch_fetch_ms_per_token=round(
                host_ms / max(1, eng.tokens_emitted), 4
            ),
            drain_stalls=st["drain_stalls"],
            overlap_hidden_ms=st["overlap_hidden_ms"],
        )
        return eng, leg

    eng1, leg1 = engine_leg(1)
    eng1.close()
    eng2, leg2 = engine_leg(2)
    _partial["engine_depth1"] = leg1
    _partial["engine_depth2"] = leg2
    _partial["pipeline_speedup"] = round(
        leg2["tokens_per_sec"] / max(leg1["tokens_per_sec"], 1e-9), 3
    )

    # Commit the engine's host-residual evidence: the span ring as a
    # chrome trace, distilled by the same obs.trace_report commit path
    # the MFU bench uses — no more dead trace files in /tmp.
    try:
        trace_dir = tempfile.mkdtemp(prefix="serve_trace_")
        eng2._tracer.write_chrome_trace(
            os.path.join(trace_dir, "engine.trace.json"),
            "serving engine (pipeline_depth=2)",
        )
        _emit_trace_report(
            trace_dir, jax.default_backend(), smoke, name="serve"
        )
    except Exception as e:  # noqa: BLE001 - the headline must still print
        _partial["trace_error"] = f"{type(e).__name__}: {e}"
    finally:
        eng2.close()

    engine_tps = leg2["tokens_per_sec"]
    tax = raw_tps / max(engine_tps, 1e-9)
    _emit(
        {
            "metric": "serve_engine_tax",
            # raw single-stream tok/s ÷ engine tok/s at full occupancy:
            # >1 = scheduling tax dominates (the relay-measured 57×
            # regime), <1 = the engine amortizes its batch
            "value": round(tax, 4),
            "unit": "x",
            # engine throughput as a multiple of the single stream —
            # higher is better, >=1 means batching pays for scheduling
            "vs_baseline": round(engine_tps / max(raw_tps, 1e-9), 3),
            "backend": jax.default_backend(),
            "chips": len(jax.devices()),
            "slots": b,
            "new_tokens": new_tokens,
            **_partial,
        }
    )


def _bench_serve_fleet(smoke: bool) -> None:
    """``--serve-fleet``: saturation throughput scaling, replicas=1 vs 2.

    Each leg puts a :class:`ServingFleet` of N in-process continuous
    engines behind the health-routing ``FleetRouter`` and drives it
    with 2x-slots concurrent blocking submitters for a fixed request
    count, alongside the router's shed/failover counters (both must be
    0 in a healthy unsaturated run: scaling must not come from
    dropping work). Two scaling numbers, the feed-plane (PR 8)
    methodology: the CONTENDED wall ratio (both replicas sharing this
    host's devices — on a 1-core CPU host this reads the routing/
    batch-splitting overhead, not capacity), and the UNCONTENDED
    per-replica rate (each replica driven alone, self-timed — flat
    per-replica rate means the fleet projects to ~N x on pods where
    each replica owns its chip, which is the deployment shape). The
    artifact lands in ``benchmarks/results/serve_fleet_<backend>.json``.
    """
    import threading as _threading

    import jax
    import jax.numpy as jnp

    from benchmarks.real_chip import _llama1b_decode_setup
    from tensorflowonspark_tpu.obs.history import History
    from tensorflowonspark_tpu.obs.slo import SLOEvaluator, router_slos
    from tensorflowonspark_tpu.serving import ContinuousBatcher
    from tensorflowonspark_tpu.serving.fleet import ServingFleet
    from tensorflowonspark_tpu.serving.router import FleetRouter

    ns = argparse.Namespace(
        batch_size=2 if smoke else 4,
        seq=16 if smoke else 128,
        new_tokens=16 if smoke else 128,
        spec_k=0,
        model_scale="tiny" if smoke else "1b",
        kv_quantize=False,
    )
    if smoke:
        _partial["smoke"] = True
    b, new_tokens, cfg, model, prompts = _llama1b_decode_setup(ns)
    params = jax.tree.map(
        jax.device_put,
        model.init(
            jax.random.PRNGKey(0), jnp.asarray(prompts[:2])
        )["params"],
    )
    requests = (2 if smoke else 6) * b  # per leg, after warmup

    def leg(n_replicas: int) -> dict:
        def factory():
            return ContinuousBatcher(
                model,
                params,
                slots=b,
                prompt_widths=(prompts.shape[1],),
            )

        fleet = ServingFleet(
            factory=factory,
            replicas=n_replicas,
            probe_interval=0.5,
            warmup=False,
            drain_timeout=10.0,
        )
        router = FleetRouter(fleet)
        errors: list = []

        def fire(count: int, n_tok: int, tag: int) -> None:
            def one(i):
                try:
                    # distinct prompts defeat prefix affinity so the
                    # load spreads — this leg measures CAPACITY
                    router.submit(
                        prompts[(tag + i) % len(prompts)].tolist(),
                        n_tok,
                    )
                except BaseException as e:  # noqa: BLE001 - ferried
                    errors.append(e)

            threads = [
                _threading.Thread(target=one, args=(i,))
                for i in range(count)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise errors[0]

        fire(n_replicas * b, 4, tag=0)  # compile/warm every replica
        # the SLO budget gate (obs.slo): one History window over the
        # timed fire, warmup compiles excluded via the window cursor
        fleet.metrics.window()
        hist = History(source=f"bench.serve_fleet.r{n_replicas}")
        ev = SLOEvaluator(
            router_slos(latency_objective_s=30.0 if smoke else 10.0),
            hist,
            registry=fleet.metrics,
        )
        t0 = time.perf_counter()
        fire(requests, new_tokens, tag=1)
        dt = time.perf_counter() - t0
        hist.scrape_registry(fleet.metrics)
        verdicts = ev.evaluate()
        st = router.stats()["router"]
        # uncontended: each replica alone, one full b-row batch,
        # self-timed — the per-chip rate a one-replica-per-chip pod
        # would see (the staggered-pull-leg methodology)
        rates = []
        for v in fleet.ready_views():
            best = 0.0
            for _ in range(3):  # best-of: least host interference
                t1 = time.perf_counter()
                v["handle"].submit_many(
                    [
                        prompts[i % len(prompts)].tolist()
                        for i in range(b)
                    ],
                    new_tokens,
                )
                best = max(
                    best,
                    b * new_tokens / (time.perf_counter() - t1),
                )
            rates.append(round(best, 1))
        out = dict(
            tokens_per_sec=round(requests * new_tokens / dt, 1),
            uncontended_per_replica=rates,
            requests=requests,
            shed=sum(st["shed"].values()) if st["shed"] else 0,
            failovers=st["failovers"],
            slo_breaching=sorted(v.slo for v in verdicts if v.breached),
            slo=[v.as_dict() for v in verdicts],
        )
        router.close()
        return out

    leg1 = leg(1)
    leg2 = leg(2)
    _partial["fleet_replicas1"] = leg1
    _partial["fleet_replicas2"] = leg2
    wall_ratio = leg2["tokens_per_sec"] / max(
        leg1["tokens_per_sec"], 1e-9
    )
    # projection: fleet-of-2 aggregate if each replica owned its own
    # device (per-replica uncontended rates summed, over the single
    # replica's uncontended rate) — >= 0.8*N means the router/fleet
    # plane itself costs < 20%; wall_ratio on a shared-device host
    # additionally pays the device contention the projection removes
    projected = sum(leg2["uncontended_per_replica"]) / max(
        leg1["uncontended_per_replica"][0], 1e-9
    )
    result = {
        "metric": "serve_fleet_scaling",
        "value": round(projected, 3),
        "unit": "x",
        "vs_baseline": round(projected / 1.6, 3),
        "wall_ratio_contended": round(wall_ratio, 3),
        "backend": jax.default_backend(),
        "chips": len(jax.devices()),
        "slots_per_replica": b,
        "new_tokens": new_tokens,
        **_partial,
    }
    path = os.path.join(
        _results_dir(),
        f"serve_fleet_{jax.default_backend()}"
        + ("_smoke" if smoke else "")
        + ".json",
    )
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        result["artifact"] = path
    except OSError as e:
        result["artifact_error"] = str(e)
    _emit(result)


def _bench_cache(smoke: bool) -> None:
    """``--cache``: the disaggregated read-through cache tier A/B.

    Serving leg: a 2-replica in-process fleet serves a shared-prefix
    workload — P distinct "system prompts" (prefix families), every
    request one family plus a unique 2-token tail — round-robin across
    the replicas, with ``prefix_l2`` off vs on. Round-robin is the
    cache-hostile shape: each replica's L1 holds only what IT served
    and thrashes across families, so without the fleet tier every
    L1 miss re-prefills the whole family prefix from token 0. With the
    tier, the ladder a sibling replica published turns that miss into
    a fetch + one-chunk continuation (and the reconstructed entry
    re-seeds L1, so the tier heals L1 instead of replacing it). The
    headline ``value`` is the fleet tokens/sec ratio (L2 on / off);
    the leg also commits both legs' tok/s and the cross-replica L2
    hit counters (must be > 0).

    Training leg: two concurrent readers drain one columnar framed
    dataset through a shared ``CacheTier``; the committed counters
    prove backing storage was read ~1x the dataset size (not once per
    reader). Artifact: ``benchmarks/results/cache_<backend>.json``.
    """
    import tempfile
    import threading as _threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.real_chip import _llama1b_decode_setup
    from tensorflowonspark_tpu.serving import ContinuousBatcher
    from tensorflowonspark_tpu.serving.fleet import ServingFleet

    ns = argparse.Namespace(
        batch_size=2,
        seq=132,  # a rung (128) + tail: the ladder covers ~the prefix
        new_tokens=4 if smoke else 16,
        spec_k=0,
        model_scale="tiny" if smoke else "1b",
        kv_quantize=False,
    )
    if smoke:
        _partial["smoke"] = True
    b, new_tokens, cfg, model, prompts = _llama1b_decode_setup(ns)
    params = jax.tree.map(
        jax.device_put,
        model.init(
            jax.random.PRNGKey(0), jnp.asarray(prompts[:2])
        )["params"],
    )
    seq = int(prompts.shape[1])
    chunk = 4 if smoke else 16
    base = [int(t) for t in prompts[0]]
    families = 7  # odd (coprime with the 2-replica round-robin) so
    # EVERY replica serves every family, and more family state than
    # one L1 holds: the per-replica L1 must thrash
    requests = 4 * families

    def mk_prompt(family: int, tail: int) -> list[int]:
        p = list(base)
        p[0] = 2 + family  # family identity up front: distinct prefixes
        p[-2] = 2 + (tail * 7) % 241
        p[-1] = 2 + (tail * 13) % 241
        return p

    def serving_leg(l2) -> dict:
        def factory():
            return ContinuousBatcher(
                model,
                params,
                slots=b,
                prompt_widths=(seq,),
                prefill_chunk=chunk,
                # >= 2x the ladder rungs: boundary inserts (and so L2
                # offers) are flood-capped at prefix_cache//2 per
                # request — smaller and the deep rungs never publish
                prefix_cache=16,
            )

        fleet = ServingFleet(
            factory=factory,
            replicas=2,
            probe_interval=0.5,
            warmup=False,
            drain_timeout=10.0,
            prefix_l2=l2,
        )
        try:
            views = fleet.views()
            # replica 0 prefills every family once: with an L2 this
            # publishes each family's boundary ladder fleet-wide;
            # replica 1 gets one request so it is compile-warm (its L1
            # stays cold for all but that family)
            for f in range(families):
                views[0]["handle"].submit_many([mk_prompt(f, 200 + f)], 2)
            views[1]["handle"].submit_many([mk_prompt(0, 220)], 2)
            if l2 is not None:
                # offers are fire-and-forget; wait for the filler to
                # drain before timing (a real fleet is long-lived)
                deadline = time.monotonic() + 30.0
                while (
                    time.monotonic() < deadline
                    and (fleet.cache_stats() or {}).get("entries", 0)
                    < families
                ):
                    time.sleep(0.05)
            # timed: round-robin, unique tails — min of 2 passes
            walls = []
            for rep in range(2):
                t0 = time.perf_counter()
                for i in range(requests):
                    views[i % 2]["handle"].submit_many(
                        [mk_prompt(i % families, 100 * rep + i)],
                        new_tokens,
                    )
                walls.append(time.perf_counter() - t0)
            dt = min(walls)
            st = [v["handle"].stats() for v in views]
            return dict(
                tokens_per_sec=round(requests * new_tokens / dt, 1),
                requests_per_pass=requests,
                wall_s=[round(w, 3) for w in walls],
                l2_hits=sum(s.get("prefix_l2_hits", 0) for s in st),
                l2_misses=sum(s.get("prefix_l2_misses", 0) for s in st),
                l2_offer_dedups=sum(
                    s.get("prefix_l2_offer_dedups", 0) for s in st
                ),
                tier=fleet.cache_stats(),
            )
        finally:
            fleet.close()

    l1_leg = serving_leg(None)
    _partial["cache_l1_only"] = l1_leg
    l2_leg = serving_leg("inproc")
    _partial["cache_l2"] = l2_leg

    # -- training leg: two readers, one backing pass -------------------
    from tensorflowonspark_tpu.cachetier import (
        CacheTier,
        FrameCache,
        LocalClient,
    )
    from tensorflowonspark_tpu.data.grain_source import (
        ColumnarFrameDataSource,
    )
    from tensorflowonspark_tpu.feed import columnar as col
    from tensorflowonspark_tpu.feed.columnar import scan_frames

    n_records = 512 if smoke else 4096
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "bench.colf")
        col.write_frames(
            path,
            (
                {
                    "x": np.arange(32, dtype=np.float32) + i,
                    "y": np.int64(i),
                }
                for i in range(n_records)
            ),
            records_per_frame=64,
        )
        payload = sum(span for _, span, n in scan_frames(path) if n)
        tier = CacheTier(capacity_bytes=256 << 20)
        srcs = [
            ColumnarFrameDataSource(
                path, frame_cache=FrameCache(LocalClient(tier))
            )
            for _ in range(2)
        ]
        orders = [
            range(n_records),
            range(n_records - 1, -1, -1),
        ]

        def drain(ri: int) -> None:
            for i in orders[ri]:
                srcs[ri][i]

        threads = [
            _threading.Thread(target=drain, args=(ri,)) for ri in range(2)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        tst = tier.stats()
    training = dict(
        records=n_records,
        readers=2,
        payload_bytes=payload,
        backing_read_bytes=tst["backing_read_bytes"],
        # ~1.0 = each frame hit backing storage once ACROSS readers
        # (2.0 would mean the tier saved nothing)
        backing_ratio=round(tst["backing_read_bytes"] / payload, 3),
        tier_hits=tst["hits"],
        tier_misses=tst["misses"],
        wall_s=round(dt, 3),
    )
    _partial["cache_training"] = training

    speedup = l2_leg["tokens_per_sec"] / max(
        l1_leg["tokens_per_sec"], 1e-9
    )
    result = {
        "metric": "cachetier_readthrough",
        # headline: fleet tok/s with the tier over without it on the
        # same round-robin shared-prefix traffic (>1 = the tier
        # recovers prefill compute the L1-thrashing fleet re-pays)
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 3),
        "tokens_per_sec_l1_only": l1_leg["tokens_per_sec"],
        "tokens_per_sec_l2": l2_leg["tokens_per_sec"],
        "l2_hits": l2_leg["l2_hits"],
        "training_backing_ratio": training["backing_ratio"],
        "backend": jax.default_backend(),
        "chips": len(jax.devices()),
        "new_tokens": new_tokens,
        **_partial,
    }
    path = os.path.join(
        _results_dir(),
        f"cache_{jax.default_backend()}"
        + ("_smoke" if smoke else "")
        + ".json",
    )
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        result["artifact"] = path
    except OSError as e:
        result["artifact_error"] = str(e)
    _emit(result)


def _metric_total(registry, name: str) -> float:
    """Sum every labelled series of one counter straight off the
    registry's rendered exposition — the same surface a scraper reads,
    so the artifact reports the metric's real value, not a shadow."""
    total = 0.0
    for line in registry.render().splitlines():
        if line.startswith(f"{name} ") or line.startswith(f"{name}{{"):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def _bench_autotune(smoke: bool) -> None:
    """``--autotune``: feedback-controlled recovery from bad knobs.

    Two legs, each booted with DELIBERATELY bad knob settings and
    handed to a :class:`tensorflowonspark_tpu.autotune.Controller`
    driving the component's sanctioned actuation path; acceptance is
    the converged throughput reaching >= 90% of the same pipeline
    hand-tuned (``recovered_frac`` per leg):

    - **feed leg** — the mnist feed pipeline (columnar frames ->
      DataFeed -> DevicePrefetcher) started at prefetch depth 1
      against a producer with periodic shard-open stalls plus a
      per-depth host staging tax, so throughput peaks at an interior
      depth: the controller must grow ``feed.prefetch_depth`` to hide
      the stalls, overshoot the peak, and REVERT (the committed audit
      trail must show ``autotune_reverts_total > 0``);
    - **serve leg** — a 1-replica continuous-batching fleet booted at
      ``decode_block=1 / pipeline_depth=1`` (the un-amortized
      host-round-trip config) behind a router with a pessimistic
      cold-start ``service_time_hint_s``: the controller climbs both
      engine knobs through ``ContinuousBatcher.set_knobs`` (installed
      between decode blocks) and the direct router policy replaces the
      hint with the measured p90.

    Every move/revert is a registered flight-recorder event and a row
    in the controllers' decision logs (dumped to ``logs/autotune-*``
    for ``tools/obs_snapshot.py`` and embedded in the committed
    ``benchmarks/results/autotune_<backend>[_smoke].json``).
    """
    import jax

    from tensorflowonspark_tpu.obs import flightrec

    if smoke:
        _partial["smoke"] = True
    rec = flightrec.install(
        os.path.join("logs", "flightrec-bench-autotune.json"),
        process="bench-autotune",
    )

    feed = _autotune_feed_leg()
    _partial["feed_leg"] = feed
    serve = _autotune_serve_leg(smoke)
    _partial["serve_leg"] = serve

    events = rec.snapshot("bench-autotune")["events"]
    at_events = [
        e for e in events if str(e.get("kind", "")).startswith("autotune_")
    ]
    decisions_total = feed["decisions_total"] + serve["decisions_total"]
    reverts_total = feed["reverts_total"] + serve["reverts_total"]
    result = {
        "metric": "autotune_recovery",
        "value": round(
            min(feed["recovered_frac"], serve["recovered_frac"]), 3
        ),
        "unit": "frac_of_hand_tuned",
        "vs_baseline": round(
            min(feed["recovered_frac"], serve["recovered_frac"]) / 0.9, 3
        ),
        "autotune_decisions_total": decisions_total,
        "autotune_reverts_total": reverts_total,
        "flightrec_autotune_events": len(at_events),
        **_partial,
    }
    path = os.path.join(
        _results_dir(),
        f"autotune_{jax.default_backend()}"
        + ("_smoke" if smoke else "")
        + ".json",
    )
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
        result["artifact"] = path
    except OSError as e:
        result["artifact_error"] = str(e)
    _emit(result)


def _autotune_feed_leg() -> dict:
    """The mnist-feed autotune leg (see ``_bench_autotune``). Pure-host
    physics so the controller's behavior — not chip speed — is what is
    measured: the consumer "train step" is a fixed sleep, the producer
    stalls periodically (a shard-open hiccup the prefetch queue must
    hide, amortizable up to ``depth x compute`` per stall), and staging
    costs a small per-depth tax (host-memory pressure), giving
    throughput an interior peak the hill-climb must find and defend."""
    import secrets

    import numpy as np

    from tensorflowonspark_tpu.autotune import Controller, KnobRegistry
    from tensorflowonspark_tpu.autotune.policies import (
        prefetch_depth_policy,
    )
    from tensorflowonspark_tpu.cluster import manager as tf_manager
    from tensorflowonspark_tpu.feed import DataFeed, DevicePrefetcher
    from tensorflowonspark_tpu.feed import columnar as col
    from tensorflowonspark_tpu.obs.history import History
    from tensorflowonspark_tpu.obs.registry import default_registry

    compute_s = 0.010  # the consumer's fixed per-batch "train step"
    stall_every = 16  # producer hiccup cadence (batches)
    stall_s = 0.12  # producer hiccup depth — hidden iff depth >= 12
    tax_knee = 17  # depth past which staging pays a per-batch tax
    tax_s = 0.006  # (host-memory pressure): past the knee the producer
    # becomes the bottleneck, so deeper REGRESSES (the revert bait)
    hand_depth = 15
    batch = 32
    rows = 256

    rng = np.random.default_rng(0)
    images = (rng.random((rows, 28, 28, 1)) * 255).astype(np.uint8)
    labels = rng.integers(0, 10, size=rows).astype(np.int32)

    def pipeline(depth: int):
        mgr = tf_manager.start(
            secrets.token_bytes(8), mode="local", maxsize=8
        )
        stop = threading.Event()

        def produce():
            import queue as _q

            q = mgr.get_queue("input")
            chunk = col.columnize_records(list(zip(images, labels)))
            seq = 0
            while not stop.is_set():
                try:
                    q.put(
                        col.ColumnarFrame(
                            col.frame_bytes(
                                chunk, stream="autotune", seq=seq
                            )
                        ),
                        timeout=0.2,
                    )
                    seq += 1
                except _q.Full:
                    continue
                except (OSError, EOFError, BrokenPipeError):
                    return  # manager torn down at leg end

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        feed = DataFeed(
            mgr, input_mapping={"image": "image", "label": "label"}
        )

        cell: dict = {}
        nbatch = [0]

        def prepare(cols):
            nbatch[0] += 1
            pf = cell.get("pf")
            d = pf.stats()["depth"] if pf is not None else depth
            if d > tax_knee:
                time.sleep(tax_s * (d - tax_knee))
            if nbatch[0] % stall_every == 0:
                time.sleep(stall_s)
            return cols

        pf = DevicePrefetcher.from_feed(
            feed,
            batch,
            depth=depth,
            prepare=prepare,
            transform=lambda b: b,  # host-physics leg: no device hop
        )
        cell["pf"] = pf
        return mgr, stop, producer, pf

    def drive(pf, seconds: float, pump=None) -> float:
        """Consume batches for ~seconds (the training loop stand-in);
        returns delivered batches/sec."""
        count = 0
        t0 = time.perf_counter()
        deadline = t0 + seconds
        for _ in pf:
            time.sleep(compute_s)
            count += 1
            if pump is not None:
                pump()
            if time.perf_counter() >= deadline:
                break
        return count / max(time.perf_counter() - t0, 1e-9)

    def teardown(mgr, stop, producer, pf) -> None:
        pf.close()
        stop.set()
        producer.join(timeout=2.0)
        mgr.stop()

    # -- hand-tuned reference (static, no controller) -----------------
    mgr, stop, producer, pf = pipeline(hand_depth)
    drive(pf, 1.5)  # settle
    hand_rate = drive(pf, 3.0)
    teardown(mgr, stop, producer, pf)

    # -- bad start, then the controller takes the knob ----------------
    mgr, stop, producer, pf = pipeline(1)
    drive(pf, 1.0)  # settle
    bad_rate = drive(pf, 2.5)

    knobs = KnobRegistry()
    knob, policy = prefetch_depth_policy(
        pf, lo=1, hi=24, window_s=1.0
    )
    knobs.register(knob)
    hist = History(source="bench.autotune.feed")
    ctrl = Controller(
        knobs, hist, [policy], source="bench-feed"
    )

    # a pending move is judged at the NEXT step, so the step cadence
    # must match the objective window for a purely post-move verdict
    scrape_s, step_s = 0.2, 1.0
    state = {"scrape": 0.0, "step": 0.0}

    def pump():
        now = time.time()
        if now >= state["scrape"]:
            state["scrape"] = now + scrape_s
            hist.scrape_registry(default_registry())
        if now >= state["step"]:
            state["step"] = now + step_s
            ctrl.step(now)

    drive(pf, 22.0, pump)  # converge: one knob move per window
    tuned_rate = drive(pf, 3.0, pump)  # still online, now converged
    final_depth = pf.stats()["depth"]
    teardown(mgr, stop, producer, pf)

    log = ctrl.decision_log()
    dump_path = ctrl.dump()
    return {
        "bad_batches_per_sec": round(bad_rate, 1),
        "hand_tuned_batches_per_sec": round(hand_rate, 1),
        "tuned_batches_per_sec": round(tuned_rate, 1),
        "recovered_frac": round(tuned_rate / max(hand_rate, 1e-9), 3),
        "initial_depth": 1,
        "hand_depth": hand_depth,
        "final_depth": final_depth,
        "decisions_total": _metric_total(
            default_registry(), "autotune_decisions_total"
        ),
        "reverts_total": _metric_total(
            default_registry(), "autotune_reverts_total"
        ),
        "decision_log": log,
        "decision_log_path": dump_path,
        "knobs": knobs.snapshot(),
    }


def _autotune_serve_leg(smoke: bool) -> dict:
    """The serve-fleet autotune leg (see ``_bench_autotune``)."""
    import threading as _threading

    import jax
    import jax.numpy as jnp

    from benchmarks.real_chip import _llama1b_decode_setup
    from tensorflowonspark_tpu.autotune import Controller, KnobRegistry
    from tensorflowonspark_tpu.autotune.policies import (
        engine_knob_policies,
        router_estimate_policy,
    )
    from tensorflowonspark_tpu.obs.history import History
    from tensorflowonspark_tpu.obs.slo import SLOEvaluator, router_slos
    from tensorflowonspark_tpu.serving import ContinuousBatcher
    from tensorflowonspark_tpu.serving.fleet import ServingFleet
    from tensorflowonspark_tpu.serving.router import FleetRouter

    ns = argparse.Namespace(
        batch_size=2 if smoke else 4,
        seq=16 if smoke else 128,
        new_tokens=8 if smoke else 32,
        spec_k=0,
        model_scale="tiny" if smoke else "1b",
        kv_quantize=False,
    )
    b, new_tokens, cfg, model, prompts = _llama1b_decode_setup(ns)
    params = jax.tree.map(
        jax.device_put,
        model.init(
            jax.random.PRNGKey(0), jnp.asarray(prompts[:2])
        )["params"],
    )
    block_hi = 4 if smoke else 8
    hand_knobs = {"decode_block": block_hi, "pipeline_depth": 2}
    bad_knobs = {"decode_block": 1, "pipeline_depth": 1}

    def build(knob_cfg: dict, hint_s: float | None):
        fleet = ServingFleet(
            factory=lambda: ContinuousBatcher(
                model,
                params,
                slots=b,
                prompt_widths=(prompts.shape[1],),
                **knob_cfg,
            ),
            replicas=1,
            probe_interval=0.5,
            warmup=False,
            drain_timeout=10.0,
        )
        router = FleetRouter(fleet, service_time_hint_s=hint_s)
        return fleet, router

    class _Load:
        """Closed-loop submitters: 2x-slots threads resubmitting
        against the router until stopped; the completed-token tally is
        the throughput read."""

        def __init__(self, router, threads: int):
            self._router = router
            self._stop = _threading.Event()
            self._lock = _threading.Lock()
            self._tokens = 0  # guarded-by: self._lock
            self.errors: list = []
            self._threads = [
                _threading.Thread(target=self._run, args=(t,), daemon=True)
                for t in range(threads)
            ]
            for t in self._threads:
                t.start()

        def _run(self, tag: int) -> None:
            i = 0
            while not self._stop.is_set():
                try:
                    self._router.submit(
                        prompts[(tag + i) % len(prompts)].tolist(),
                        new_tokens,
                    )
                except BaseException as e:  # noqa: BLE001 - ferried
                    if not self._stop.is_set():
                        self.errors.append(e)
                    return
                with self._lock:
                    self._tokens += new_tokens
                i += 1

        def tokens(self) -> int:
            with self._lock:
                return self._tokens

        def stop(self) -> None:
            self._stop.set()
            for t in self._threads:
                t.join(timeout=30.0)

    def warm(router, engine) -> None:
        """Compile prefill and every decode-block program the climb
        will visit, then restore the leg's boot knobs — warmup, not
        tuning: the timed phases still start from the bad config."""
        boot = dict(engine.stats())
        for k in range(1, block_hi + 1):
            engine.set_knobs(decode_block=k)
            threads = [
                _threading.Thread(
                    target=lambda i=i: router.submit(
                        prompts[i % len(prompts)].tolist(), 4
                    )
                )
                for i in range(b)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        engine.set_knobs(
            decode_block=boot["decode_block"],
            pipeline_depth=boot["pipeline_depth"],
        )

    def rate_over(load, seconds: float, pump=None) -> float:
        c0, t0 = load.tokens(), time.perf_counter()
        deadline = t0 + seconds
        while time.perf_counter() < deadline:
            time.sleep(0.05)
            if pump is not None:
                pump()
        if load.errors:
            raise load.errors[0]
        return (load.tokens() - c0) / max(
            time.perf_counter() - t0, 1e-9
        )

    # -- hand-tuned reference -----------------------------------------
    fleet, router = build(hand_knobs, None)
    engine = fleet.ready_views()[0]["handle"].engine
    warm(router, engine)
    load = _Load(router, threads=2 * b)
    time.sleep(1.0)  # settle
    hand_rate = rate_over(load, 5.0)
    load.stop()
    router.close()

    # -- bad boot, then the controller takes the knobs ----------------
    fleet, router = build(bad_knobs, hint_s=25.0)
    engine = fleet.ready_views()[0]["handle"].engine
    warm(router, engine)
    est_before = router.service_estimate()
    load = _Load(router, threads=2 * b)
    time.sleep(1.0)
    bad_rate = rate_over(load, 2.5)

    knobs = KnobRegistry()
    policies = []
    for knob, policy in engine_knob_policies(
        engine,
        deadline_s=30.0,
        decode_block_hi=block_hi,
        pipeline_depth_hi=2,
        window_s=2.0,
    ):
        knobs.register(knob)
        policies.append(policy)
    rknob, rpolicy = router_estimate_policy(
        router, q=0.9, lo_s=0.02, window_s=4.0
    )
    knobs.register(rknob)
    policies.append(rpolicy)
    hist = History(source="bench.autotune.serve")
    ev = SLOEvaluator(
        router_slos(latency_objective_s=30.0 if smoke else 10.0),
        hist,
        registry=fleet.metrics,
    )
    ctrl = Controller(
        knobs,
        hist,
        policies,
        slo=ev,
        metrics_registry=fleet.metrics,
        source="bench-serve",
    )

    state = {"scrape": 0.0, "step": 0.0}

    def pump():
        now = time.time()
        if now >= state["scrape"]:
            state["scrape"] = now + 0.25
            hist.scrape_registry(fleet.metrics)
            hist.scrape_registry(engine.metrics)
        if now >= state["step"]:
            # judge-at-next-step: 2.5s between steps keeps the 2.0s
            # objective window clear of the apply transient (a
            # pipeline-depth change drains the current window first)
            state["step"] = now + 2.5
            ev.evaluate(now)
            ctrl.step(now)

    rate_over(load, 30.0, pump)  # converge
    tuned_rate = rate_over(load, 5.0, pump)  # still online, converged
    final = {
        k: engine.stats()[k] for k in ("decode_block", "pipeline_depth")
    }
    est_after = router.service_estimate()
    load.stop()
    router.close()

    log = ctrl.decision_log()
    dump_path = ctrl.dump()
    return {
        "bad_tokens_per_sec": round(bad_rate, 1),
        "hand_tuned_tokens_per_sec": round(hand_rate, 1),
        "tuned_tokens_per_sec": round(tuned_rate, 1),
        "recovered_frac": round(tuned_rate / max(hand_rate, 1e-9), 3),
        "initial_knobs": bad_knobs,
        "hand_knobs": hand_knobs,
        "final_knobs": final,
        "service_estimate_before_s": round(est_before, 4),
        "service_estimate_after_s": round(est_after, 4),
        "slo_breaching": ev.breaching(),
        "decisions_total": _metric_total(
            fleet.metrics, "autotune_decisions_total"
        ),
        "reverts_total": _metric_total(
            fleet.metrics, "autotune_reverts_total"
        ),
        "decision_log": log,
        "decision_log_path": dump_path,
        "knobs": knobs.snapshot(),
    }


def _bench_rollout(smoke: bool) -> None:
    """``--rollout``: chaos-proving zero-downtime weight rollout.

    A 2-replica in-process fleet behind the health-routing router
    serves SUSTAINED streaming load while K successive weight versions
    roll through the :class:`RolloutController` (per-seat drain →
    between-block swap → re-warm → readiness-gated rejoin). The
    committed artifact asserts the acceptance contract directly:

    - **zero dropped or hung requests** — every stream started during
      the run resolves as ok or a typed shed (worker joins bound it;
      non-shed errors fail the bench),
    - **admitted p99 within the deadline budget** throughout the
      rollouts (every request carries ``deadline_s``; admitted =
      not shed at admission),
    - **every completion stamped with a coherent weights version** —
      a stamp from the published set, with the post-rollout tail
      entirely on the final version.

    Artifact: ``benchmarks/results/rollout_<backend>[_smoke].json``.
    """
    import threading as _threading

    import jax
    import jax.numpy as jnp
    import numpy as _np

    from benchmarks.real_chip import _llama1b_decode_setup
    from tensorflowonspark_tpu.obs.history import History
    from tensorflowonspark_tpu.obs.slo import SLOEvaluator, router_slos
    from tensorflowonspark_tpu.serving import ContinuousBatcher
    from tensorflowonspark_tpu.serving.fleet import ServingFleet
    from tensorflowonspark_tpu.serving.rollout import RolloutController
    from tensorflowonspark_tpu.serving.router import FleetRouter

    ns = argparse.Namespace(
        batch_size=2 if smoke else 4,
        seq=16 if smoke else 64,
        new_tokens=8 if smoke else 32,
        spec_k=0,
        model_scale="tiny" if smoke else "1b",
        kv_quantize=False,
    )
    if smoke:
        _partial["smoke"] = True
    b, new_tokens, cfg, model, prompts = _llama1b_decode_setup(ns)
    rng = jax.random.PRNGKey(0)
    base_params = jax.tree.map(
        jax.device_put,
        model.init(rng, jnp.asarray(prompts[:2]))["params"],
    )
    n_versions = 2 if smoke else 3
    deadline_s = 60.0 if smoke else 120.0
    n_workers = 4
    versions = {}
    for k in range(1, n_versions + 1):
        vp = model.init(
            jax.random.PRNGKey(k), jnp.asarray(prompts[:2])
        )["params"]
        versions[f"v{k}"] = jax.tree.map(_np.asarray, vp)
    published = {"v0", *versions}

    def factory():
        return ContinuousBatcher(
            model,
            base_params,
            slots=b,
            prompt_widths=(prompts.shape[1],),
        )

    fleet = ServingFleet(
        factory=factory,
        replicas=2,
        probe_interval=0.5,
        warmup=False,
        drain_timeout=30.0,
    )
    router = FleetRouter(fleet)
    ctl = RolloutController(
        fleet, drain_timeout=60.0, verify_timeout=120.0
    )
    # the SLO budget gate (obs.slo): windowed history over the whole
    # run; the latency objective IS the deadline budget, so "admitted
    # p99 within deadline" and the declarative SLO agree by design
    hist = History(source="bench.rollout")
    slo_ev = SLOEvaluator(
        router_slos(latency_objective_s=deadline_s),
        hist,
        registry=fleet.metrics,
    )
    results: dict[int, tuple] = {}
    stop_load = _threading.Event()
    phase = {"current": "v0"}  # version being served when issued

    def load_worker(widx: int) -> None:
        n = 0
        while not stop_load.is_set():
            key = widx * 1_000_000 + n
            n += 1
            t0 = time.perf_counter()
            try:
                s = router.stream(
                    prompts[key % len(prompts)].tolist(),
                    new_tokens,
                    deadline_s=deadline_s,
                )
                toks = list(s)
                results[key] = (
                    "ok",
                    time.perf_counter() - t0,
                    s.weights_version,
                    len(toks),
                    phase["current"],
                )
            except BaseException as e:  # noqa: BLE001 - the verdict
                results[key] = (
                    "err",
                    time.perf_counter() - t0,
                    type(e).__name__,
                    0,
                    phase["current"],
                )
            time.sleep(0.01)

    workers = [
        _threading.Thread(target=load_worker, args=(i,), daemon=True)
        for i in range(n_workers)
    ]
    t_start = time.perf_counter()
    for t in workers:
        t.start()
    time.sleep(1.0)
    outcomes = []
    for k in range(1, n_versions + 1):
        ver = f"v{k}"
        out = ctl.publish(versions[ver], version=ver)
        outcomes.append({"version": ver, "outcome": out})
        phase["current"] = ver
        hist.scrape_registry(fleet.metrics)
        time.sleep(0.5)  # serve a beat between versions
    time.sleep(1.0)  # post-rollout tail on the final version
    stop_load.set()
    hung = 0
    for t in workers:
        t.join(timeout=max(120.0, deadline_s + 60.0))
        if t.is_alive():
            hung += 1
    wall_s = time.perf_counter() - t_start
    hist.scrape_registry(fleet.metrics)
    slo_verdicts = slo_ev.evaluate()
    router.close()

    oks = [v for v in results.values() if v[0] == "ok"]
    errs = [v for v in results.values() if v[0] == "err"]
    sheds = [
        v
        for v in errs
        if v[2] in ("FleetOverloaded", "FleetUnavailable")
    ]
    hard_errors = [v for v in errs if v not in sheds]
    latencies = sorted(v[1] for v in oks)
    p99 = (
        latencies[max(0, int(len(latencies) * 0.99) - 1)]
        if latencies
        else float("inf")
    )
    version_counts: dict[str, int] = {}
    bad_stamps = 0
    for v in oks:
        stamp = v[2]
        version_counts[stamp] = version_counts.get(stamp, 0) + 1
        if stamp not in published:
            bad_stamps += 1
    final_ver = f"v{n_versions}"
    tail_ok = [v for v in oks if v[4] == final_ver]
    tail_on_final = sum(1 for v in tail_ok if v[2] == final_ver)
    checks = {
        "zero_dropped_or_hung": hung == 0 and not hard_errors,
        "all_rollouts_completed": all(
            o["outcome"] == "completed" for o in outcomes
        ),
        "admitted_p99_within_deadline": p99 <= deadline_s,
        "every_completion_version_stamped": bad_stamps == 0
        and all(v[2] is not None for v in oks),
        "tail_serves_final_version": (
            tail_ok and tail_on_final == len(tail_ok)
        )
        or not tail_ok,
        # the declarative gate: rollouts must not burn the fleet's
        # latency budget (availability verdicts are reported below but
        # not gated — transient drain sheds are the tolerated cost)
        "slo_latency_silent": not any(
            v.slo == "fleet_latency" and v.breached for v in slo_verdicts
        ),
    }
    result = {
        "metric": "rollout_zero_downtime",
        "value": float(len(oks)),
        "unit": "requests",
        "vs_baseline": 1.0 if all(checks.values()) else 0.0,
        "passed": all(checks.values()),
        "checks": checks,
        "versions_rolled": n_versions,
        "rollouts": outcomes,
        "requests_ok": len(oks),
        "requests_shed": len(sheds),
        "requests_hard_errors": len(hard_errors),
        "hung_workers": hung,
        "admitted_p99_s": round(p99, 3),
        "deadline_budget_s": deadline_s,
        "version_counts": version_counts,
        "slo": [v.as_dict() for v in slo_verdicts],
        "rollout_stats": ctl.stats(),
        "wall_s": round(wall_s, 1),
        "replicas": 2,
        "new_tokens": new_tokens,
        **_partial,
    }
    path = os.path.join(
        _results_dir(),
        f"rollout_{jax.default_backend()}"
        + ("_smoke" if smoke else "")
        + ".json",
    )
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        result["artifact"] = path
    except OSError as e:
        result["artifact_error"] = str(e)
    _emit(result)
    if not all(checks.values()):
        raise SystemExit(
            f"rollout bench failed acceptance checks: "
            f"{ {k: v for k, v in checks.items() if not v} }"
        )


def _bench_online(smoke: bool) -> None:
    """``--online``: close the continual-training loop on live traffic.

    A 2-replica in-process fleet serves sustained streaming load; every
    completed request is appended to a crash-safe
    :class:`~tensorflowonspark_tpu.feed.livelog.TrafficLog` stamped
    with the ``weights_version`` that generated it. The driver-side
    :class:`~tensorflowonspark_tpu.online.OnlineLoop` discovers each
    sealed segment and hands it to a trainer, which folds the logged
    records into a new weights version and publishes it through the
    :class:`RolloutController` — so the fleet hot-swaps to weights
    trained on its OWN live traffic, mid-run, K times. The committed
    artifact asserts the loop's acceptance contract:

    - **generation measurably shifts toward fresh data**: the share of
      completions stamped with a live-trained version goes from 0
      before the first cycle to ~1.0 in the tail;
    - **zero requests dropped**: no hard errors or hung workers on the
      serve path, and zero traffic-log records dropped
      (``online_records_dropped_total`` stays 0 — the log never
      blocks or loses the serve path's data);
    - **serve p99 within the SLO budget** throughout the in-loop
      rollouts (the same declarative ``router_slos`` gate the rollout
      bench uses);
    - **the loop stays healthy**: every cycle trains on nonzero fresh
      records, no stall events, final data age within the freshness
      objective.

    Artifact: ``benchmarks/results/online_<backend>[_smoke].json``.
    """
    import tempfile as _tempfile
    import threading as _threading

    import jax
    import jax.numpy as jnp
    import numpy as _np

    from benchmarks.real_chip import _llama1b_decode_setup
    from tensorflowonspark_tpu.feed.livelog import (
        TrafficLog,
        decode_records,
        metrics as livelog_metrics,
    )
    from tensorflowonspark_tpu.feed.manifest import read_manifest
    from tensorflowonspark_tpu.obs.history import History
    from tensorflowonspark_tpu.obs.slo import SLOEvaluator, router_slos
    from tensorflowonspark_tpu.online import OnlineLoop
    from tensorflowonspark_tpu.serving import ContinuousBatcher
    from tensorflowonspark_tpu.serving.fleet import ServingFleet
    from tensorflowonspark_tpu.serving.rollout import RolloutController
    from tensorflowonspark_tpu.serving.router import FleetRouter

    ns = argparse.Namespace(
        batch_size=2 if smoke else 4,
        seq=16 if smoke else 64,
        new_tokens=8 if smoke else 32,
        spec_k=0,
        model_scale="tiny" if smoke else "1b",
        kv_quantize=False,
    )
    if smoke:
        _partial["smoke"] = True
    b, new_tokens, cfg, model, prompts = _llama1b_decode_setup(ns)
    rng = jax.random.PRNGKey(0)
    base_params = jax.tree.map(
        jax.device_put,
        model.init(rng, jnp.asarray(prompts[:2]))["params"],
    )
    n_cycles = 2 if smoke else 3
    deadline_s = 60.0 if smoke else 120.0
    freshness_objective_s = 30.0
    n_workers = 4

    def factory():
        return ContinuousBatcher(
            model,
            base_params,
            slots=b,
            prompt_widths=(prompts.shape[1],),
        )

    fleet = ServingFleet(
        factory=factory,
        replicas=2,
        probe_interval=0.5,
        warmup=False,
        drain_timeout=30.0,
    )
    router = FleetRouter(fleet)
    ctl = RolloutController(
        fleet, drain_timeout=60.0, verify_timeout=120.0
    )
    hist = History(source="bench.online")
    slo_ev = SLOEvaluator(
        router_slos(latency_objective_s=deadline_s),
        hist,
        registry=fleet.metrics,
    )

    # the live traffic log the serve path feeds (small rotation so
    # segments seal within each beat) and the loop that grows the
    # "training run" — here a stub cluster whose appended shards feed
    # the in-process trainer below
    log_root = _tempfile.mkdtemp(prefix="tfos-online-bench-")
    traffic = TrafficLog(
        log_root,
        rotate_records=16 if smoke else 64,
        frame_records=8,
    )

    class _BenchCluster:
        def __init__(self):
            self.pending: list = []
            self.lock = _threading.Lock()

        def extend_shards(self, files):
            with self.lock:
                self.pending.extend(files)

        def take(self):
            with self.lock:
                out, self.pending = self.pending, []
            return out

    cluster = _BenchCluster()
    progress = {"v": "v0"}
    loop = OnlineLoop(
        cluster,
        log_root,
        progress_fn=lambda: progress["v"],
        stall_after_s=60.0,
        freshness_objective_s=freshness_objective_s,
    )

    results: dict[int, tuple] = {}
    stop_load = _threading.Event()
    phase = {"current": "v0"}

    def load_worker(widx: int) -> None:
        n = 0
        while not stop_load.is_set():
            key = widx * 1_000_000 + n
            n += 1
            t0 = time.perf_counter()
            prompt = prompts[key % len(prompts)].tolist()
            try:
                s = router.stream(prompt, new_tokens, deadline_s=deadline_s)
                toks = list(s)
                results[key] = (
                    "ok",
                    time.perf_counter() - t0,
                    s.weights_version,
                    len(toks),
                    phase["current"],
                )
                # the serve path's write into the loop: stamped with
                # the version that generated the completion
                traffic.append(
                    prompt,
                    toks,
                    outcome=1.0,
                    weights_version=s.weights_version,
                    trace_id=f"r{key}",
                )
            except BaseException as e:  # noqa: BLE001 - the verdict
                results[key] = (
                    "err",
                    time.perf_counter() - t0,
                    type(e).__name__,
                    0,
                    phase["current"],
                )
            time.sleep(0.01)

    workers = [
        _threading.Thread(target=load_worker, args=(i,), daemon=True)
        for i in range(n_workers)
    ]
    # pay the jit compile before the timed beats: the cycles below
    # measure the loop, not XLA's first-touch latency
    for _ in range(2):
        list(router.stream(prompts[0].tolist(), new_tokens,
                           deadline_s=deadline_s))
    t_start = time.perf_counter()
    for t in workers:
        t.start()

    published = {"v0"}
    cycles = []
    consumed_total = 0
    for k in range(1, n_cycles + 1):
        time.sleep(1.0)  # serve a beat: traffic accumulates
        traffic.rotate()  # seal what the beat logged
        step = loop.step()  # discover + extend (the growing dataset)
        shards = cluster.take()
        # the "trainer": fold the freshly logged records into a new
        # weights version — a convex step from the served params toward
        # a data-derived target, so the published weights demonstrably
        # depend on the live traffic just consumed
        records = []
        for fm in shards:
            records.extend(decode_records(read_manifest(fm)))
        consumed_total += len(records)
        ver = f"live{k}"
        if records:
            seed = sum(int(r["completion"][0]) for r in records if
                       len(r["completion"])) + len(records)
            target = model.init(
                jax.random.PRNGKey(seed % (2**31)),
                jnp.asarray(prompts[:2]),
            )["params"]
            w = 0.1
            new_params = jax.tree.map(
                lambda a, t: _np.asarray((1.0 - w) * a + w * t),
                base_params,
                target,
            )
            out = ctl.publish(new_params, version=ver)
            published.add(ver)
            progress["v"] = ver
            phase["current"] = ver
        else:
            out = "skipped_no_records"
        hist.scrape_registry(fleet.metrics)
        after = loop.step()  # observe the publish: loop lag resets
        cycles.append(
            {
                "cycle": k,
                "version": ver,
                "rollout_outcome": out,
                "discovered": step["discovered"],
                "records_consumed": len(records),
                "data_age_s": round(after["data_age_s"], 3),
                "loop_lag_s": round(after["loop_lag_s"], 3),
            }
        )
    time.sleep(1.0)  # tail: the loop's final version serves
    stop_load.set()
    hung = 0
    for t in workers:
        t.join(timeout=max(120.0, deadline_s + 60.0))
        if t.is_alive():
            hung += 1
    wall_s = time.perf_counter() - t_start
    final_step = loop.step()
    hist.scrape_registry(fleet.metrics)
    slo_verdicts = slo_ev.evaluate()
    router.close()
    traffic.close()

    oks = [v for v in results.values() if v[0] == "ok"]
    errs = [v for v in results.values() if v[0] == "err"]
    sheds = [
        v for v in errs if v[2] in ("FleetOverloaded", "FleetUnavailable")
    ]
    hard_errors = [v for v in errs if v not in sheds]
    latencies = sorted(v[1] for v in oks)
    p99 = (
        latencies[max(0, int(len(latencies) * 0.99) - 1)]
        if latencies
        else float("inf")
    )
    live_versions = {v for v in published if v.startswith("live")}
    early = [v for v in oks if v[4] == "v0"]
    late = [v for v in oks if v[4] == f"live{n_cycles}"]
    early_fresh = sum(1 for v in early if v[2] in live_versions)
    late_fresh = sum(1 for v in late if v[2] in live_versions)
    early_share = early_fresh / len(early) if early else 0.0
    late_share = late_fresh / len(late) if late else 0.0
    dropped = sum(
        livelog_metrics()["dropped"].value(reason=r)
        for r in ("failpoint", "io_error", "closed", "disk_budget")
    )
    stats = loop.stats()
    checks = {
        # the loop's point: the served generation shifts onto weights
        # trained from the live traffic mid-run
        "freshness_shift": late_share >= 0.9 and late_share > early_share,
        "zero_dropped_or_hung": hung == 0 and not hard_errors,
        "zero_log_records_dropped": dropped == 0,
        "all_rollouts_completed": all(
            c["rollout_outcome"] == "completed" for c in cycles
        ),
        "every_cycle_trained_fresh_records": all(
            c["records_consumed"] > 0 for c in cycles
        ),
        "admitted_p99_within_deadline": p99 <= deadline_s,
        "slo_latency_silent": not any(
            v.slo == "fleet_latency" and v.breached for v in slo_verdicts
        ),
        "no_stalls": stats["stalls"] == 0,
        "final_data_age_within_objective": (
            final_step["data_age_s"] <= freshness_objective_s
        ),
    }
    result = {
        "metric": "online_continual_loop",
        "value": float(consumed_total),
        "unit": "records_trained",
        "vs_baseline": 1.0 if all(checks.values()) else 0.0,
        "passed": all(checks.values()),
        "checks": checks,
        "cycles": cycles,
        "requests_ok": len(oks),
        "requests_shed": len(sheds),
        "requests_hard_errors": len(hard_errors),
        "hung_workers": hung,
        "admitted_p99_s": round(p99, 3),
        "deadline_budget_s": deadline_s,
        "fresh_share_early": round(early_share, 3),
        "fresh_share_late": round(late_share, 3),
        "records_trained": consumed_total,
        "log_records_dropped": int(dropped),
        "loop_stats": stats,
        "slo": [v.as_dict() for v in slo_verdicts],
        "rollout_stats": ctl.stats(),
        "wall_s": round(wall_s, 1),
        "replicas": 2,
        "new_tokens": new_tokens,
        **_partial,
    }
    path = os.path.join(
        _results_dir(),
        f"online_{jax.default_backend()}"
        + ("_smoke" if smoke else "")
        + ".json",
    )
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        result["artifact"] = path
    except OSError as e:
        result["artifact_error"] = str(e)
    _emit(result)
    if not all(checks.values()):
        raise SystemExit(
            f"online bench failed acceptance checks: "
            f"{ {k: v for k, v in checks.items() if not v} }"
        )


def _bench_serve_slo(smoke: bool) -> None:
    """``--serve-slo``: the end-to-end trace + SLO burn proof (ISSUE 16).

    A 2-replica in-process fleet behind the health-routing router runs
    two legs against ONE History + SLO evaluator:

    - **clean leg**: requests well inside the latency objective — the
      evaluator must stay silent (no false burn at baseline);
    - **armed leg**: ``fleet.dispatch`` drops the proof request's first
      dispatch (a forced failover hop) while ``engine.submit`` delays
      it past the objective, then a latency failpoint slows the rest of
      the leg — the fleet_latency SLO must fire exactly here, with the
      availability SLO (no sheds) still silent.

    The proof request is traced end-to-end: the committed artifact
    asserts one trace id spans router placement -> failover hop ->
    replica -> engine segments with >= 95% of its wall time attributed
    to named segments, and that the timeline round-trips through
    ``obs.trace_merge``. Artifact:
    ``benchmarks/results/serve_slo_<backend>[_smoke].json``.
    """
    import threading as _threading

    import jax
    import jax.numpy as jnp

    from benchmarks.real_chip import _llama1b_decode_setup
    from tensorflowonspark_tpu.obs import reqtrace, trace_merge
    from tensorflowonspark_tpu.obs.history import History
    from tensorflowonspark_tpu.obs.slo import SLOEvaluator, router_slos
    from tensorflowonspark_tpu.serving import ContinuousBatcher
    from tensorflowonspark_tpu.serving.fleet import ServingFleet
    from tensorflowonspark_tpu.serving.router import FleetRouter
    from tensorflowonspark_tpu.utils import failpoints

    ns = argparse.Namespace(
        batch_size=2 if smoke else 4,
        seq=16 if smoke else 64,
        new_tokens=8 if smoke else 32,
        spec_k=0,
        model_scale="tiny" if smoke else "1b",
        kv_quantize=False,
    )
    if smoke:
        _partial["smoke"] = True
    b, new_tokens, cfg, model, prompts = _llama1b_decode_setup(ns)
    params = jax.tree.map(
        jax.device_put,
        model.init(
            jax.random.PRNGKey(0), jnp.asarray(prompts[:2])
        )["params"],
    )
    # retain EVERY finished trace: the proof below reads the ring back
    ring = reqtrace.install(capacity=64, sample_every=1)

    def factory():
        return ContinuousBatcher(
            model,
            params,
            slots=b,
            prompt_widths=(prompts.shape[1],),
        )

    fleet = ServingFleet(
        factory=factory,
        replicas=2,
        probe_interval=0.5,
        warmup=False,
        drain_timeout=10.0,
    )
    router = FleetRouter(fleet)
    objective_s = 1.0  # a bucket edge: fraction_le needs no interpolation
    delay_s = 1.6  # past the objective, inside the next bucket
    history = History(source="bench.serve_slo")
    ev = SLOEvaluator(
        router_slos(
            latency_objective_s=objective_s,
            latency_budget=0.1,
            shed_budget=0.02,
            fast_burn=5.0,  # breach at >= 50% of requests slow (fast)
            slow_burn=2.5,  # and >= 25% over the slow window
        ),
        history,
        registry=fleet.metrics,
    )
    clean_n, armed_n = (4, 5) if smoke else (8, 10)

    def fire(count: int, tag: int, trace: str | None = None) -> None:
        errors: list = []

        def one(i):
            try:
                router.submit(
                    prompts[(tag + i) % len(prompts)].tolist(),
                    new_tokens,
                    **({"trace": trace} if trace and i == 0 else {}),
                )
            except BaseException as e:  # noqa: BLE001 - ferried
                errors.append(e)

        threads = [
            _threading.Thread(target=one, args=(i,))
            for i in range(count)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    try:
        fire(2 * b, tag=0)  # compile/warm both replicas
        # consume the warmup's registry window so the evaluator's first
        # scrape delta covers exactly the clean leg, not the compiles
        fleet.metrics.window()

        fire(clean_n, tag=100)
        history.scrape_registry(fleet.metrics)
        clean_verdicts = ev.evaluate()

        # -- armed leg: proof request takes a forced failover hop AND
        # the latency delay; the rest of the leg is just slow ---------
        proof_tid = reqtrace.mint(route="bench.proof")
        t_proof = time.perf_counter()
        failpoints.arm("fleet.dispatch", "drop", count=1)
        failpoints.arm("engine.submit", "delay", delay_s=delay_s, count=1)
        fire(1, tag=200, trace=proof_tid)
        proof_wall = time.perf_counter() - t_proof
        reqtrace.finish(proof_tid, outcome="ok")
        failpoints.arm(
            "fleet.dispatch", "delay", delay_s=delay_s, count=armed_n
        )
        fire(armed_n - 1, tag=300)
        failpoints.disarm_all()
        history.scrape_registry(fleet.metrics)
        armed_verdicts = ev.evaluate()
        # one more scrape so the breach counter + burn gauges the
        # evaluation just wrote are themselves in the windowed history
        history.scrape_registry(fleet.metrics)
    finally:
        failpoints.disarm_all()
        router.close()

    # -- the trace proof ----------------------------------------------
    attribution = ring.attribution(proof_tid) or {}
    record = reqtrace.get_record(proof_tid) or {}
    seg_names = {s["name"] for s in record.get("segments", ())}
    ev_names = {e["name"] for e in record.get("events", ())}
    merged_events = 0
    trace_path = os.path.join(
        _results_dir(), "serve_slo_proof_trace.json"
    )
    chrome = reqtrace.to_chrome(proof_tid)
    if chrome is not None:
        os.makedirs(os.path.dirname(trace_path), exist_ok=True)
        with open(trace_path, "w", encoding="utf-8") as f:
            json.dump(chrome, f)
        merged_events = len(
            trace_merge.merge_traces([trace_path]).get("traceEvents") or []
        )

    clean_breached = sorted(v.slo for v in clean_verdicts if v.breached)
    armed_breached = sorted(v.slo for v in armed_verdicts if v.breached)
    checks = {
        "clean_leg_silent": not clean_breached,
        "armed_leg_fires_latency_slo": armed_breached == ["fleet_latency"],
        "breach_is_rising_edge_once": history.delta(
            "slo_breaches_total", window_s=None
        ) == 1.0,
        "proof_trace_retained": proof_tid in ring.ids(),
        "proof_spans_router_to_engine": (
            "router.submit" in seg_names
            and any(n.startswith("engine.") for n in seg_names)
            and "router.failover" in ev_names
        ),
        "proof_attribution_ge_95pct": (
            attribution.get("covered_fraction", 0.0) >= 0.95
        ),
        "proof_slower_than_objective": proof_wall >= objective_s,
        "timeline_merges": merged_events > 0,
    }
    result = {
        "metric": "serve_slo_burn_gate",
        "value": 1.0 if all(checks.values()) else 0.0,
        "unit": "pass",
        "vs_baseline": 1.0 if all(checks.values()) else 0.0,
        "passed": all(checks.values()),
        "checks": checks,
        "objective_s": objective_s,
        "armed_delay_s": delay_s,
        "requests_clean": clean_n,
        "requests_armed": armed_n,
        "proof_trace_id": proof_tid,
        "proof_wall_s": round(proof_wall, 3),
        "attribution": attribution,
        "slo_clean": [v.as_dict() for v in clean_verdicts],
        "slo_armed": [v.as_dict() for v in armed_verdicts],
        "reqtrace": ring.stats(),
        "history": history.to_artifact(
            names=(
                "router_request_seconds",
                "router_requests_total",
                "router_shed_total",
                "slo_burn_rate",
                "slo_breaches_total",
            )
        ),
        "merged_trace_events": merged_events,
        **_partial,
    }
    path = os.path.join(
        _results_dir(),
        f"serve_slo_{jax.default_backend()}"
        + ("_smoke" if smoke else "")
        + ".json",
    )
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        result["artifact"] = path
    except OSError as e:
        result["artifact_error"] = str(e)
    _emit(result)
    if not all(checks.values()):
        raise SystemExit(
            f"serve-slo bench failed acceptance checks: "
            f"{ {k: v for k, v in checks.items() if not v} }"
        )


def _relay_dial_probe(timeout: float = 180.0) -> tuple[bool, str]:
    """One short-lived subprocess dial: (ok, detail). ok=True iff jax
    backend init completes. Distinguishes a HEALTHY relay from a
    listening-but-WEDGED session (ports stay open while every dial hangs
    in epoll — the state a killed/timed-out dialer leaves behind;
    observed in the round-3 and round-4 windows). Sequential clean dials
    are safe — the relay-window scripts run one interpreter after
    another this way; the probe exits before the main process dials.

    Why the probe is not itself a second concurrent dialer: verified
    against the sitecustomize hook's source (round 5) — ``register()``
    only REGISTERS a lazy PJRT plugin factory
    (``axon/register/pjrt.py`` ``_do_jax_registration`` →
    ``xla_bridge.register_plugin``; its provider comment states all
    provider modes "defer the :8082 session to first stateful RPC;
    jax.devices() goes via :8083 stateless"). So this parent
    interpreter holds NO relay connection until its own first
    ``jax.devices()``, which main() reaches only after the probe child
    has exited. Set ``BENCH_DIAL_PROBE=0`` to skip the probe anyway
    (falls back to treating listening ports as healthy).

    On timeout the child gets SIGTERM + a grace period (not SIGKILL) so
    a merely-slow dialer can close its connection cleanly; if the
    session was healthy-but-slow this minimizes the chance the probe
    itself leaves the wedge it is testing for. Healthy init completes in
    seconds (round-4 window: full bench incl. compile in ~2 min), so the
    timeout has a wide margin, and the probe's cost fits the ~300s of
    watchdog budget the benchmark run leaves unused.
    """
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )
    try:
        _, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
        return False, f"probe dial hung for {timeout:.0f}s"
    if proc.returncode == 0:
        return True, ""
    tail = (err or b"").decode(errors="replace").strip().splitlines()[-3:]
    return False, (
        f"probe dial exited rc={proc.returncode}: " + " | ".join(tail)
    )


def _setup_trace(backend: str) -> str | None:
    """Point real_chip's post-timing profile hook at a scratch dir;
    returns the dir, or None (with a stderr warning) when tracing is
    unavailable on this backend."""
    import sys
    import tempfile

    if backend != "tpu" and not os.environ.get("BENCH_TRACE_CPU"):
        print(
            f"bench: --trace is a no-op on the {backend!r} backend "
            "(no device timeline to attribute); set BENCH_TRACE_CPU=1 "
            "to capture host lanes anyway",
            file=sys.stderr,
            flush=True,
        )
        return None
    from benchmarks import real_chip

    trace_dir = tempfile.mkdtemp(prefix="bench_trace_")
    real_chip._PROFILE_DIR = trace_dir
    return trace_dir


def _emit_trace_report(
    trace_dir: str, backend: str, smoke: bool, name: str = "llama1b"
) -> None:
    """Distill the captured trace into a committed artifact; failures
    annotate the JSON line rather than sinking the scored run. A smoke
    run writes a DISTINCT filename so it can never clobber the evidence
    artifact of the last real scored run. ``name`` prefixes the
    artifact (``llama1b`` for the MFU bench, ``serve`` for the serving
    bench) so each bench owns its own evidence file."""
    repo = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(
        repo,
        _results_dir(),
        f"{name}_{backend}{'_smoke' if smoke else ''}_trace_report.json",
    )
    try:
        from tensorflowonspark_tpu.obs import trace_report

        report = trace_report.write_report(trace_dir, out)
        att = report["attribution"]
        _partial["trace_report"] = (
            os.path.relpath(out, repo)
            if not os.environ.get("TFOS_BENCH_RESULTS_DIR")
            else out
        )
        _partial["trace_mxu_fraction"] = att["mxu_fraction"]
        _partial["trace_device_ms"] = round(
            att["device_total_us"] / 1e3, 1
        )
        _partial["trace_host_ms"] = round(att["host_total_us"] / 1e3, 1)
    except Exception as e:  # noqa: BLE001 - the headline must still print
        _partial["trace_error"] = f"{type(e).__name__}: {e}"


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(prog="bench")
    ap.add_argument(
        "--trace",
        dest="trace",
        action="store_true",
        default=None,
        help="capture a jax.profiler trace after the timed loop and "
        "commit a benchmarks/results/*_trace_report.json attribution "
        "artifact (default: on; a no-op warning on CPU backends)",
    )
    ap.add_argument(
        "--no-trace", dest="trace", action="store_false",
        help="skip the trace capture",
    )
    ap.add_argument(
        "--serve-fleet",
        action="store_true",
        help="measure serving-fleet saturation scaling: replicas=1 vs "
        "2 in-process continuous engines behind the health-routing "
        "FleetRouter, reporting the throughput ratio plus "
        "shed/failover counts, committed to "
        "benchmarks/results/serve_fleet_*.json (BENCH_SMOKE=1 for the "
        "tiny model)",
    )
    ap.add_argument(
        "--cache",
        action="store_true",
        help="prove the disaggregated read-through cache tier: a "
        "2-replica fleet under a shared-prefix workload with the "
        "fleet-global prefix L2 on vs off (cold-replica first-request "
        "speedup + cross-replica L2 hits > 0), plus two concurrent "
        "columnar readers sharing one CacheTier (backing reads ~1x "
        "the dataset, not per-reader), committed to "
        "benchmarks/results/cache_*.json (BENCH_SMOKE=1 for the tiny "
        "model)",
    )
    ap.add_argument(
        "--autotune",
        action="store_true",
        help="prove feedback-controlled knob recovery: the mnist feed "
        "pipeline at prefetch depth 1 and a continuous-batching fleet "
        "at decode_block=1/pipeline_depth=1 each hand their knobs to "
        "an autotune Controller, which must recover >= 90% of the "
        "hand-tuned throughput (with every move a flight-recorder "
        "event and at least one audited revert), committed to "
        "benchmarks/results/autotune_*.json (BENCH_SMOKE=1 for the "
        "tiny model)",
    )
    ap.add_argument(
        "--zero",
        nargs="?",
        const="on,off",
        default=None,
        metavar="on,off",
        help="run the cross-replica sharded weight-update A/B instead "
        "of the headline bench: the llama train step at fixed batch on "
        "a pure data-parallel mesh with zero_sharding on vs off, "
        "committing benchmarks/results/zero_weight_update*.json "
        "(step_time_ms, MFU on TPU, optimizer-span ms per leg; "
        "BENCH_SMOKE=1 for the tiny model + params byte-identity hash)",
    )
    ap.add_argument(
        "--rollout",
        action="store_true",
        help="chaos-prove zero-downtime weight rollout: a 2-replica "
        "fleet serves sustained streaming load while K successive "
        "versions hot-swap through the RolloutController; the "
        "committed benchmarks/results/rollout_*.json asserts zero "
        "dropped/hung requests, admitted p99 within the deadline "
        "budget, and coherent per-completion version stamps "
        "(BENCH_SMOKE=1 for the tiny model)",
    )
    ap.add_argument(
        "--online",
        action="store_true",
        help="close the continual-training loop on live traffic: a "
        "2-replica fleet's completions feed a crash-safe TrafficLog, "
        "the online loop discovers sealed segments and a trainer folds "
        "them into new weights versions that hot-swap mid-run; the "
        "committed benchmarks/results/online_*.json asserts the served "
        "generation shifts onto live-trained weights with zero dropped "
        "requests or log records and p99 within the SLO budget "
        "(BENCH_SMOKE=1 for the tiny model)",
    )
    ap.add_argument(
        "--serve-slo",
        action="store_true",
        help="end-to-end trace + SLO burn proof: a 2-replica fleet "
        "runs a clean leg then a failpoint-armed leg (one forced "
        "failover hop + a latency delay) against one History-backed "
        "SLO evaluator; the committed benchmarks/results/serve_slo_*"
        ".json asserts the latency SLO fires exactly on the armed leg "
        "and that the proof request's trace attributes >= 95% of its "
        "wall time to named router/engine segments (BENCH_SMOKE=1 for "
        "the tiny model)",
    )
    ap.add_argument(
        "--serve",
        action="store_true",
        help="measure the serving engine tax instead of training MFU: "
        "continuous-engine tokens/sec (pipeline_depth 1 and 2) vs raw "
        "single-stream generate on the same params, plus a committed "
        "benchmarks/results/serve_*_trace_report.json of the engine's "
        "host-side phase residual (BENCH_SMOKE=1 for the tiny model)",
    )
    args = ap.parse_args(argv)
    threading.Thread(target=_watchdog, daemon=True).start()

    # Fail fast and diagnosably when the TPU relay is down or wedged: in
    # either state the first backend touch (jax.devices()) blocks forever
    # in epoll and the only output would be the watchdog's opaque
    # "incomplete" 510s later. Pure-CPU images (no relay marker) proceed —
    # there is no backend that can wedge there. BENCH_ALLOW_CPU=1
    # overrides for debugging on a relay-equipped image without touching
    # the chip.
    if os.path.exists(RELAY_MARKER) and not os.environ.get("BENCH_ALLOW_CPU"):
        ports = _relay_ports_listening()
        _partial["relay_ports_listening"] = ports
        if ports == 0:
            _emit(
                {
                    "metric": "llama1b_train_mfu",
                    "value": 0,
                    "unit": "%",
                    "vs_baseline": 0.0,
                    "error": "relay_unreachable: no TPU relay ports "
                    f"listening on 127.0.0.1:{RELAY_PORTS.start}-"
                    f"{RELAY_PORTS.stop - 1}; backend init would wedge. "
                    + BANKED_POINTER,
                    **_partial,
                }
            )
            raise SystemExit(3)
        if os.environ.get("BENCH_DIAL_PROBE") == "0":
            ok, detail = True, ""
        else:
            ok, detail = _relay_dial_probe()
        if not ok:
            _emit(
                {
                    "metric": "llama1b_train_mfu",
                    "value": 0,
                    "unit": "%",
                    "vs_baseline": 0.0,
                    "error": f"relay_wedged: ports are listening but the "
                    f"dial probe failed ({detail}) — typically a "
                    "previously killed/timed-out dialer's grant that has "
                    "not expired, so backend init would block forever. "
                    + BANKED_POINTER,
                    **_partial,
                }
            )
            raise SystemExit(3)

    import jax

    _partial["backend"] = jax.default_backend()
    _partial["chips"] = len(jax.devices())

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    if args.zero:
        legs = [leg.strip() for leg in args.zero.split(",") if leg.strip()]
        bad = [leg for leg in legs if leg not in ("on", "off")]
        if bad or not legs:
            ap.error(f"--zero legs must be 'on'/'off', got {bad or args.zero!r}")
        _bench_zero_ab(smoke, legs)
        return
    if args.cache:
        _bench_cache(smoke)
        return
    if args.autotune:
        _bench_autotune(smoke)
        return
    if args.serve_fleet:
        _bench_serve_fleet(smoke)
        return
    if args.rollout:
        _bench_rollout(smoke)
        return
    if args.online:
        _bench_online(smoke)
        return
    if args.serve_slo:
        _bench_serve_slo(smoke)
        return
    if args.serve:
        # the serving bench commits its own span-based trace report;
        # the jax.profiler MFU trace path doesn't apply here
        _bench_serve(smoke)
        return
    trace_dir = None
    # default-on applies to REAL runs only; a smoke run traces just when
    # asked (its tiny-model attribution is not scoring evidence)
    if args.trace is True or (args.trace is None and not smoke):
        trace_dir = _setup_trace(jax.default_backend())
    _bench_llama(smoke=smoke)  # headline first; a late wedge still reports
    if trace_dir is not None:
        _emit_trace_report(trace_dir, jax.default_backend(), smoke)
    _bench_mnist_feed(steps=5 if smoke else 40)

    mfu = _partial.pop("mfu_pct", None)
    _emit(
        {
            "metric": "llama1b_train_mfu",
            "value": round(mfu, 1) if mfu is not None else 0,
            "unit": "%",
            "vs_baseline": (
                round(mfu / (MFU_TARGET * 100), 3) if mfu is not None else 0.0
            ),
            **_partial,
        }
    )


if __name__ == "__main__":
    main()
