"""Benchmark entry: prints ONE JSON line with the headline metric.

Round-1 headline: MNIST CNN training examples/sec through the framework's
own data plane (producer thread -> manager queue -> DataFeed -> shard_batch
-> jitted train step on the mesh), i.e. the BASELINE.md "MNIST
InputMode.SPARK" config measured end-to-end, not a bare matmul loop.

Runs single-process on whatever backend jax gives (the real TPU chip under
the driver; CPU elsewhere). A watchdog prints a failure JSON line and
exits if backend init wedges (this environment's TPU relay is fragile).
"""

from __future__ import annotations

import json
import os
import threading
import time

WATCHDOG_SECS = 480  # fire before any outer ~600s kill, so the failure
# JSON line still reaches the driver when backend init wedges
_result_printed = threading.Event()


def _watchdog():
    if not _result_printed.wait(WATCHDOG_SECS):
        print(
            json.dumps(
                {
                    "metric": "mnist_train_examples_per_sec",
                    "value": 0,
                    "unit": "examples/sec",
                    "vs_baseline": 0.0,
                    "error": f"watchdog: no result within {WATCHDOG_SECS}s "
                    "(backend init wedged?)",
                }
            ),
            flush=True,
        )
        os._exit(2)


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True).start()

    import secrets

    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.cluster import manager as tf_manager
    from tensorflowonspark_tpu.cluster.marker import EndOfFeed
    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.feed.datafeed import DataFeed
    from tensorflowonspark_tpu.models import mnist

    backend = jax.default_backend()
    mesh = make_mesh({"data": len(jax.devices())})

    batch_size = 1024
    warmup_steps, bench_steps = 10, 50
    total_steps = warmup_steps + bench_steps

    model = mnist.CNN()
    rng = np.random.default_rng(0)
    images = rng.random((batch_size, 28, 28, 1), dtype=np.float32)
    labels = rng.integers(0, 10, size=batch_size).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), images[:2])["params"]
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    step = build_train_step(mnist.loss_fn(model.apply), tx, mesh)

    # The framework's push data plane, in-process: producer thread fills the
    # node manager queue with record chunks; DataFeed consumes.
    mgr = tf_manager.start(secrets.token_bytes(8), mode="local", maxsize=64)

    def produce():
        q = mgr.get_queue("input")
        for _ in range(total_steps):
            q.put(list(zip(images, labels)))
        q.put(EndOfFeed())

    threading.Thread(target=produce, daemon=True).start()
    feed = DataFeed(mgr, input_mapping={"image": "image", "label": "label"})

    def next_device_batch():
        cols = feed.next_batch(batch_size)
        return shard_batch(
            mesh, {"image": cols["image"], "label": cols["label"]}
        )

    # warmup (includes compile)
    for _ in range(warmup_steps):
        state, loss = step(state, next_device_batch())
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(bench_steps):
        state, loss = step(state, next_device_batch())
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    examples_per_sec = bench_steps * batch_size / dt
    step_ms = dt / bench_steps * 1000
    n_chips = len(jax.devices())

    # The reference publishes no absolute numbers (BASELINE.md): baseline is
    # self-defined as this round's first TPU measurement, recorded below
    # once known. vs_baseline = value / baseline.
    baseline = 40000.0  # examples/sec, provisional round-1 target (TPU)
    print(
        json.dumps(
            {
                "metric": "mnist_train_examples_per_sec",
                "value": round(examples_per_sec, 1),
                "unit": "examples/sec",
                "vs_baseline": round(examples_per_sec / baseline, 3),
                "step_time_ms": round(step_ms, 2),
                "batch_size": batch_size,
                "backend": backend,
                "chips": n_chips,
                "per_chip": round(examples_per_sec / n_chips, 1),
                "final_loss": float(loss),
            }
        ),
        flush=True,
    )
    _result_printed.set()
    mgr.stop()


if __name__ == "__main__":
    main()
