"""Pipeline parallelism — GPipe microbatch schedule over the ``pipe`` axis.

Layers are grouped into ``n = mesh.shape['pipe']`` stages; each device in
the pipe ring owns one stage's parameters (sharded on the stacked stage
dimension, so optimizer state shards with them for free). Microbatches
stream through the ring: every tick, each device applies its stage to its
current activation and passes the result to the next stage with
``jax.lax.ppermute``. After ``num_micro + n - 1`` ticks all microbatches
have exited the last stage (the standard GPipe bubble:
``(n-1)/(num_micro+n-1)`` idle fraction — amortised away by more
microbatches).

The whole schedule is a ``lax.scan`` — one traced tick, compiler-friendly —
and every op is differentiable, so ``jax.grad`` through a pipelined forward
yields the reverse schedule automatically. Each tick is wrapped in
``jax.checkpoint`` so backward rematerialises per-tick activations rather
than storing all of them.

The reference has no pipeline parallelism (SURVEY.md §2.3); this is part of
the rebuild's beyond-parity parallelism layer.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowonspark_tpu.utils import compat


def _gpipe_local(
    stage_params: Any,
    microbatches: jax.Array,
    *,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    axis_name: str,
):
    """Per-device GPipe schedule; call under ``shard_map``.

    ``stage_params``: this stage's parameter pytree (leading stacked-stage
    dim of size 1, squeezed here). ``microbatches``: (num_micro, mb, ...)
    replicated along the pipe axis. Returns (num_micro, mb, ...) outputs,
    summed-broadcast from the last stage so ``out_specs`` can replicate.
    """
    n = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    params = jax.tree.map(lambda x: x[0], stage_params)
    num_micro = microbatches.shape[0]
    ticks = num_micro + n - 1

    # No wraparound: the last stage's output leaves the ring.
    perm = [(j, j + 1) for j in range(n - 1)]

    act0 = jnp.zeros_like(microbatches[0])

    @jax.checkpoint
    def tick(carry, t):
        act = carry
        mb_idx = jnp.clip(t, 0, num_micro - 1)
        inp = jnp.where(idx == 0, microbatches[mb_idx], act)
        out = stage_fn(params, inp)
        nxt = lax.ppermute(out, axis_name, perm)
        return nxt, out

    _, outs = lax.scan(tick, act0, jnp.arange(ticks, dtype=jnp.int32))
    # Valid last-stage outputs are ticks [n-1, n-1+num_micro).
    outs = lax.dynamic_slice_in_dim(outs, n - 1, num_micro, axis=0)
    # Broadcast from the last stage to the whole pipe ring (other stages
    # contribute garbage -> zero them and psum).
    outs = jnp.where(idx == n - 1, outs, 0)
    return lax.psum(outs, axis_name)


def gpipe(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    mesh: Mesh,
    *,
    pipe_axis: str = "pipe",
    batch_axes: tuple[str, ...] = ("data", "fsdp"),
) -> jax.Array:
    """Run a stage-stacked network over microbatches, pipelined on the mesh.

    - ``stage_fn(params, x) -> y``: one pipeline stage (may itself contain
      many layers, e.g. a ``lax.scan`` over the layers it owns).
    - ``stage_params``: pytree whose leaves have leading dim ``n_stages``
      (== mesh.shape[pipe_axis]); sharded on ``pipe`` here.
    - ``microbatches``: (num_micro, mb_size, ...) global array; the
      microbatch *content* dims may additionally be sharded on
      ``batch_axes`` (dp) / ``model`` inside ``stage_fn``'s own ops.

    Returns (num_micro, mb_size, ...) outputs of the final stage.
    """
    param_specs = jax.tree.map(  # lint: layout-ok: stage placement over the caller-chosen pipe axis; shard_map operand spec, not a model layout
        lambda _: P(pipe_axis), stage_params
    )
    mb_spec = P(None, batch_axes)  # lint: layout-ok: microbatch spec over caller-chosen dp axes; shard_map operand spec, not a model layout
    fn = compat.shard_map(
        functools.partial(
            _gpipe_local, stage_fn=stage_fn, axis_name=pipe_axis
        ),
        mesh=mesh,
        in_specs=(param_specs, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )
    return fn(stage_params, microbatches)


def stack_stages(params_per_stage: list[Any]) -> Any:
    """Stack per-stage param pytrees into one pytree with a leading
    stage dim (the layout ``gpipe`` shards on the ``pipe`` axis)."""
    return jax.tree.map(
        lambda *leaves: jnp.stack(leaves), *params_per_stage
    )
