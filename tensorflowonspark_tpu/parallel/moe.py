"""Mixture-of-experts with expert parallelism over the ``expert`` axis.

GShard-style top-k routing with a fixed per-expert capacity so every shape
is static under ``jit``: tokens are scattered into an ``(experts,
capacity, d)`` buffer with one einsum against a dispatch mask, the expert
FFN bank runs as a single batched matmul over the stacked expert dimension
(one big MXU-friendly contraction, not a Python loop over experts), and a
second einsum with the combine weights gathers results back to token order.

Expert parallelism is pure sharding: the stacked expert dim of the FFN
params and of the dispatched buffer carries ``PartitionSpec('expert')``,
and XLA lowers the token exchange implied by resharding (tokens sharded on
batch → buffers sharded on expert) to ``all_to_all`` over ICI. There is no
hand-written dispatch collective to maintain.

Load balancing is the standard Switch/GShard auxiliary loss
(``aux_load_balancing_loss``): mean fraction of tokens routed to each
expert × mean router probability per expert, × num_experts.

The reference has no MoE/expert parallelism (SURVEY.md §2.3) — this is
beyond-parity capability.
"""

from __future__ import annotations

import dataclasses
import math

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowonspark_tpu.compute import layout


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    hidden_size: int = 128
    intermediate_size: int = 256
    dtype: jnp.dtype = jnp.bfloat16
    router_aux_weight: float = 0.01


def _capacity(num_tokens: int, cfg: MoEConfig) -> int:
    # ceil, per GShard/Switch: capacity_factor=1.0 must mean "exactly
    # enough slots under perfect balance", never fewer.
    cap = math.ceil(
        num_tokens * cfg.top_k * cfg.capacity_factor / cfg.num_experts
    )
    return max(cap, cfg.top_k)


def top_k_routing(
    router_logits: jax.Array, cfg: MoEConfig, num_tokens: int
):
    """Build dispatch mask and combine weights from router logits.

    router_logits (T, E) → dispatch (T, E, C) bool-ish float, combine
    (T, E, C) float32, aux_loss scalar. Tokens over an expert's capacity
    are dropped (standard fixed-capacity semantics); priority is token
    order, matching GShard/Switch.
    """
    t, e = router_logits.shape
    c = _capacity(num_tokens, cfg)
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    # top-k expert choices per token
    gate_vals, expert_idx = jax.lax.top_k(probs, cfg.top_k)  # (T, k)
    # normalise the selected gates to sum to 1 per token
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    # position of each (token, choice) within its expert's capacity buffer:
    # cumulative count of earlier assignments to the same expert, counting
    # across choices-major-then-token order.
    choice_mask = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)  # (T,k,E)
    flat_mask = choice_mask.reshape(t * cfg.top_k, e)  # choices flattened
    pos_in_expert = jnp.cumsum(flat_mask, axis=0) - flat_mask  # (T*k, E)
    pos = jnp.sum(pos_in_expert * flat_mask, axis=-1).reshape(t, cfg.top_k)
    keep = pos < c  # over-capacity assignments dropped

    gates = gate_vals * keep
    # scatter into (T, E, C)
    combine = jnp.einsum(
        "tk,tke,tkc->tec",
        gates,
        jax.nn.one_hot(expert_idx, e, dtype=jnp.float32),
        jax.nn.one_hot(jnp.where(keep, pos, 0), c, dtype=jnp.float32)
        * keep[..., None],
    )
    dispatch = (combine > 0).astype(jnp.float32)

    # Switch-style load-balancing aux loss on the top-1 assignment.
    top1 = jax.nn.one_hot(expert_idx[:, 0], e, dtype=jnp.float32)
    frac_tokens = jnp.mean(top1, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Drop-in MLP block: top-k routed bank of SwiGLU experts.

    Call with x (B, S, d); returns (B, S, d). Stores the aux loss with
    ``self.sow('losses', 'router_aux', ...)`` — collect via
    ``mutable=['losses']`` or read it from a surrounding train step.
    """

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, s, d = x.shape
        t = b * s
        tokens = x.reshape(t, d)

        router = nn.Dense(
            cfg.num_experts, use_bias=False, dtype=jnp.float32,
            name="router", kernel_init=nn.initializers.normal(0.02),
        )
        logits = router(tokens.astype(jnp.float32))
        dispatch, combine, aux = top_k_routing(logits, cfg, t)
        self.sow("losses", "router_aux", cfg.router_aux_weight * aux)

        init = nn.initializers.normal(0.02)
        e, f = cfg.num_experts, cfg.intermediate_size
        w_gate = self.param("w_gate", init, (e, d, f))
        w_up = self.param("w_up", init, (e, d, f))
        w_down = self.param("w_down", init, (e, f, d))

        # (T,E,C) x (T,d) -> (E,C,d): the resharding T-sharded -> E-sharded
        # is the all_to_all dispatch.
        xs = jnp.einsum(
            "tec,td->ecd", dispatch.astype(cfg.dtype), tokens.astype(cfg.dtype)
        )
        gate = jnp.einsum("ecd,edf->ecf", xs, w_gate.astype(cfg.dtype))
        up = jnp.einsum("ecd,edf->ecf", xs, w_up.astype(cfg.dtype))
        ys = jnp.einsum(
            "ecf,efd->ecd", nn.silu(gate) * up, w_down.astype(cfg.dtype)
        )
        out = jnp.einsum(
            "tec,ecd->td", combine.astype(cfg.dtype), ys
        )
        return out.reshape(b, s, d).astype(x.dtype)


def moe_expert_bank_spec(param_name: str) -> P:
    """PartitionSpec for one 3-dim expert bank leaf: stacked dim on
    ``expert``, FFN hidden on ``model``, the remaining dim on ``fsdp``
    — the declarative 'moe' table in
    :mod:`tensorflowonspark_tpu.compute.layout` (the llama table
    carries the same rules, pinned equal by tests/test_layout.py)."""
    return layout.expert_bank_spec(param_name)


def moe_param_shardings(params, mesh: Mesh):
    """Sharding rules for an MoEMLP param tree: expert banks per
    :func:`moe_expert_bank_spec`; the router is replicated."""
    return layout.param_shardings(params, mesh, "moe")
