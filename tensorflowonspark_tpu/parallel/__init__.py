"""Parallelism layer: sequence/context, tensor, pipeline, expert.

The reference had only data parallelism (SURVEY.md §2.3 — PS and
MultiWorkerMirroredStrategy, both DP). This package is where the rebuild
goes past parity: every strategy is expressed as shardings + XLA
collectives over the project mesh axes (``data``, ``fsdp``, ``model``,
``seq`` — :mod:`tensorflowonspark_tpu.compute.mesh`), so strategies
compose by construction instead of by glue code.

- :mod:`.ring_attention` — sequence/context parallelism: blockwise
  attention with K/V blocks rotated around the ``seq`` axis ring via
  ``ppermute`` (long-context training; SURVEY.md §5.7).
- :mod:`.pipeline` — pipeline parallelism: stage-sharded layer stacks,
  microbatches streamed with collective permutes.
- :mod:`.moe` — mixture-of-experts with expert parallelism via
  ``all_to_all`` dispatch/combine.
- :mod:`.context` — ambient mesh plumbing so model code can reach the
  mesh without threading it through every module attribute.
"""

from tensorflowonspark_tpu.parallel.context import (  # noqa: F401
    current_mesh,
    use_mesh,
)
from tensorflowonspark_tpu.parallel.moe import (  # noqa: F401
    MoEConfig,
    MoEMLP,
    moe_param_shardings,
)
from tensorflowonspark_tpu.parallel.pipeline import (  # noqa: F401
    gpipe,
    stack_stages,
)
from tensorflowonspark_tpu.parallel.ring_attention import (  # noqa: F401
    mesh_ring_attention,
    ring_attention,
)
from tensorflowonspark_tpu.parallel.ulysses import (  # noqa: F401
    mesh_ulysses_attention,
)

__all__ = [
    "current_mesh",
    "use_mesh",
    "ring_attention",
    "mesh_ring_attention",
    "mesh_ulysses_attention",
    "gpipe",
    "stack_stages",
    "MoEConfig",
    "MoEMLP",
    "moe_param_shardings",
]
