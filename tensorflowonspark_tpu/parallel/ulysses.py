"""Ulysses-style sequence parallelism — all-to-all over the ``seq`` axis.

The second sequence-parallel strategy beside ring attention
(:mod:`~tensorflowonspark_tpu.parallel.ring_attention`): instead of
rotating K/V blocks around a ring, one ``lax.all_to_all`` reshards
activations from sequence-sharded to *head*-sharded, each device runs
ordinary full-sequence attention over its head subset, and a second
all-to-all reshards back. Two collectives total (vs n-1 permutes), at the
cost of requiring heads divisible by the seq-axis size — the classic
DeepSpeed-Ulysses trade: better for moderate sequence lengths with many
heads, while the ring wins when S_local is the memory constraint.

The reference had neither strategy (SURVEY.md §5.7).

Composition: attention is head-independent, so after the first all-to-all
each device holds FULL sequences for Hq/n heads and any single-device
attention implementation applies — including the Pallas flash kernel on
TPU (``impl`` passthrough), which the ring formulation cannot use without
reworking its online-softmax merge.
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowonspark_tpu.utils import compat


def _ulysses_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    segment_ids: jax.Array | None = None,
    *,
    axis_name: str,
    causal: bool,
    scale: float | None,
    impl: str,
    window: int | None = None,
):
    """Per-device body; call under ``shard_map``.

    Shards: q (B, S_loc, Hq, D), k/v (B, S_loc, Hkv, D), segment_ids
    (B, S_loc). Heads must be divisible by the axis size (enforced by
    the caller).
    """
    from tensorflowonspark_tpu.ops.attention import dot_product_attention

    # seq-sharded -> head-sharded: split the head axis across devices,
    # concatenate the sequence axis. (B, S_loc, H, D) -> (B, S, H/n, D).
    def to_heads(x):
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    seg_full = None
    if segment_ids is not None:
        # After the reshard each device attends over the FULL sequence
        # (for its head subset), so it needs the full segment-id row —
        # an all-gather of a (B, S_loc) int32 array, negligible next to
        # the activation all-to-alls.
        seg_full = lax.all_gather(
            segment_ids, axis_name, axis=1, tiled=True
        )
    # impl='auto' stays correct here: the dispatcher detects the
    # enclosing shard_map (nonempty axis env), skips its mesh route, and
    # resolves via _local_auto_impl — flash on TPU when shapes allow,
    # exactly because these operands are shard-local.
    out = dot_product_attention(
        qh, kh, vh, causal=causal, scale=scale, impl=impl,
        segment_ids=seg_full, window=window,
    )
    # head-sharded -> seq-sharded: the inverse resharding.
    return lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def mesh_ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: float | None = None,
    seq_axis: str = "seq",
    impl: str = "auto",
    segment_ids: jax.Array | None = None,
    window: int | None = None,
) -> jax.Array:
    """Global-view Ulysses attention: shard_map over the mesh ``seq`` axis.

    Inputs are global arrays (B, S, H, D); batch shards over
    ``(data, fsdp)``, sequence over ``seq``, heads over ``model`` (TP
    composes as usual). Requires S and *both* head counts divisible by the
    seq-axis size. ``segment_ids`` (B, S) masks cross-segment attention
    for packed sequences.
    """
    n = mesh.shape.get(seq_axis, 1)
    tp = mesh.shape.get("model", 1)
    hq, hk = q.shape[2], k.shape[2]
    # Heads are already split over 'model' by the in_specs; what each
    # device all-to-alls must still divide by the seq-axis size.
    if hq % (tp * n) or hk % (tp * n):
        raise ValueError(
            f"ulysses needs q heads ({hq}) and kv heads ({hk}) divisible "
            f"by model x {seq_axis} ({tp} x {n}); use ring attention for "
            "head-poor configs"
        )
    from tensorflowonspark_tpu.parallel.context import sp_specs_and_args

    spec = P(("data", "fsdp"), seq_axis, "model", None)  # lint: layout-ok: SP operand spec over the caller-chosen seq axis; shard_map plumbing, not a model layout
    body = functools.partial(
        _ulysses_local,
        axis_name=seq_axis,
        causal=causal,
        scale=scale,
        impl=impl,
        window=window,
    )
    in_specs, args = sp_specs_and_args(spec, q, k, v, segment_ids)
    fn = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec,
        check_vma=False,
    )
    return fn(*args)
