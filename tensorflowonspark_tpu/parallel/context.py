"""Ambient mesh context.

Model code (flax modules) should not have to carry a ``jax.sharding.Mesh``
in hashable module attributes just to reach a ``shard_map``; the train-step
builder knows the mesh and publishes it here for the duration of tracing.

This mirrors the role the reference's ``TF_CONFIG`` environment variable
played (``TFSparkNode._mapfn`` writes it, strategy objects deep inside user
code read it — SURVEY.md §3.1): ambient cluster topology, set by the
runtime, consumed by the model layer.
"""

from __future__ import annotations

import contextlib
import threading

from jax.sharding import Mesh

_state = threading.local()


def current_mesh() -> Mesh | None:
    """The mesh published by the innermost :func:`use_mesh`, or None."""
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Publish ``mesh`` as the ambient mesh for code traced inside."""
    prev = current_mesh()
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev
