"""Ambient mesh context.

Model code (flax modules) should not have to carry a ``jax.sharding.Mesh``
in hashable module attributes just to reach a ``shard_map``; the train-step
builder knows the mesh and publishes it here for the duration of tracing.

This mirrors the role the reference's ``TF_CONFIG`` environment variable
played (``TFSparkNode._mapfn`` writes it, strategy objects deep inside user
code read it — SURVEY.md §3.1): ambient cluster topology, set by the
runtime, consumed by the model layer.
"""

from __future__ import annotations

import contextlib
import threading

from jax.sharding import Mesh

_state = threading.local()


def current_mesh() -> Mesh | None:
    """The mesh published by the innermost :func:`use_mesh`, or None."""
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Publish ``mesh`` as the ambient mesh for code traced inside."""
    prev = current_mesh()
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def dispatch_mesh(on_tpu, batch_extent: int, forbidden_axes=()):
    """Shared trace-time gate for routing an op to a shard_map placement
    of a Pallas kernel (GSPMD cannot partition a ``pallas_call``; the
    multi-device fast path is explicit per-shard placement).

    Returns the ambient mesh iff ALL hold — multi-device TPU process
    (``on_tpu`` is the caller's backend predicate, usually carrying a
    module-level TREAT_AS_TPU test hook), not already inside a shard_map
    body (nesting over the same mesh is a trace error), a mesh published
    via :func:`use_mesh`, none of ``forbidden_axes`` sharded, and
    ``batch_extent`` divisible over the mesh's ``(data, fsdp)`` extent.
    Callers layer their own op-specific checks (head divisibility,
    kernel shape minima) on top. None means "use a local/GSPMD path".
    """
    import jax

    try:
        if not on_tpu() or len(jax.devices()) == 1:
            return None
    except RuntimeError:  # pragma: no cover - no backend at all
        return None
    try:
        if jax.core.nonempty_axis_env_DO_NOT_USE():
            return None
    except AttributeError:  # pragma: no cover - future jax renames it
        pass
    mesh = current_mesh()
    if mesh is None:
        return None
    if any(mesh.shape.get(ax, 1) != 1 for ax in forbidden_axes):
        return None
    dp = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    if batch_extent % dp:
        return None
    return mesh


def sp_specs_and_args(base_spec, q, k, v, segment_ids=None):
    """Assemble shard_map ``(in_specs, args)`` for a sequence-parallel
    attention call with an optional ``(B, S)`` segment-id operand (its
    spec reuses the batch/seq axes of ``base_spec``). Shared by the ring
    and Ulysses front-ends so the optional-operand wiring cannot
    diverge."""
    from jax.sharding import PartitionSpec as P

    in_specs: tuple = (base_spec, base_spec, base_spec)
    args: tuple = (q, k, v)
    if segment_ids is not None:
        in_specs = in_specs + (  # lint: layout-ok: the segment-ids spec is the leading two dims of the caller's q spec (parametric seq axis), not a fixed table row
            P(base_spec[0], base_spec[1]),
        )
        args = args + (segment_ids,)
    return in_specs, args
