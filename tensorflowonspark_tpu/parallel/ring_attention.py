"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Long-context training shards the *sequence* dimension across devices, so no
single chip ever materialises full-length K/V, let alone the S×S logits.
Each device holds one block of Q (which never moves) and one block of K/V
(which rotates around the ring): at ring step ``t`` a device combines its Q
block with the K/V block originally owned by device ``(i - t) mod n``, then
passes its current K/V block to its neighbour with
``jax.lax.ppermute`` — a pure-ICI collective. Partial attention results
merge with the flash-attention online-softmax recurrence, so memory stays
O(S_local) and the communication fully overlaps MXU work when XLA schedules
the permute asynchronously.

The reference had **nothing** in this space (SURVEY.md §5.7: "no ring
attention, no context/sequence parallel ... max sequence length is whatever
fits one replica") — this module is where the rebuild's long-context
first-class requirement lives.

Causal masking with a sharded sequence is computed against *global*
positions: Q block ``i`` attends fully to K/V blocks ``< i``, diagonally to
block ``i``, and not at all to blocks ``> i`` (those steps contribute
nothing, which the online-softmax merge handles exactly). Each ring step is
wrapped in ``jax.checkpoint`` so the backward pass recomputes blockwise
logits instead of storing all ``n`` of them — the blockwise-memory property
of the ring-attention formulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowonspark_tpu.utils import compat

NEG_INF = -1e30


def _block_attend(
    qg, k, v, q_pos, k_pos, m, l, acc, *, causal, scale,
    q_seg=None, k_seg=None, window=None,
):
    """One online-softmax accumulation step against a K/V block.

    GQA stays grouped throughout — no ``jnp.repeat`` of K/V per device per
    ring step. qg (B,Sq,Hk,G,D) fp-any; k/v (B,Sk,Hk,D); q_pos (Sq,),
    k_pos (Sk,) global positions; m/l (B,Hk,G,Sq,1) fp32 running max /
    normaliser; acc (B,Hk,G,Sq,D) fp32 running numerator; q_seg (B,Sq) /
    k_seg (B,Sk) optional packed-sequence segment ids (cross-segment
    pairs are masked; fully-masked rows stay exact via the NEG_INF
    guards below).
    """
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk",
        qg,
        k,
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        if window is not None:
            mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    if q_seg is not None:
        seg_mask = q_seg[:, :, None] == k_seg[:, None, :]  # (B, Sq, Sk)
        s = jnp.where(seg_mask[:, None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    # Guard fully-masked rows: keep the running max finite once anything
    # has been seen; before that, exp(NEG_INF - NEG_INF) must not be 1.
    p = jnp.exp(s - m_new)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    correction = jnp.exp(m - m_new)
    correction = jnp.where(m <= NEG_INF / 2, 0.0, correction)
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum(
        "bhgqk,bkhd->bhgqd",
        p,
        v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    acc_new = acc * correction + pv
    return m_new, l_new, acc_new


def ring_hops(window: int | None, s_loc: int, n: int) -> int:
    """Ring steps needed after the diagonal block. Full causal ring:
    n - 1. With a sliding window only owners within the window's reach
    contribute — block j overlaps query block i's key range iff
    i - j <= 1 + (window - 2) // s_loc — so a 4096-token window over a
    32k sequence on 8 devices rotates ONCE instead of 7 times: the ICI
    traffic and block compute drop to O(window), the whole point of
    windowed attention at long context."""
    if window is None:
        return n - 1
    if window < 2:
        return 0  # each query attends only itself: the diagonal block
    return min(n - 1, 1 + (window - 2) // s_loc)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    segment_ids: jax.Array | None = None,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    scale: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Sequence-sharded attention; call under ``shard_map``.

    Shapes are per-device shards: q (B, S_loc, Hq, D), k/v (B, S_loc,
    Hkv, D) — the global sequence is ``S_loc * axis_size`` with this
    device owning block ``axis_index``. ``segment_ids`` (B, S_loc),
    sequence-sharded like q, masks cross-segment attention for packed
    sequences; the K-side ids rotate around the ring with their K/V
    block. ``window`` (requires ``causal=True``) applies sliding-window
    masking AND shortens the ring to :func:`ring_hops` steps — every
    device stops rotating once no owner in reach can contribute (the
    hop count depends only on window/s_loc/n, so it is uniform across
    devices and the permute chain stays collective-complete). Returns
    the local output shard (B, S_loc, Hq, D) in q's dtype.
    """
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1"
        )
    b, s_loc, hq, d = q.shape
    hk = k.shape[2]
    if hq % hk:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hk}")
    group = hq // hk
    scale = (d**-0.5) if scale is None else scale
    qg = q.reshape(b, s_loc, hk, group, d)

    n = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    local_pos = jnp.arange(s_loc, dtype=jnp.int32)
    q_pos = idx * s_loc + local_pos

    perm = [(j, (j + 1) % n) for j in range(n)]

    m0 = jnp.full((b, hk, group, s_loc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hk, group, s_loc, 1), jnp.float32)
    acc0 = jnp.zeros((b, hk, group, s_loc, d), jnp.float32)

    # The K-side segment ids travel with their K/V block; a zero-size
    # placeholder keeps the scan carry structure static when unused.
    k_seg0 = (
        segment_ids
        if segment_ids is not None
        else jnp.zeros((b, 0), jnp.int32)
    )

    # Step 0 attends the locally-owned (diagonal) block with no permute;
    # the scan then rotates-and-attends n-1 times, so exactly n-1 permute
    # pairs go around the ring (none after the last block is consumed).
    m, l, acc = _block_attend(  # diagonal block: k_pos == q_pos
        qg, k, v, q_pos, q_pos, m0, l0, acc0, causal=causal, scale=scale,
        q_seg=segment_ids, k_seg=segment_ids, window=window,
    )

    @jax.checkpoint
    def step(carry, t):
        k_blk, v_blk, k_seg, m, l, acc = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if segment_ids is not None:
            k_seg = lax.ppermute(k_seg, axis_name, perm)
        src = (idx - t) % n  # owner of the block just received
        k_pos = src * s_loc + local_pos
        m, l, acc = _block_attend(
            qg, k_blk, v_blk, q_pos, k_pos, m, l, acc,
            causal=causal, scale=scale,
            q_seg=segment_ids,
            k_seg=k_seg if segment_ids is not None else None,
            window=window,
        )
        return (k_blk, v_blk, k_seg, m, l, acc), None

    hops = ring_hops(window, s_loc, n)
    if hops > 0:
        (_, _, _, m, l, acc), _ = lax.scan(
            step,
            (k, v, k_seg0, m, l, acc),
            jnp.arange(1, hops + 1, dtype=jnp.int32),
        )
    out = acc / jnp.maximum(l, 1e-30)  # (B, Hk, G, Sq, D)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, s_loc, hq, d)
    return out.astype(q.dtype)


def mesh_ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: float | None = None,
    seq_axis: str = "seq",
    segment_ids: jax.Array | None = None,
    window: int | None = None,
) -> jax.Array:
    """Global-view ring attention: shard_map over the mesh's ``seq`` axis.

    Inputs are global arrays (B, S, H, D); batch shards over
    ``(data, fsdp)``, heads over ``model`` (tensor parallelism composes —
    attention is head-independent), sequence over ``seq``. Requires S
    divisible by the seq-axis size and heads divisible by the model-axis
    size. ``segment_ids`` (B, S) masks cross-segment attention for
    packed sequences.
    """
    from tensorflowonspark_tpu.parallel.context import sp_specs_and_args

    qspec = P(("data", "fsdp"), seq_axis, "model", None)  # lint: layout-ok: SP operand spec over the caller-chosen seq axis; shard_map plumbing, not a model layout
    body = functools.partial(
        ring_attention, axis_name=seq_axis, causal=causal, scale=scale,
        window=window,
    )
    in_specs, args = sp_specs_and_args(qspec, q, k, v, segment_ids)
    fn = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=qspec,
        check_vma=False,
    )
    return fn(*args)
