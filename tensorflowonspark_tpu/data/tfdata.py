"""tf.data pull-mode adapter: TFRecord dir -> numpy batch iterator.

Reference parity: the ``InputMode.TENSORFLOW`` examples consumed their
shards through ``tf.data`` (``mnist_tf.py``'s
``TFRecordDataset -> parse -> shuffle -> batch`` chain — SURVEY.md
§2.4), and SURVEY.md §2.2 names tf.data as one of the record-reader
equivalents of the Hadoop connector. This module is that chain behind
one call, ending at the JAX boundary: the dataset's output is a plain
iterator of numpy dicts, ready for ``shard_batch``/``DevicePrefetcher``.

tf.data brings what the pure-Python tier (``data/readers.py``) doesn't:
parallel interleaved file reads, parallel Example parsing, and an
autotuned prefetch pipeline — the host-side input throughput story for
image-scale training. TensorFlow stays an optional dependency of this
module only (the core framework never imports it).
"""

from __future__ import annotations

from typing import Any, Iterator, Sequence


def _tf():
    import tensorflow as tf

    try:
        tf.config.set_visible_devices([], "GPU")  # host-side pipeline only
    except RuntimeError:
        pass  # TF runtime already initialized elsewhere in the process
    return tf


def tfdata_batches(
    input_dir: str,
    batch_size: int,
    shard_index: int = 0,
    num_shards: int = 1,
    shuffle_buffer: int = 0,
    num_epochs: int | None = None,
    drop_remainder: bool = True,
    binary_features: Sequence[str] = (),
    seed: int = 0,
) -> Iterator[dict[str, Any]]:
    """Stream column-batched numpy dicts from a TFRecord directory.

    Sharding: by FILE when the file count divides ``num_shards`` evenly
    (each worker reads only its files), otherwise by RECORD (stride over
    the interleaved stream, every worker reads all files) — so per-shard
    record counts never differ by more than one, and multi-process SPMD
    jobs keep equal step counts (unequal feeds deadlock collectives;
    SURVEY.md §7 "hard parts"). Each node of an ``InputMode.TENSORFLOW``
    job passes its ``ctx.executor_id``/``ctx.num_workers``. Feature
    shapes and dtypes come from the first record (``dfutil.infer_schema``
    on a decoded row); every record must share that layout, the TFRecord
    convention this package writes (``dfutil.saveAsTFRecords``).

    ``num_epochs=None`` repeats forever (the training default — pair
    with a step budget); ``drop_remainder=True`` keeps jit shapes
    static.
    """
    tf = _tf()

    from tensorflowonspark_tpu.data import dfutil

    files = dfutil.tfrecord_files(input_dir)  # raises on a fileless dir

    # schema + fixed shapes from the first record. Eager (this function
    # returns a generator rather than being one, so both this and the
    # fileless-dir case raise at call time); the explicit StopIteration
    # catch stops record-less shard files surfacing as an opaque PEP 479
    # "generator raised StopIteration" RuntimeError.
    try:
        first = next(iter(dfutil.loadTFRecords(input_dir, binary_features)))
    except StopIteration:
        raise ValueError(
            f"TFRecord files in {input_dir} contain no records "
            f"({len(files)} shard file(s), all empty)"
        ) from None
    schema = dfutil.infer_schema(first)
    features = {}
    for col, kind in schema.items():
        val = first[col]
        if kind == "int64":
            shape = list(getattr(val, "shape", ())) or []
            features[col] = tf.io.FixedLenFeature(shape, tf.int64)
        elif kind == "float":
            shape = list(getattr(val, "shape", ())) or []
            features[col] = tf.io.FixedLenFeature(shape, tf.float32)
        else:
            # bytes columns decode to a single value or a list of values
            shape = [len(val)] if isinstance(val, (list, tuple)) else []
            features[col] = tf.io.FixedLenFeature(shape, tf.string)

    def parse(serialized):
        return tf.io.parse_example(serialized, features)

    ds = tf.data.Dataset.from_tensor_slices(sorted(files))
    shard_records = num_shards > 1 and len(files) % num_shards != 0
    if num_shards > 1 and not shard_records:
        ds = ds.shard(num_shards, shard_index)
    ds = ds.interleave(
        tf.data.TFRecordDataset,
        cycle_length=tf.data.AUTOTUNE,
        num_parallel_calls=tf.data.AUTOTUNE,
    )
    if shard_records:
        ds = ds.shard(num_shards, shard_index)
    ds = ds.repeat(num_epochs)
    if shuffle_buffer:
        ds = ds.shuffle(shuffle_buffer, seed=seed)
    ds = ds.batch(batch_size, drop_remainder=drop_remainder)
    ds = ds.map(parse, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.prefetch(tf.data.AUTOTUNE)

    import numpy as np

    str_cols = [
        c
        for c, kind in schema.items()
        if kind == "bytes" and c not in binary_features
    ]

    def batches():
        for batch in ds.as_numpy_iterator():
            if str_cols:
                batch = dict(batch)
                for c in str_cols:
                    # elementwise decode, any rank (scalar or multi-value)
                    batch[c] = np.char.decode(
                        np.asarray(batch[c]).astype("S"), "utf-8"
                    )
            yield batch

    return batches()
