"""Data interop: record formats and feed adapters.

Reference parity: ``tensorflowonspark/dfutil.py`` (DataFrame↔TFRecord) →
:mod:`.dfutil`, operating on python record iterables instead of Spark
DataFrames (no pyspark in this stack; the launcher plays Spark's role).
"""
