"""Sequence packing — variable-length documents into fixed training rows.

Long-context training wants every (B, S) slot doing useful work, but real
corpora are variable-length: padding each document to S wastes compute
quadratically with the length spread. Packing concatenates documents into
rows of exactly ``seq_len + 1`` tokens alongside a ``segment_ids`` plane;
the model layer (``llama_loss_fn(..., segment_ids=...)``) then isolates
attention per document, restarts RoPE positions at each boundary, and
drops the cross-document boundary targets from the loss — so a packed
batch trains identically to the unpacked documents (guaranteed by
``tests/test_models.py::test_llama_packed_sequences_match_separate_docs``).

The reference had no packing (its examples padded fixed-shape image/MNIST
batches; SURVEY.md §5.7 notes the absence of any long-sequence machinery).
This is greedy first-fit-in-arrival-order packing — streaming-friendly
(bounded buffer, documents emitted in arrival order), which matters
because the data plane feeds from partition queues, not a random-access
corpus.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Sequence

import numpy as np


def pack_sequences(
    docs: Iterable[Sequence[int]],
    seq_len: int,
    *,
    pad_id: int = 0,
    drop_overlong: bool = False,
) -> Iterator[dict[str, np.ndarray]]:
    """Greedily pack token documents into ``(seq_len + 1,)`` rows.

    Yields ``{"tokens": (seq_len+1,) int32, "segment_ids": (seq_len+1,)
    int32}`` — the ``+1`` is the next-token-loss shift, matching
    ``llama_loss_fn``'s ``tokens (B, S+1)`` contract. Documents longer
    than ``seq_len + 1`` are split across consecutive rows (their
    continuation keeps training as one document per row but does NOT
    attend across the row break — the standard packing tradeoff), or
    skipped with ``drop_overlong=True``. Rows are flushed when the next
    document does not fit; the final partial row is padded with
    ``pad_id`` under segment id 0, which the loss machinery masks out
    (padding never matches a real document's id because real ids start
    at 1).
    """
    if seq_len < 1:
        raise ValueError("seq_len must be >= 1")
    row_len = seq_len + 1
    tokens: list[int] = []
    segs: list[int] = []
    next_id = 1

    def flush():
        nonlocal tokens, segs, next_id
        if not tokens:
            return None
        pad = row_len - len(tokens)
        out = {
            "tokens": np.asarray(
                tokens + [pad_id] * pad, np.int32
            ),
            "segment_ids": np.asarray(segs + [0] * pad, np.int32),
        }
        tokens, segs = [], []
        next_id = 1
        return out

    for doc in docs:
        doc = list(doc)
        if not doc:
            continue
        if drop_overlong and len(doc) > row_len:
            continue
        while doc:
            space = row_len - len(tokens)
            if space == 0 or (len(doc) > space and len(doc) <= row_len):
                # doesn't fit, but fits a fresh row: flush, don't split
                row = flush()
                if row is not None:
                    yield row
                space = row_len
            take = min(len(doc), space)
            tokens.extend(doc[:take])
            segs.extend([next_id] * take)
            doc = doc[take:]
            if doc:
                # overlong document continues into the next row
                row = flush()
                if row is not None:
                    yield row
        next_id += 1

    row = flush()
    if row is not None:
        yield row


def pack_batches(
    docs: Iterable[Sequence[int]],
    batch_size: int,
    seq_len: int,
    *,
    pad_id: int = 0,
    drop_overlong: bool = False,
    drop_remainder: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Batch :func:`pack_sequences` rows into ``(B, seq_len+1)`` arrays
    ready for ``shard_batch`` + ``llama_loss_fn(..., segment_ids=...)``.
    ``drop_remainder`` keeps jit shapes static (the tail short batch is
    dropped, like the reference's drop-remainder datasets)."""
    rows: list[dict[str, np.ndarray]] = []
    for row in pack_sequences(
        docs, seq_len, pad_id=pad_id, drop_overlong=drop_overlong
    ):
        rows.append(row)
        if len(rows) == batch_size:
            yield {
                k: np.stack([r[k] for r in rows]) for k in rows[0]
            }
            rows = []
    if rows and not drop_remainder:
        yield {k: np.stack([r[k] for r in rows]) for k in rows[0]}
