"""grain integration: random-access TFRecord source + a configured loader.

SURVEY.md §2.2 names ``grain`` as the TPU-native record-reader equivalent
of the reference's Hadoop connector. grain wants *random access*
(``__len__``/``__getitem__``) so its samplers own ordering, sharding, and
reproducible shuffling; TFRecord is a sequential format — so this module
builds a one-pass byte-offset index over the shard files (framing: 8-byte
length + 4-byte length-crc + payload + 4-byte payload-crc) and serves
records by ``pread``. The index costs one sequential metadata scan
(payload bytes are skipped, not read).

Everything here is optional: the core framework never imports grain.
"""

from __future__ import annotations

import os
import struct
import threading
from collections import OrderedDict
from typing import Any, Sequence

_LEN = struct.Struct("<Q")
_HEADER = 8 + 4  # length + masked length-crc
_FOOTER = 4  # masked payload-crc


def _index_file(path: str) -> list[tuple[int, int]]:
    """[(payload_offset, payload_len)] for one TFRecord file.

    Each 12-byte header's length-crc is verified, so a corrupted length
    field fails here instead of mis-framing every later record into
    garbage rows. Payload bytes are genuinely skipped.

    Fast path: the native scanner (``tfrecord.cc:tfr_index_file`` —
    hardware crc32c, one buffered pass) when the C++ library is built;
    the pure-Python scan below is the fallback (unbuffered header reads).
    """
    native = _index_file_native(path)
    if native is not None:
        return native
    from tensorflowonspark_tpu.native.tfrecord import _py_masked_crc

    out: list[tuple[int, int]] = []
    size = os.path.getsize(path)
    with open(path, "rb", buffering=0) as f:
        pos = 0
        while pos + _HEADER <= size:
            f.seek(pos)
            header = f.read(_HEADER)
            n = _LEN.unpack(header[:8])[0]
            if _py_masked_crc(header[:8]) != struct.unpack("<I", header[8:])[0]:
                raise ValueError(
                    f"{path}: corrupt record length at offset {pos}"
                )
            payload = pos + _HEADER
            end = payload + n + _FOOTER
            if end > size:
                raise ValueError(
                    f"{path}: truncated record at offset {pos} "
                    f"(needs {end - size} more bytes)"
                )
            out.append((payload, n))
            pos = end
        if pos != size:
            raise ValueError(
                f"{path}: truncated record at offset {pos} "
                f"({size - pos} trailing bytes, less than a record header)"
            )
    return out


def _index_file_native(path: str) -> list[tuple[int, int]] | None:
    """Native index scan; None when the C++ library is unavailable."""
    import ctypes

    from tensorflowonspark_tpu.native import load_library
    from tensorflowonspark_tpu.native.tfrecord import _ERRORS

    lib = load_library()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_uint64)()
    n = lib.tfr_index_file(path.encode(), ctypes.byref(out))
    if n < 0:
        raise ValueError(f"{path}: {_ERRORS.get(n, f'index error {n}')}")
    if n == 0:
        return []
    try:
        import numpy as np

        flat = np.ctypeslib.as_array(out, shape=(2 * n,)).copy()
    finally:
        lib.tfr_index_free(out)
    return list(zip(flat[0::2].tolist(), flat[1::2].tolist()))


class TFRecordDataSource:
    """grain ``RandomAccessDataSource`` over a TFRecord directory.

    ``__getitem__`` returns the decoded dict row (``dfutil.fromTFExample``)
    — plug into ``grain.python.DataLoader`` with any sampler.
    """

    def __init__(
        self, input_dir: str, binary_features: Sequence[str] = ()
    ):
        from tensorflowonspark_tpu.data import dfutil

        self._binary = tuple(binary_features)
        self._files = dfutil.tfrecord_files(input_dir)
        self._entries: list[tuple[int, int, int]] = []  # (file, off, len)
        for fi, path in enumerate(self._files):
            for off, n in _index_file(path):
                self._entries.append((fi, off, n))
        self._handles: dict[int, Any] = {}

    def __getstate__(self):
        # grain spawns worker processes and pickles the source into them:
        # raw fd numbers are meaningless (or worse, unrelated-but-valid)
        # in another process, so workers must reopen lazily.
        state = self.__dict__.copy()
        state["_handles"] = {}
        return state

    def __len__(self) -> int:
        return len(self._entries)

    def __getitem__(self, index: int) -> dict[str, Any]:
        from tensorflowonspark_tpu.data import dfutil

        fi, off, n = self._entries[index]
        fd = self._handles.get(fi)
        if fd is None:
            # raw fds: os.pread is thread-safe (grain reads from a thread
            # pool). Racing first-touchers must not leak the loser's fd —
            # setdefault keeps exactly one open handle per file.
            fd = os.open(self._files[fi], os.O_RDONLY)
            winner = self._handles.setdefault(fi, fd)
            if winner != fd:
                os.close(fd)
                fd = winner
        return dfutil.fromTFExample(os.pread(fd, n, off), self._binary)

    def __del__(self):  # pragma: no cover - best-effort cleanup
        for fd in getattr(self, "_handles", {}).values():
            try:
                os.close(fd)
            except OSError:
                pass


class ColumnarFrameDataSource:
    """grain ``RandomAccessDataSource`` over columnar frame files (the
    pull plane's on-disk wire format, ``feed.columnar.write_frames``).

    The index is one header-only scan per file (``columnar.scan_frames``
    — payload bytes untouched), mapping every record to its owning
    frame; ``__getitem__`` decodes that frame lazily into zero-copy
    views over a shared per-file mmap (a tiny LRU of decoded frames
    absorbs a sampler's locality) and returns the record in its row
    shape. This is the random-access tier of executor-local ingestion:
    grain's samplers own sharding/shuffling/resume, while sequential
    shard drains go through ``feed.ingest.IngestFeed``.

    ``frame_cache`` (a ``cachetier.FrameCache``) optionally fronts the
    mmap reads: a frame missing from the local decoded-frame LRU is
    fetched through the shared read-through cache tier first, so N
    co-located sources over one dataset hit backing storage once per
    frame instead of once per source. Cache failure degrades to the
    local mmap path; the facade is process-local and is dropped on
    pickle (grain worker processes re-attach their own if desired).
    """

    _CACHE_FRAMES = 4

    def __init__(
        self,
        paths: "str | Sequence[str]",
        *,
        frame_cache: "Any | None" = None,
    ):
        import glob

        if isinstance(paths, str):
            if os.path.isdir(paths):
                files = sorted(glob.glob(os.path.join(paths, "*")))
            else:
                files = [paths]
        else:
            files = list(paths)
        if not files:
            raise ValueError(f"no columnar frame files under {paths!r}")
        from tensorflowonspark_tpu.feed.columnar import scan_frames

        self._files = files
        self._frame_cache = frame_cache
        # (file_idx, byte_offset, byte_span, first_record_index) per
        # frame; the parallel _starts list serves bisect. The span is
        # the frame_cache key ingredient (scan_frames header index =
        # the cache tier's key space).
        self._frames: list[tuple[int, int, int, int]] = []
        self._starts: list[int] = []
        total = 0
        for fi, path in enumerate(files):
            for off, span, n in scan_frames(path):
                if n == 0:
                    continue
                self._frames.append((fi, off, span, total))
                self._starts.append(total)
                total += n
        self._total = total
        # _mmaps is deliberately lock-free: racing first-touchers keep
        # exactly one mapping via setdefault (see _mmap)
        self._mmaps: dict[int, Any] = {}
        # grain samplers fan __getitem__ out across threads; the decoded-
        # frame LRU is shared mutable state (tfsan dogfood — an unlocked
        # dict pop/insert race here corrupts the eviction order or drops
        # a racing insert mid-rehash)
        self._cache_lock = threading.Lock()
        # (fi, off) -> chunk, true LRU: hits move-to-end, eviction pops
        # the head — FIFO here silently evicted the HOT frame under a
        # sampler's locality and re-decoded it every touch.
        self._cache: "OrderedDict[tuple[int, int], Any]" = OrderedDict()  # guarded-by: self._cache_lock

    def __getstate__(self):
        # grain worker processes pickle the source: mmaps, decoded
        # views and the cache lock are process-local, workers re-open
        # lazily.
        state = self.__dict__.copy()
        state["_mmaps"] = {}
        state["_cache"] = OrderedDict()
        state["_frame_cache"] = None  # holds a socket/lock; re-attach
        del state["_cache_lock"]  # unpicklable; recreated in __setstate__
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._cache_lock = threading.Lock()

    def __len__(self) -> int:
        return self._total

    def _mmap(self, fi: int):
        mm = self._mmaps.get(fi)
        if mm is None:
            import mmap as _mmap

            with open(self._files[fi], "rb") as f:
                new = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
            # racing first-touchers must keep exactly one mapping
            mm = self._mmaps.setdefault(fi, new)
            if mm is not new:
                new.close()
        return mm

    def _chunk(self, fi: int, off: int, span: int):
        key = (fi, off)
        with self._cache_lock:
            chunk = self._cache.get(key)
            if chunk is not None:
                self._cache.move_to_end(key)  # LRU: a hit IS recency
        if chunk is None:
            from tensorflowonspark_tpu.feed.columnar import decode_frame

            # decode outside the lock (it is the expensive part; a
            # racing double-decode of one frame is benign — last insert
            # wins and both views are valid)
            blob = None
            if self._frame_cache is not None:
                # shared tier first (one backing read per frame fleet-
                # wide); None = miss/down → local mmap exactly as before
                blob = self._frame_cache.get(self._files[fi], off, span)
            if blob is not None:
                chunk = decode_frame(memoryview(blob))
            else:
                chunk = decode_frame(memoryview(self._mmap(fi))[off:])
            with self._cache_lock:
                if len(self._cache) >= self._CACHE_FRAMES:
                    self._cache.popitem(last=False)
                self._cache[key] = chunk
                self._cache.move_to_end(key)
        return chunk

    def __getitem__(self, index: int):
        import bisect

        if not 0 <= index < self._total:
            raise IndexError(index)
        fidx = bisect.bisect_right(self._starts, index) - 1
        fi, off, span, start = self._frames[fidx]
        return self._chunk(fi, off, span).view(index - start, index - start + 1).rows()[0]

    def __del__(self):  # pragma: no cover - best-effort cleanup
        for mm in getattr(self, "_mmaps", {}).values():
            try:
                mm.close()
            except (BufferError, OSError):
                pass  # live views pin the mapping; GC releases it later


def grain_loader(
    input_dir: str,
    *,
    shard_index: int = 0,
    num_shards: int = 1,
    shuffle: bool = True,
    seed: int = 0,
    num_epochs: int | None = 1,
    batch_size: int | None = None,
    worker_count: int = 0,
    binary_features: Sequence[str] = (),
    transformations: Sequence[Any] = (),
):
    """A configured ``grain.python.DataLoader`` over TFRecords.

    The grain-native spelling of ``readers.sharded_rows`` + ``shuffled`` +
    ``column_batches``: sharding and shuffling are the sampler's
    (deterministic, resumable), batching a ``Batch`` transformation.
    """
    import grain.python as gp

    source = TFRecordDataSource(input_dir, binary_features)
    sampler = gp.IndexSampler(
        num_records=len(source),
        shard_options=gp.ShardOptions(
            shard_index=shard_index, shard_count=num_shards, drop_remainder=False
        ),
        shuffle=shuffle,
        num_epochs=num_epochs,
        seed=seed,
    )
    ops = list(transformations)
    if batch_size is not None:
        ops.append(gp.Batch(batch_size=batch_size, drop_remainder=True))
    return gp.DataLoader(
        data_source=source,
        sampler=sampler,
        operations=ops,
        worker_count=worker_count,
    )
