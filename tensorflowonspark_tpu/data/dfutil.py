"""TFRecord interop: save/load record sets, Example conversion, schema
inference.

Reference parity: ``tensorflowonspark/dfutil.py`` — ``saveAsTFRecords``,
``loadTFRecords``, ``toTFExample``, ``fromTFExample``, ``infer_schema``.
The reference delegated file I/O to the Hadoop ``tensorflow-hadoop``
connector jar (SURVEY.md §2.2); here the installed TensorFlow writes/reads
TFRecord files directly, and "DataFrame" means any iterable of dict rows
(or tuple rows + column names).

TensorFlow is imported lazily — it is only needed for this interop layer,
never for training.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Iterable, Iterator, Sequence

import numpy as np


def _tf():
    import tensorflow as tf  # heavy import, deferred

    return tf


# --- schema ----------------------------------------------------------------


def infer_schema(row: dict[str, Any]) -> dict[str, str]:
    """Map column → feature kind ('int64' | 'float' | 'bytes').

    Reference: ``dfutil.infer_schema`` (from DataFrame dtypes; here from a
    sample row).
    """
    schema: dict[str, str] = {}
    for col, val in row.items():
        arr = np.asarray(val)
        if arr.dtype.kind in "iub":
            schema[col] = "int64"
        elif arr.dtype.kind == "f":
            schema[col] = "float"
        elif arr.dtype.kind in "SU" or isinstance(val, (bytes, str)):
            schema[col] = "bytes"
        else:
            raise TypeError(f"column {col!r}: unsupported dtype {arr.dtype}")
    return schema


# --- Example conversion ----------------------------------------------------


def toTFExample(row: dict[str, Any], schema: dict[str, str] | None = None):
    """dict row → ``tf.train.Example`` (reference: ``dfutil.toTFExample``)."""
    tf = _tf()
    schema = schema or infer_schema(row)
    feature = {}
    for col, kind in schema.items():
        val = np.asarray(row[col]).reshape(-1)
        if kind == "int64":
            feature[col] = tf.train.Feature(
                int64_list=tf.train.Int64List(value=val.astype(np.int64))
            )
        elif kind == "float":
            feature[col] = tf.train.Feature(
                float_list=tf.train.FloatList(value=val.astype(np.float32))
            )
        else:
            vals = [
                v.encode() if isinstance(v, str) else bytes(v) for v in val.tolist()
            ]
            feature[col] = tf.train.Feature(
                bytes_list=tf.train.BytesList(value=vals)
            )
    return tf.train.Example(features=tf.train.Features(feature=feature))


def fromTFExample(
    serialized: bytes, binary_features: Sequence[str] = ()
) -> dict[str, Any]:
    """Serialized Example → dict row (reference: ``dfutil.fromTFExample``).

    ``binary_features`` names bytes columns to keep as raw bytes (others
    are decoded to str) — same knob as the reference's ``loadTFRecords``.
    """
    tf = _tf()
    ex = tf.train.Example.FromString(serialized)
    row: dict[str, Any] = {}
    for col, feat in ex.features.feature.items():
        kind = feat.WhichOneof("kind")
        if kind == "int64_list":
            vals: Any = np.asarray(feat.int64_list.value, dtype=np.int64)
        elif kind == "float_list":
            vals = np.asarray(feat.float_list.value, dtype=np.float32)
        else:
            raw = list(feat.bytes_list.value)
            vals = (
                raw if col in binary_features else [b.decode("utf-8", "replace") for b in raw]
            )
        if isinstance(vals, np.ndarray) and vals.size == 1:
            vals = vals[0]
        elif isinstance(vals, list) and len(vals) == 1:
            vals = vals[0]
        row[col] = vals
    return row


# --- file I/O ---------------------------------------------------------------


def saveAsTFRecords(
    rows: Iterable[dict[str, Any]],
    output_dir: str,
    schema: dict[str, str] | None = None,
    records_per_file: int = 10000,
) -> list[str]:
    """Write rows as sharded TFRecord files (reference: ``saveAsTFRecords``,
    which used ``saveAsNewAPIHadoopFile`` + ``TFRecordFileOutputFormat``).
    Returns the shard paths (``part-rNNNNN`` naming, like the connector)."""
    from tensorflowonspark_tpu.native.tfrecord import TFRecordWriter

    os.makedirs(output_dir, exist_ok=True)
    paths: list[str] = []
    writer = None
    count = 0
    try:
        for row in rows:
            if schema is None:
                schema = infer_schema(row)
            if writer is None or count >= records_per_file:
                if writer is not None:
                    writer.close()
                path = os.path.join(
                    output_dir, f"part-r-{len(paths):05d}.tfrecord"
                )
                paths.append(path)
                # Record framing by the in-repo C++ codec (the reference
                # delegated it to the tensorflow-hadoop jar); Example
                # protos still come from TF via toTFExample.
                writer = TFRecordWriter(path)
                count = 0
            writer.write(toTFExample(row, schema).SerializeToString())
            count += 1
    finally:
        if writer is not None:
            writer.close()
    return paths


def tfrecord_files(input_dir: str) -> list[str]:
    """Resolve a TFRecord directory or glob to its sorted shard paths."""
    pattern = (
        input_dir
        if any(ch in input_dir for ch in "*?[")
        else os.path.join(input_dir, "part-*")
    )
    files = sorted(glob.glob(pattern)) or sorted(
        glob.glob(os.path.join(input_dir, "*.tfrecord"))
    )
    if not files:
        raise FileNotFoundError(f"no TFRecord files under {input_dir}")
    return files


def loadTFRecords(
    input_dir: str, binary_features: Sequence[str] = ()
) -> Iterator[dict[str, Any]]:
    """Iterate dict rows from TFRecord files (reference: ``loadTFRecords``)."""
    from tensorflowonspark_tpu.native.tfrecord import read_records

    for path in tfrecord_files(input_dir):
        for serialized in read_records(path):
            yield fromTFExample(serialized, binary_features)
