"""Pull-mode input readers — the ``InputMode.TENSORFLOW`` data path.

Reference parity: in ``InputMode.TENSORFLOW`` the reference's nodes built
their own ``tf.data`` pipelines over HDFS TFRecord shards (SURVEY.md §2.4,
``examples/mnist/keras/mnist_tf.py`` pattern). These are the composable
pieces of that role for our nodes: shard → shuffle → repeat → batch,
streaming throughout (no whole-dataset materialization), pure Python over
the native TFRecord codec so the hot path has no TF dependency.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "columnar_pieces",
    "sharded_chunks",
    "sharded_rows",
    "shuffled",
    "repeated",
    "column_batches",
]


def sharded_rows(
    input_dir: str,
    shard_index: int = 0,
    num_shards: int = 1,
    binary_features: Sequence[str] = (),
) -> Iterator[dict[str, Any]]:
    """This shard's rows of a TFRecord directory, round-robin by record.

    ``sharded_rows(dir, ctx.executor_id, ctx.num_workers)`` is the per-node
    shard — every node sees distinct records, together covering the set
    (the reference's file-sharding / ``disable_auto_shard`` concern).
    Sharding happens on the *serialized* record index, so a node never
    pays proto decoding for records it does not own.
    """
    from tensorflowonspark_tpu.data import dfutil
    from tensorflowonspark_tpu.native.tfrecord import read_records

    i = 0
    for path in dfutil.tfrecord_files(input_dir):
        for serialized in read_records(path):
            if i % num_shards == shard_index:
                yield dfutil.fromTFExample(serialized, binary_features)
            i += 1


def columnar_pieces(
    rows: Iterable[Any], records_per_chunk: int = 1024
) -> Iterator[Any]:
    """Group a row stream into ``ColumnChunk`` pieces — the
    executor-local half of the driver feeder's per-chunk columnization
    (``cluster/node.py feed_partition``), run where the data lives.

    Each block of ``records_per_chunk`` rows is columnized ONCE into
    per-field contiguous buffers; blocks that cannot columnize
    losslessly (ragged/object/mixed records — the same matrix as the
    push wire) are yielded as plain row lists, so downstream assembly
    (``ColumnAssembler``) handles both shapes exactly as it does wire
    pieces. Block boundaries are deterministic for a given
    ``records_per_chunk``: the pull plane's replay cursor counts these
    blocks, and a restarted reader must re-derive identical ordinals.
    """
    from tensorflowonspark_tpu.feed.columnar import columnize_records

    if records_per_chunk < 1:
        raise ValueError(
            f"records_per_chunk must be >= 1, got {records_per_chunk}"
        )

    def flush(buf: list[Any]):
        chunk = columnize_records(buf)
        return buf if chunk is None else chunk

    buf: list[Any] = []
    for row in rows:
        buf.append(row)
        if len(buf) >= records_per_chunk:
            yield flush(buf)
            buf = []
    if buf:
        yield flush(buf)


def sharded_chunks(
    input_dir: str,
    shard_index: int = 0,
    num_shards: int = 1,
    records_per_chunk: int = 1024,
    binary_features: Sequence[str] = (),
) -> Iterator[Any]:
    """This shard's records of a TFRecord directory as ``ColumnChunk``
    pieces — :func:`sharded_rows` (serialized-index sharding, no decode
    of unowned records) composed with :func:`columnar_pieces`, so an
    ``InputMode.TENSORFLOW`` node feeds the slice-not-stack batch
    assembly (``ColumnAssembler`` / ``DevicePrefetcher.from_feed``)
    directly from local TFRecord shards with no driver in the loop."""
    yield from columnar_pieces(
        sharded_rows(input_dir, shard_index, num_shards, binary_features),
        records_per_chunk,
    )


def shuffled(
    rows: Iterable[Any], buffer_size: int = 4096, seed: int | None = None
) -> Iterator[Any]:
    """Streaming shuffle with a bounded reservoir (tf.data ``shuffle``)."""
    rng = np.random.default_rng(seed)
    buf: list[Any] = []
    for row in rows:
        buf.append(row)
        if len(buf) >= buffer_size:
            j = int(rng.integers(len(buf)))
            buf[j], buf[-1] = buf[-1], buf[j]
            yield buf.pop()
    rng.shuffle(buf)
    yield from buf


def repeated(
    make_rows: Callable[[int], Iterable[Any]], epochs: int | None = None
) -> Iterator[Any]:
    """Re-open the source per epoch (tf.data ``repeat``); None = forever.

    ``make_rows`` receives the epoch index — fold it into the shuffle seed
    so each epoch gets a fresh permutation (``reshuffle_each_iteration``),
    not a replay of the first.
    """
    epoch = 0
    while epochs is None or epoch < epochs:
        yield from make_rows(epoch)
        epoch += 1


def column_batches(
    rows: Iterable[dict[str, Any]],
    batch_size: int,
    multiple_of: int = 1,
    transform: Callable[[dict[str, np.ndarray]], Any] | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Stack dict rows into {column: array} batches of exactly
    ``batch_size`` (rounded down to ``multiple_of``, so batches shard over
    the mesh); the sub-multiple tail is dropped with a log line."""
    from tensorflowonspark_tpu.utils.batching import fixed_size_batches

    yield from fixed_size_batches(
        rows, batch_size, multiple_of, assemble=lambda p: _stack(p, transform)
    )


def _stack(rows: list[dict[str, Any]], transform) -> Any:
    batch = {
        col: np.stack([np.asarray(r[col]) for r in rows]) for col in rows[0]
    }
    return transform(batch) if transform is not None else batch
