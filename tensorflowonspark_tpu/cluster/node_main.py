"""Standalone node-process entry for remote launchers.

``HostListLauncher`` starts one of these per host (via ssh or a custom
command template)::

    python -m tensorflowonspark_tpu.cluster.node_main --payload <b64>

The payload is a base64 pickle of ``(executor_id, map_fun, tf_args,
cluster_meta)`` — the same tuple :func:`~tensorflowonspark_tpu.cluster.
node.run_node` takes from the local launcher. ``map_fun`` is pickled by
qualified name, so the user's module must be importable on every host
(the same contract Spark imposed on the reference's ``map_fun``).
"""

from __future__ import annotations

import argparse
import base64
import pickle


def encode_payload(executor_id, map_fun, tf_args, cluster_meta) -> str:
    return base64.b64encode(
        pickle.dumps((executor_id, map_fun, tf_args, cluster_meta))
    ).decode("ascii")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tfos-tpu-node")
    parser.add_argument("--payload", required=True, help="base64 node payload")
    args = parser.parse_args(argv)
    executor_id, map_fun, tf_args, cluster_meta = pickle.loads(
        base64.b64decode(args.payload)
    )
    from tensorflowonspark_tpu.cluster.node import run_node

    run_node(executor_id, map_fun, tf_args, cluster_meta)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
