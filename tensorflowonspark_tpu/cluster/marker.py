"""Sentinel objects pushed through the feed queues.

Reference parity: ``tensorflowonspark/marker.py`` (``Marker``,
``EndPartition``). The consumer side (:class:`~tensorflowonspark_tpu.feed.
datafeed.DataFeed`) interprets these to emit partial batches at partition
boundaries and to flip ``should_stop`` at end of feed.
"""

from __future__ import annotations


class Marker:
    """Base class for queue sentinels."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__}>"

    def __eq__(self, other: object) -> bool:
        # Sentinels cross process boundaries by pickling, so identity
        # comparison is wrong; type equality is the contract.
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self))


class EndPartition(Marker):
    """One data partition is exhausted; the consumer may emit a partial
    batch but must keep reading (more partitions may follow)."""

    __slots__ = ()


class EndOfFeed(Marker):
    """The whole feed is exhausted; ``DataFeed.should_stop()`` becomes True.

    The reference signalled this with a terminal marker pushed by
    ``TFCluster.shutdown`` / ``TFSparkNode._shutdown``; we give it a named
    type so queue traffic is self-describing.
    """

    __slots__ = ()
