"""Driver-side cluster orchestrator.

Reference parity: ``tensorflowonspark/TFCluster.py`` — ``InputMode``,
``run()`` (role template → reservation server → launch nodes → roster
barrier → handle), ``TFCluster.train/inference/shutdown/tensorboard_url``.

TPU-native differences:

- ``num_ps`` is rejected: parameter servers dissolve into sharded optimizer
  state (FSDP) on the mesh — see SURVEY.md §2.3 and
  :mod:`tensorflowonspark_tpu.compute`.
- The roster carries a ``jax.distributed`` coordinator address instead of a
  TF_CONFIG role map.
- Data feeding runs from driver-side threads over TCP to each node's
  manager (Spark's feed *tasks* collapse into these threads).
"""

from __future__ import annotations

import logging
import os
import queue as _stdqueue
import secrets
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Sequence

from tensorflowonspark_tpu.cluster import node as tfnode_runtime
from tensorflowonspark_tpu.cluster import reservation
from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.cluster.launchers import LocalLauncher
from tensorflowonspark_tpu.obs import cluster as obs_cluster
from tensorflowonspark_tpu.obs import flightrec
from tensorflowonspark_tpu.obs.registry import default_registry

logger = logging.getLogger(__name__)


class InputMode:
    """Reference: ``TFCluster.py:InputMode``."""

    TENSORFLOW = 0  # nodes read data themselves (files / grain / tf.data)
    SPARK = 1  # driver pushes partitions into node queues (the push plane)


class TFCluster:
    """Handle to a running cluster; returned by :func:`run`."""

    def __init__(
        self,
        launcher,
        server: reservation.Server,
        server_addr: tuple[str, int],
        cluster_info: list[dict[str, Any]],
        cluster_meta: dict[str, Any],
        input_mode: int,
        queues: Sequence[str],
    ):
        self.launcher = launcher
        self.server = server
        self.server_addr = server_addr
        self.cluster_info = cluster_info
        self.cluster_meta = cluster_meta
        self.input_mode = input_mode
        self.queues = queues
        self.heartbeat_interval = float(
            cluster_meta.get("heartbeat_interval", 0) or 0
        )
        self.heartbeat_grace = float(cluster_meta.get("heartbeat_grace", 0) or 0)
        # Chunk-columnar wire format on the feed plane (feed/columnar.py);
        # False pins the legacy row-pickle wire end-to-end.
        self.columnar = bool(cluster_meta.get("columnar", True))
        self._shutdown_done = False
        self._dstream_bridge: tuple | None = None
        # -- elastic plane (compute/elastic.py; docs/ROBUSTNESS.md) --------
        # With elastic=True, supervise() answers a membership change with
        # a reconfigure (remove/admit + epoch bump) instead of raising.
        self.elastic = bool(cluster_meta.get("elastic", False))
        self.elastic_min_nodes = int(cluster_meta.get("elastic_min_nodes", 1))
        # Live shard redistribution (docs/ROBUSTNESS.md): with
        # ingest_handover (the default), an elastic reconfigure
        # RE-SPLITS the remaining records over the survivors instead of
        # re-publishing stable shards — the PR-8 stable assignment
        # stays as the ingest_handover=False fallback.
        self.ingest_handover = bool(cluster_meta.get("ingest_handover", True))
        self.handover_timeout = float(
            cluster_meta.get("handover_timeout", 30.0)
        )
        # The startup barrier roster is epoch-0 membership.
        server.reservations.seal()
        # Executors that elastically LEFT (death or voluntary): their
        # nonzero exits are expected, not failures, and no manager RPC
        # may ever target them again.
        self._departed: set[int] = set()  # guarded-by: self._dead_lock
        # Launchers spawned for replacement nodes (launch_replacement);
        # shutdown waits on / terminates these alongside the primary.
        self._replacement_launchers: list[Any] = []
        # The env run() launched nodes with — replacements must boot
        # with the same one (run() fills this in).
        self._node_env: dict[str, str] = {}
        # Pull-plane shard map (assign_shards): executor id -> manifest
        # list. With the handover protocol armed (elastic +
        # ingest_handover) this is the CURRENT plan — each reconfigure
        # replaces it with the re-split of the remaining records; with
        # handover off it is stable per executor id forever (PR-8).
        # Guarded: the supervise thread re-splits while the user thread
        # may still be assigning/tearing down.
        self._ingest_lock = threading.Lock()
        self._ingest_shards: dict[int, list[Any]] | None = None  # guarded-by: self._ingest_lock
        self._ingest_complete = False  # guarded-by: self._ingest_lock
        self._ingest_republished = False  # guarded-by: self._ingest_lock
        # Plan GENERATION within a membership epoch (the growing-
        # dataset wire): bumped by assign_shards and extend_shards so a
        # lingering consumer can tell appended work from a stale
        # republish, and so completion requires finals at the CURRENT
        # generation (a final published before an append must not
        # complete the grown dataset).
        self._ingest_seq = 0  # guarded-by: self._ingest_lock
        # Online mode (run_online): suppress the supervise loop's
        # auto-completion while the dataset is still growing; shutdown
        # clears it so teardown always releases lingering consumers.
        self._ingest_hold_completion = False  # guarded-by: self._ingest_lock
        # Serializes whole plan-mutation episodes (an epoch re-split vs
        # a growth append) INCLUDING their out-of-lock IO, so neither
        # can clobber the other's published plan. Ordering:
        # _ingest_replan_lock > _ingest_lock, never the reverse.
        self._ingest_replan_lock = threading.Lock()
        # Driver-pushed feed knobs (autotune): monotonically increasing
        # publication seq — consumers adopt each publication once.
        self._feed_knob_seq = 0  # guarded-by: self._ingest_lock
        # -- cluster observability plane (obs.cluster; docs/OBSERVABILITY.md)
        # Liveness surfaced in the registry: per-executor heartbeat age
        # as a render-time collector (PR 4's plane was invisible to
        # /metrics), and a counter that ticks once per node DEATH
        # transition (dead_nodes()).
        reg = default_registry()
        self._m_dead = reg.counter(
            "cluster_dead_nodes_total",
            "nodes declared dead by the liveness plane (transitions)",
        )
        self._counted_dead: set[int] = set()  # guarded-by: self._dead_lock
        self._dead_lock = threading.Lock()
        hb_gauge = reg.gauge(
            "node_heartbeat_age_seconds",
            "seconds since each executor's last heartbeat, by node",
        )

        def _liveness_collector(
            _g=hb_gauge, _res=server.reservations
        ) -> None:
            for eid, age in _res.last_seen().items():
                _g.set(age, node=str(eid))

        self._liveness_collector = _liveness_collector
        reg.add_collector(_liveness_collector)
        # Driver-side aggregation: scrape every node's /metrics on the
        # liveness cadence, merge, and re-serve at a driver /metrics
        # endpoint (every sample labelled node="<eid>"; the driver's
        # own registry under node="driver").
        self.aggregator: obs_cluster.MetricsAggregator | None = None
        self._driver_metrics_server = None
        self._driver_metrics_port: int | None = None
        if cluster_meta.get("metrics", True) and self.metrics_urls():
            self.aggregator = obs_cluster.MetricsAggregator(
                self.metrics_urls,
                interval=max(self.heartbeat_interval, 1.0)
                if self.heartbeat_interval > 0
                else 2.0,
            )
            self.aggregator.start()
            (
                self._driver_metrics_server,
                self._driver_metrics_port,
            ) = obs_cluster.serve_text(self.aggregator.render)

    # ------------------------------------------------------------------
    # liveness plane
    def dead_nodes(self, grace: float | None = None) -> list[int]:
        """Executor ids whose heartbeats have been silent longer than
        the grace window ([] when heartbeats are disabled). This is the
        fast failure detector: a SIGKILLed or wedged node shows up here
        within ``heartbeat_grace`` seconds instead of only at a feed or
        shutdown timeout."""
        if self.heartbeat_interval <= 0 or self._shutdown_done:
            return []
        grace = self.heartbeat_grace if grace is None else grace
        if grace <= 0:
            return []
        silent = self.server.reservations.dead_nodes(grace)
        if not silent:
            return []
        # A node that FINISHED and exited 0 stops heartbeating too —
        # silence plus a clean exit is completion, not death (supervise
        # and shutdown would otherwise tear down healthy runs with
        # skewed finish times).
        exit_codes = self.launcher.exitcodes()
        dead = [
            eid
            for eid in silent
            if not (eid < len(exit_codes) and exit_codes[eid] == 0)
        ]
        self._note_dead(dead)
        return dead

    def _note_dead(self, dead: list[int]) -> None:
        """Once per death TRANSITION (not per poll): tick the
        cluster_dead_nodes_total counter and drop a driver-side flight
        record — the postmortem's first artifact, written the moment
        the liveness plane passes judgment."""
        if not dead:
            return
        with self._dead_lock:
            new = [eid for eid in dead if eid not in self._counted_dead]
            self._counted_dead.update(new)
        if new:
            self._m_dead.inc(len(new))
            for eid in new:
                flightrec.note("dead_node", executor_id=eid)
            flightrec.dump_now("dead_node")

    def _dead_error(self, dead: list[int], detail: str = "") -> RuntimeError:
        """THE presumed-dead diagnostic — one builder so every surface
        (liveness check, stream polls) reports identically."""
        return RuntimeError(
            f"node(s) {dead} missed heartbeats for more than "
            f"{self.heartbeat_grace}s — presumed dead{detail}"
        )

    def _check_liveness(self) -> None:
        """Raise if any node is presumed dead; prefer its ferried
        traceback (or process exit) over the bare liveness message when
        one exists."""
        dead = self.dead_nodes()
        if not dead:
            return
        self._check_errors()  # a real traceback beats "missed heartbeats"
        failed = self.launcher.poll_failed()
        detail = f" (process(es) {failed} exited nonzero)" if failed else ""
        raise self._dead_error(dead, detail)

    # ------------------------------------------------------------------
    @property
    def workers(self) -> list[dict[str, Any]]:
        """Data-plane nodes (everything except evaluators), roster order."""
        return sorted(
            (n for n in self.cluster_info if n["job_name"] != "evaluator"),
            key=lambda n: n["executor_id"],
        )

    def tensorboard_url(self) -> str | None:
        """Reference: ``TFCluster.tensorboard_url``."""
        for n in self.cluster_info:
            if n.get("tb_port"):
                return f"http://{n['host']}:{n['tb_port']}"
        return None

    def profiler_urls(self) -> dict[int, str]:
        """Per-node ``jax.profiler`` trace-server addresses, by executor id.

        Populated when the cluster was started with ``profiler=True``
        (SURVEY.md §5.1: the coordinator knows every host's profiler URL —
        point TensorBoard's profile capture, or ``jax.profiler.trace``, at
        any of these).
        """
        return {
            n["executor_id"]: f"{n['host']}:{n['prof_port']}"
            for n in self.cluster_info
            if n.get("prof_port")
        }

    def metrics_urls(self) -> dict[int, str]:
        """Per-node Prometheus ``/metrics`` endpoints, by executor id —
        each node runtime serves its process-global obs registry
        (``tensorflowonspark_tpu.obs``); point a scraper at all of
        them, or curl one mid-run."""
        return {
            n["executor_id"]: (
                f"http://{n['host']}:{n['metrics_port']}/metrics"
            )
            for n in self.cluster_info
            if n.get("metrics_port")
        }

    def cluster_stats(self, fresh: bool = True) -> dict[str, Any]:
        """Typed cluster-level series scraped from every node's
        ``/metrics`` plus the driver's own registry: ``{"nodes":
        {key: health}, "series": {name: {"type", "per_node", "sum",
        "max"}}}`` (obs.cluster.MetricsAggregator.cluster_stats).
        ``fresh=False`` reuses the background loop's last round
        instead of scraping now. ``{}`` when metrics are disabled."""
        if self.aggregator is None:
            return {}
        return self.aggregator.cluster_stats(fresh=fresh)

    def driver_metrics_url(self) -> str | None:
        """The driver's aggregated ``/metrics`` endpoint (every node's
        samples re-labelled ``node="<eid>"``), or None when metrics
        are disabled — point ONE scraper here instead of N."""
        if self._driver_metrics_port is None:
            return None
        return f"http://127.0.0.1:{self._driver_metrics_port}/metrics"

    # ------------------------------------------------------------------
    def train(
        self,
        data: Iterable,
        num_epochs: int = 1,
        feed_timeout: float = 600.0,
        qname: str = "input",
        close_feed: bool = False,
    ) -> None:
        """Feed data partitions to the workers (InputMode.SPARK only).

        ``data`` is either an iterable of partitions (each an iterable of
        records) or a flat iterable of records (auto-partitioned). Partitions
        go round-robin to workers; each worker's partitions are fed
        sequentially by a dedicated thread (the moral equivalent of Spark's
        waves of ``foreachPartition`` feed tasks, reference ``TFCluster.train``
        → ``TFSparkNode._train``).

        A :class:`~tensorflowonspark_tpu.streaming.DStream` is also
        accepted (reference: ``TFCluster.train`` with a DStream →
        ``foreachRDD`` feeding): the call registers the feed bridge and
        returns immediately; micro-batches flow once the stream's
        ``StreamingContext.start()`` runs. End with
        ``shutdown(ssc=ssc)``.

        ``close_feed=True`` pushes EndOfFeed after the last partition, so
        worker loops see a clean end-of-stream without waiting for
        ``shutdown()``. Required for multi-controller workers consuming
        via ``DataFeed.synchronized_batch_stream`` (feeds must end for
        the cross-process exhaustion agreement to fire); no further
        ``train()`` calls are allowed on ``qname`` afterwards.
        """
        from tensorflowonspark_tpu.streaming import DStream

        if isinstance(data, DStream):
            if num_epochs != 1:
                raise ValueError(
                    "num_epochs does not apply to a DStream (each "
                    "micro-batch is fed once, on arrival)"
                )
            self._train_dstream(data, feed_timeout, qname)
            return
        self._require_spark_mode("train")
        workers = self.workers
        partitions = _as_partitions(data, len(workers))
        assignments: list[list[Any]] = [[] for _ in workers]
        n_parts = 0
        for epoch in range(num_epochs):
            for i, part in enumerate(partitions):
                assignments[(n_parts) % len(workers)].append(part)
                n_parts += 1
        self._check_errors()
        errors: list[BaseException] = []

        def feed_worker(widx: int) -> None:
            try:
                mgr = tfnode_runtime.connect_manager(workers[widx])
                # publish the feed policy to the node: DataFeed pull
                # loops bound their queue waits by the same timeout the
                # driver feeds under (see DataFeed._next_raw/FeedTimeout)
                mgr.set(
                    wire.FEED_TIMEOUT_KEY,
                    wire.encode("kv.feed_timeout", value=float(feed_timeout)),
                )
                for part in assignments[widx]:
                    tfnode_runtime.feed_partition(
                        mgr,
                        part,
                        feed_timeout=feed_timeout,
                        qname=qname,
                        node=workers[widx],
                        columnar=self.columnar,
                    )
                if close_feed:
                    tfnode_runtime.close_feed(
                        workers[widx], qname=qname, timeout=feed_timeout
                    )
            except BaseException as e:  # noqa: BLE001 - ferried to caller
                errors.append(e)

        threads = [
            threading.Thread(target=feed_worker, args=(i,), daemon=True)
            for i in range(len(workers))
        ]
        for t in threads:
            t.start()
        self._join_feeders(threads)
        if errors:
            self._check_errors()
            raise errors[0]
        self._check_errors()

    def _join_feeders(
        self, threads: list[threading.Thread], poll: float = 2.0
    ) -> None:
        """Join feeder threads while watching node liveness: a feeder
        blocked pushing to a SIGKILLed node would otherwise sit out the
        whole ``feed_timeout`` before anyone noticed the death. On a
        liveness failure the (daemon) feeders are abandoned and the
        error raises within the heartbeat grace."""
        last_check = time.monotonic()
        while True:
            alive = [t for t in threads if t.is_alive()]
            if not alive:
                return
            alive[0].join(min(1.0, poll))
            if time.monotonic() - last_check >= poll:
                self._check_liveness()
                last_check = time.monotonic()

    def _train_dstream(self, dstream, feed_timeout: float, qname: str) -> None:
        """Bridge a DStream into :meth:`train_stream`: ``foreachRDD``
        pushes micro-batches into a bounded queue; a background thread
        drains it through the normal streaming feed path. Non-blocking —
        mirrors the reference, where ``train(DStream)`` just registered
        the ``foreachRDD`` and Spark Streaming drove the feeding."""
        self._require_spark_mode("train")
        if getattr(self, "_dstream_bridge", None) is not None:
            raise RuntimeError("a DStream is already being trained on")
        bridge: _stdqueue.Queue = _stdqueue.Queue(maxsize=2)
        end = object()
        errors: list[BaseException] = []
        stop_evt = threading.Event()

        def micro_batches():
            while True:
                item = bridge.get()
                if item is end:
                    return
                yield item

        def run() -> None:
            try:
                self.train_stream(
                    micro_batches(), feed_timeout=feed_timeout, qname=qname
                )
            except BaseException as e:  # noqa: BLE001 - ferried to shutdown
                errors.append(e)

        thread = threading.Thread(
            target=run, name="dstream-feed", daemon=True
        )

        def bridge_put(rdd) -> None:
            # Never block the scheduler forever: if the feed thread died
            # (worker early-stop, feeder error) or shutdown started, drop
            # the micro-batch instead of wedging the tick loop — the
            # reference's foreachRDD feed task failed/no-opped the same
            # way once the TF side stopped consuming.
            while not stop_evt.is_set() and thread.is_alive():
                try:
                    bridge.put(rdd, timeout=0.2)
                    return
                except _stdqueue.Full:
                    continue

        dstream.foreachRDD(bridge_put)
        thread.start()
        self._dstream_bridge = (bridge, end, thread, errors, stop_evt)

    def _drain_dstream(self) -> None:
        bridge, end, thread, errors, stop_evt = self._dstream_bridge
        self._dstream_bridge = None
        stop_evt.set()  # scheduler callbacks stop feeding / unblock
        while thread.is_alive():
            try:
                bridge.put(end, timeout=0.2)
                break
            except _stdqueue.Full:
                # Feed thread stopped consuming (early stop) — make room
                # by dropping pending micro-batches; shutdown means stop.
                try:
                    bridge.get_nowait()
                except _stdqueue.Empty:
                    pass
        thread.join()
        if errors:
            raise errors[0]

    def train_stream(
        self,
        stream: Iterable[Iterable],
        feed_timeout: float = 600.0,
        qname: str = "input",
    ) -> None:
        """Feed an unbounded stream of micro-batches (Spark Streaming parity).

        Reference: ``TFCluster.train`` with a DStream — each RDD of the
        stream is fed on arrival via ``foreachRDD`` (``TFCluster.py:train``).
        Here ``stream`` yields micro-batches; each micro-batch is
        partitioned like :meth:`train` and its partitions are handed
        round-robin to persistent per-worker feeder threads, so feeding
        micro-batch *k+1* overlaps with workers still consuming *k*.

        Returns when the stream is exhausted or every worker has entered
        the ``terminating`` state (early stop). The stream may be infinite;
        call :meth:`shutdown` from another thread (or let the workers call
        ``DataFeed.terminate``) to end training. The stream generator runs
        in a pump thread, so worker termination and feeder errors are
        noticed within ~5 s even while the source is quiet between
        micro-batches (a slow generator itself cannot be interrupted
        mid-``next()``, only abandoned).
        """
        self._require_spark_mode("train_stream")
        workers = self.workers
        errors: list[BaseException] = []
        work_qs: list[Any] = []
        feeders: list[threading.Thread] = []
        terminated = [False] * len(workers)
        pump_done = threading.Event()
        pump_stop = threading.Event()
        # Bounded so an unbounded stream can't buffer itself into the
        # driver's memory.
        micro_q: _stdqueue.Queue = _stdqueue.Queue(maxsize=2)

        def pump() -> None:
            try:
                for micro_batch in stream:
                    while not pump_stop.is_set():
                        try:
                            micro_q.put(micro_batch, timeout=1.0)
                            break
                        except _stdqueue.Full:
                            continue
                    if pump_stop.is_set():
                        return
            except BaseException as e:  # noqa: BLE001 - ferried to caller
                errors.append(e)
            finally:
                pump_done.set()

        def feed_worker(widx: int) -> None:
            # NOTE: deliberately no feed_timeout KV publish here (unlike
            # train): a stream is allowed to be quiet for arbitrary
            # stretches, so the consumer pull must stay unbounded.
            try:
                mgr = tfnode_runtime.connect_manager(workers[widx])
                while True:
                    part = work_qs[widx].get()
                    if part is None:
                        return
                    fed = tfnode_runtime.feed_partition(
                        mgr,
                        part,
                        feed_timeout=feed_timeout,
                        qname=qname,
                        node=workers[widx],
                        columnar=self.columnar,
                    )
                    if fed is None:  # node terminating; partition skipped
                        terminated[widx] = True
            except BaseException as e:  # noqa: BLE001 - ferried to caller
                errors.append(e)
                terminated[widx] = True

        for i in range(len(workers)):
            # 4 pending partitions per worker keeps the pipeline full
            # across micro-batch boundaries.
            work_qs.append(_stdqueue.Queue(maxsize=4))
            t = threading.Thread(target=feed_worker, args=(i,), daemon=True)
            feeders.append(t)
            t.start()
        threading.Thread(target=pump, daemon=True, name="stream-pump").start()

        def poll_node_states() -> None:
            # Worker-initiated termination (DataFeed.terminate) only flips
            # terminated[i] when a feed attempt observes it; on a quiet
            # stream no feed happens, so poll manager state directly.
            # Liveness first: a SIGKILLed node's manager port may refuse
            # (indistinguishable from clean termination below), but its
            # missed heartbeats are an unambiguous death signal that must
            # RAISE, not silently early-stop the stream.
            dead = set(self.dead_nodes())
            for i, w in enumerate(workers):
                if not terminated[i] and w["executor_id"] in dead:
                    errors.append(self._dead_error([w["executor_id"]]))
                    terminated[i] = True
            for i, w in enumerate(workers):
                if not terminated[i]:
                    try:
                        mgr = tfnode_runtime.connect_manager(w)
                        # 'finished' too: a map_fun that terminate()s and
                        # returns flips terminating -> finished immediately.
                        state = tfnode_runtime.fetch_node_state(mgr)
                        if state in ("terminating", "finished", "error"):
                            terminated[i] = True
                    except (ConnectionError, OSError, EOFError):
                        terminated[i] = True

        n_parts = 0
        last_err_check = time.monotonic()
        try:
            while not (all(terminated) or errors):
                # Node-side failures and worker-initiated termination
                # surface through the managers, not the feeder threads —
                # poll them, but at most every 5 s (each poll opens a
                # connection to every node).
                if time.monotonic() - last_err_check > 5.0:
                    self._check_errors()
                    poll_node_states()
                    last_err_check = time.monotonic()
                try:
                    micro_batch = micro_q.get(timeout=1.0)
                except _stdqueue.Empty:
                    if pump_done.is_set() and micro_q.empty():
                        break
                    continue
                for part in _as_partitions(micro_batch, len(workers)):
                    if not part:
                        continue  # empty partition: nothing to feed
                    widx = n_parts % len(workers)
                    n_parts += 1
                    while not terminated[widx] and not errors:
                        try:
                            work_qs[widx].put(part, timeout=1.0)
                            break
                        except _stdqueue.Full:
                            continue
        finally:
            pump_stop.set()
            for q, t in zip(work_qs, feeders):
                # A dead feeder no longer drains its (bounded) queue, so an
                # unconditional put could block forever — poll instead.
                # After an error (including a liveness failure) the
                # poison-put and join are BOUNDED: a feeder blocked
                # mid-push to a wedged node would otherwise hang this
                # cleanup forever, exactly the wait the liveness plane
                # exists to cut short (the feeders are daemons).
                give_up = (
                    time.monotonic() + 2.0 if errors else float("inf")
                )
                while t.is_alive() and time.monotonic() < give_up:
                    try:
                        q.put(None, timeout=1.0)
                        break
                    except _stdqueue.Full:
                        continue
            for t in feeders:
                t.join(2.0 if errors else None)
        if errors:
            self._check_errors()
            raise errors[0]
        self._check_errors()

    def inference(
        self,
        data: Iterable,
        feed_timeout: float = 600.0,
        qname: str = "input",
    ) -> list[Any]:
        """Feed partitions and gather results, preserving input order.

        Reference: ``TFCluster.inference`` → ``TFSparkNode._inference``.
        Equal-count contract: the user fn must emit exactly one result per
        input record via ``DataFeed.batch_results``.
        """
        # mode check BEFORE draining data: misuse on a TENSORFLOW-mode
        # cluster must raise promptly, not block on an unbounded iterable
        self._require_spark_mode("inference")
        # contiguous: partition-order reassembly then preserves flat
        # input order end-to-end
        partitions = _as_partitions(data, len(self.workers), contiguous=True)
        return list(
            self.inference_stream(
                partitions, feed_timeout=feed_timeout, qname=qname
            )
        )

    def inference_stream(
        self,
        partitions: Iterable,
        feed_timeout: float = 600.0,
        qname: str = "input",
    ):
        """Streaming :meth:`inference`: pull record-list partitions lazily
        from an iterable and yield results in partition order as they
        complete.

        Memory contract (the scale fix the reference got from
        ``mapPartitions``, SURVEY §3.4): the input is never materialized
        — workers stay at most ``2 × num_workers`` partitions ahead of
        the consumer (in-flight work plus reorder slack), so a slow
        consumer throttles the pulls instead of the whole source
        buffering in the reorder dict. Closing the generator early
        (``break`` / ``.close()``) stops further pulls; it waits only
        for each worker's current in-flight partition, not the rest of
        the source. Unlike :meth:`inference`, ``partitions`` is taken
        as-is (every element IS one record-list partition); no
        flat-input convention detection, which would need the whole
        input up front.
        """
        self._require_spark_mode("inference")
        workers = self.workers
        source = enumerate(iter(partitions))
        results: dict[int, list[Any]] = {}
        errors: list[BaseException] = []
        finished = [0]
        # head = next partition index to deliver; taken = indices handed
        # to workers; stop = consumer gone, pull no more
        state = {"head": 0, "taken": 0, "stop": False}
        max_ahead = 2 * len(workers)
        cond = threading.Condition()

        def next_partition():
            with cond:  # cond's lock doubles as the source lock
                while (
                    not state["stop"]
                    and not errors
                    and state["taken"] - state["head"] >= max_ahead
                ):
                    cond.wait(1.0)  # backpressure: consumer is behind
                if state["stop"] or errors:
                    return None
                item = next(source, None)
                if item is not None:
                    state["taken"] = item[0] + 1
                return item

        def run_worker(widx: int) -> None:
            # no feed_timeout KV publish: inference_stream throttles
            # workers when the RESULT consumer lags, so the node's input
            # queue legitimately goes quiet for as long as the consumer
            # pleases — a consumer-side pull bound would misread that
            # backpressure as producer death.
            try:
                mgr = tfnode_runtime.connect_manager(workers[widx])
                while True:
                    item = next_partition()
                    if item is None:
                        return
                    pidx, part = item
                    part = list(part)
                    fed = tfnode_runtime.feed_partition(
                        mgr,
                        part,
                        feed_timeout=feed_timeout,
                        qname=qname,
                        node=workers[widx],
                        columnar=self.columnar,
                    )
                    if fed is None:  # node terminating; partition skipped
                        with cond:
                            results[pidx] = []
                            cond.notify_all()
                        continue
                    out = tfnode_runtime.collect_results(
                        mgr, fed, timeout=feed_timeout
                    )
                    with cond:
                        results[pidx] = out
                        cond.notify_all()
            except BaseException as e:  # noqa: BLE001
                with cond:
                    errors.append(e)
                    cond.notify_all()
            finally:
                with cond:
                    finished[0] += 1
                    cond.notify_all()

        threads = [
            threading.Thread(target=run_worker, args=(i,), daemon=True)
            for i in range(len(workers))
        ]
        for t in threads:
            t.start()
        try:
            while True:
                with cond:
                    head = state["head"]
                    while (
                        head not in results
                        and not errors
                        and finished[0] < len(threads)
                    ):
                        cond.wait(1.0)
                        dead = self.dead_nodes()
                        if dead:
                            errors.append(self._dead_error(dead))
                    if errors:
                        break
                    if head in results:
                        out = results.pop(head)
                        state["head"] = head + 1
                        cond.notify_all()  # frees throttled workers
                    else:  # finished[0] >= len(threads): source drained
                        break
                # yield OUTSIDE the lock: a slow consumer must not stall
                # workers posting results
                yield from out
        finally:
            # normal exhaustion, an error, or the consumer closing the
            # generator early: stop further pulls, then wait out only
            # the in-flight partitions
            with cond:
                state["stop"] = True
                cond.notify_all()
            for t in threads:
                # After an error (including a liveness failure) the
                # (daemon) workers may be mid-push to a dead node —
                # abandon them instead of riding out feed_timeout.
                t.join(2.0 if errors else None)
        if errors:
            self._check_errors()
            raise errors[0]
        self._check_errors()

    # ------------------------------------------------------------------
    # pull plane (driverless sharded ingestion — feed/ingest.py)
    def assign_shards(
        self,
        manifests: Iterable[Any],
        *,
        seed: int | None = None,
        epoch: int = 0,
        split: int = 1,
    ) -> None:
        """Plan and publish the pull plane's shard assignment
        (``InputMode.TENSORFLOW`` only): ``manifests`` (typically
        :class:`~tensorflowonspark_tpu.feed.manifest.FileManifest`
        records — a path and a format, O(files) driver bytes) are
        round-robin split across the workers
        (``feed.manifest.plan_manifests``) and each worker's shard is
        published to its manager KV. Nodes consume via
        ``ctx.get_ingest_feed()`` — the driver never touches the data
        again. Use ``feed.manifest.split_manifest`` first when one
        large file must feed many nodes.

        With the handover protocol armed (``elastic=True`` +
        ``ingest_handover``, the default), the plan FOLLOWS membership:
        every reconfigure re-splits the *remaining* records over the
        survivors from the consumers' published replay cursors
        (:meth:`_redistribute_ingest_plan`) — no shard is ever left
        unread by a permanent shrink, and a joiner picks up real work.

        With handover off (``ingest_handover=False``, or a non-elastic
        cluster), assignment is computed ONCE and is then **stable per
        executor id**: an elastic reconfigure re-publishes each active
        executor's ORIGINAL shard — a replacement for executor *k*
        (``launch_replacement`` reuses the id) fetches *k*'s shard and
        seeds its predecessor's persisted replay cursor
        (``IngestFeed.seed_cursor``). A shard whose executor id has no
        active owner is then logged loudly as UNREAD (and counted in
        the ``ingest_unread_shards`` gauge) — the recorded limitation
        the handover protocol exists to remove.

        ``seed``/``epoch``/``split`` thread the per-epoch seeded
        shuffle (``feed.manifest.plan_manifests``): the SAME
        (seed, epoch) pair always re-derives the same plan — cursor-
        exact resume composes with ``reshuffle_each_iteration`` — and
        each epoch's manifests carry epoch-folded stream ids, so one
        ``assign_shards(..., seed=s, epoch=e)`` + drain cycle per
        epoch gives pull-mode training a fresh deterministic
        permutation per pass.
        """
        if self.input_mode != InputMode.TENSORFLOW:
            raise RuntimeError(
                "assign_shards() requires InputMode.TENSORFLOW — in "
                "InputMode.SPARK the driver pushes records itself "
                "(use train(), or ManifestFeed for node-local reads)"
            )
        from tensorflowonspark_tpu.feed.manifest import plan_manifests

        workers = self.workers
        shards = plan_manifests(
            list(manifests), len(workers), seed=seed, epoch=epoch,
            split=split,
        )
        with self._ingest_lock:
            self._ingest_shards = {
                w["executor_id"]: shard for w, shard in zip(workers, shards)
            }
            # a fresh assignment is a fresh dataset: a completion
            # latched by the PREVIOUS dataset must neither suppress
            # this one's completion nor prematurely release its
            # consumers at the next reconfigure
            self._ingest_complete = False
            self._ingest_republished = False
            # a fresh dataset is also a fresh plan generation (never a
            # reset: the seq must stay monotonic per membership epoch
            # so consumers can order publications)
            self._ingest_seq += 1
        failed = self._publish_ingest_plan()
        if failed:
            # At ASSIGN time a publish failure is the caller's problem
            # (the pre-handover behavior): without a plan, consumers
            # block the full fetch timeout blaming a missing
            # assign_shards call. Reconfigure-time republishes stay
            # best-effort (the next bump retries).
            raise RuntimeError(
                f"ingest: plan publish failed for node(s) {failed} — "
                "no consumer on those nodes will receive a shard"
            )

    def extend_shards(self, manifests: Iterable[Any]) -> None:
        """APPEND manifests to the RUNNING plan (the growing-dataset
        wire — docs/ROBUSTNESS.md "Online continual loop"): the new
        manifests are dealt round-robin across the current workers,
        each worker's cumulative shard is republished under the SAME
        membership epoch with a bumped plan generation (``seq``), and
        a lingering consumer (exhaustion-linger) adopts exactly the
        appended streams instead of completing. Active consumers are
        never interrupted — they discover the growth at their own
        exhaustion. Requires the handover protocol (``elastic=True`` +
        ``ingest_handover``): without the linger there is no consumer-
        side hook to hand appended work to."""
        if self.input_mode != InputMode.TENSORFLOW:
            raise RuntimeError(
                "extend_shards() requires InputMode.TENSORFLOW"
            )
        if not self._handover_armed:
            raise RuntimeError(
                "extend_shards() requires the handover protocol "
                "(elastic=True + ingest_handover) — a static plan has "
                "no lingering consumers to adopt appended shards"
            )
        new = list(manifests)
        if not new:
            return
        # Serialize the whole append against a concurrent epoch
        # re-split: interleaving their read-modify-write cycles could
        # publish a plan missing either the appended shards or the
        # re-split (both are zero-gap violations).
        with self._ingest_replan_lock:
            workers = self.workers
            if not workers:
                logger.warning(
                    "ingest: no live workers to extend the plan to — "
                    "appended manifests deferred to the next call"
                )
                return
            from tensorflowonspark_tpu.feed.manifest import plan_manifests

            shards = plan_manifests(new, len(workers))
            with self._ingest_lock:
                if self._ingest_shards is None:
                    self._ingest_shards = {}
                base = self._ingest_shards
                for w, shard in zip(workers, shards):
                    eid = w["executor_id"]
                    base[eid] = list(base.get(eid, ())) + list(shard)
                self._ingest_seq += 1
                seq = self._ingest_seq
                # appended work un-latches a completed dataset: the
                # grown plan must complete on ITS OWN finals
                self._ingest_complete = False
            logger.info(
                "ingest: extended plan with %d manifest(s) over %d "
                "worker(s) (seq %d)",
                len(new),
                len(workers),
                seq,
            )
            self._publish_ingest_plan()

    def hold_ingest_completion(self, hold: bool = True) -> None:
        """Suppress (or release) the supervise loop's auto-completion
        of the ingest plan: an online loop's dataset is never "as
        consumed as it will ever be" while traffic still flows, so
        all-finals must not release the lingering consumers between
        growth cycles. :meth:`shutdown` force-releases regardless."""
        with self._ingest_lock:
            self._ingest_hold_completion = bool(hold)

    def run_online(self, log_root: str, **kw: Any) -> Any:
        """Start the continual-training loop over a live traffic log
        (``tfos.online``): holds ingest completion open, then polls
        ``log_root`` for sealed traffic-log manifests and appends them
        to the running plan via :meth:`extend_shards` on a daemon
        thread. Keyword arguments pass through to
        :class:`tensorflowonspark_tpu.online.OnlineLoop` (notably
        ``channel_dir=`` — the rollout channel whose published
        ``weights_version`` is the trainer-progress signal for stall
        detection). Returns the started loop; call ``.stop()`` to end
        it (releasing the hold so the run can drain), or let
        :meth:`shutdown` force-release. Run :meth:`supervise` alongside
        — growth publication rides the same plan machinery elastic
        reshards use."""
        if not self._handover_armed:
            raise RuntimeError(
                "run_online() requires the handover protocol "
                "(elastic=True + ingest_handover)"
            )
        from tensorflowonspark_tpu.online import OnlineLoop

        return OnlineLoop(self, log_root, **kw).start()

    @property
    def _handover_armed(self) -> bool:
        return self.elastic and self.ingest_handover

    def _publish_ingest_plan(self, complete: bool = False) -> list[int]:
        """Publish the current plan to every live worker's manager KV;
        returns the executor ids whose publish failed after retries
        (callers decide whether that is fatal — assign time — or
        best-effort — reconfigure time)."""
        workers = self.workers
        epoch = self.membership_epoch()
        with self._ingest_lock:
            shards = {
                k: list(v) for k, v in (self._ingest_shards or {}).items()
            }
            republish = self._ingest_republished
            self._ingest_republished = True
            seq = self._ingest_seq
        # Never RPC a node the liveness plane declared dead: a wedged
        # process's kernel still accepts the connect and hangs the
        # handshake (same rule as shutdown/_check_errors).
        dead = set(self.dead_nodes())
        failed: list[int] = []
        from tensorflowonspark_tpu.utils.retry import RetryPolicy

        # A re-split plan is load-bearing: the consumer is blocked in
        # plan_fetch(min_epoch) and a lost publish escalates to a node
        # TimeoutError after adopt_timeout — so transient RPC blips are
        # retried here (short, bounded) rather than merely logged.
        policy = RetryPolicy(
            max_attempts=3, base_delay=0.1, max_delay=0.5, deadline_s=5.0
        )
        for w in workers:
            eid = w["executor_id"]
            if eid in dead:
                continue
            try:
                policy.call(
                    lambda w=w, eid=eid: tfnode_runtime.publish_ingest_plan(
                        tfnode_runtime.connect_manager(w),
                        shards.get(eid, []),
                        epoch=epoch,
                        shard_index=eid,
                        num_shards=len(shards),
                        plan_id=self.cluster_meta.get("id"),
                        handover=self._handover_armed,
                        complete=complete,
                        seq=seq,
                    ),
                    retry_on=(ConnectionError, OSError, EOFError),
                    site="ingest.plan_publish",
                )
            except (ConnectionError, OSError, EOFError) as e:
                failed.append(eid)
                logger.warning(
                    "ingest: plan publish to node %s failed (%s)", eid, e
                )
        unowned = sorted(
            set(shards) - {w["executor_id"] for w in workers}
        )
        if unowned:
            logger.warning(
                "ingest: shard(s) of departed executor(s) %s have no "
                "active owner — their manifests are UNREAD until a "
                "replacement with the same id rejoins",
                unowned,
            )
        reg = default_registry()
        # the log-only UNREAD warning, as a scrapeable signal (0 when
        # every shard has an owner — the gauge must CLEAR on recovery)
        reg.gauge(
            "ingest_unread_shards",
            "published shards with no active owner (manifests unread "
            "until a replacement rejoins); nonzero is data loss in "
            "progress",
        ).set(len(unowned))
        from tensorflowonspark_tpu.feed.ingest import metrics as _ing_metrics

        _ing_metrics()["plan_epoch"].set(epoch)
        flightrec.note(
            "ingest_plan_republish" if republish else "ingest_plan",
            epoch=epoch,
            seq=seq,
            shards={k: len(v) for k, v in shards.items()},
            unowned=unowned,
            complete=complete,
            publish_failed=failed,
        )
        if republish:
            # a republish is always part of an incident (membership
            # change / completion) — leave the postmortem artifact now
            flightrec.dump_now("ingest_plan_republish")
        logger.info(
            "ingest plan published: %d shard(s) over %d worker(s) "
            "(epoch %d%s)",
            len(shards),
            len(workers),
            epoch,
            ", complete" if complete else "",
        )
        return failed

    def publish_feed_knobs(self, **knobs: Any) -> list[int]:
        """Driver-side autotune actuation for NODE-side feed knobs
        (currently ``publish_blocks``): re-publish the tuned values to
        every live worker's manager KV under a fresh monotonically
        increasing seq. Each node's ``IngestFeed`` polls the key at
        block boundaries and adopts a publication exactly once — a
        controller revert is simply the next publication. Best-effort
        like the plan republish: returns the executor ids whose
        publish failed (the next publication covers them)."""
        if not knobs:
            raise ValueError("publish_feed_knobs: no knobs given")
        with self._ingest_lock:
            self._feed_knob_seq += 1
            seq = self._feed_knob_seq
        dead = set(self.dead_nodes())
        failed: list[int] = []
        for w in self.workers:
            eid = w["executor_id"]
            if eid in dead:
                continue
            try:
                tfnode_runtime.publish_feed_knobs(
                    tfnode_runtime.connect_manager(w), knobs, seq=seq
                )
            except (ConnectionError, OSError, EOFError) as e:
                failed.append(eid)
                logger.warning(
                    "feed knobs publish to node %s failed (%s) — the "
                    "next publication covers it",
                    eid,
                    e,
                )
        logger.info(
            "feed knobs published (seq %d): %s%s",
            seq,
            knobs,
            f"; failed for {failed}" if failed else "",
        )
        return failed

    def _await_handover_cursors(
        self, epoch: int, fresh_ids: "set[int] | frozenset" = frozenset()
    ) -> dict[int, dict]:
        """Bounded wait for every live, actively-consuming worker to
        drain and publish a cursor stamped >= ``epoch``. Dead nodes
        cannot publish (their last periodic cursor is the seed — the
        crash-handover duplicate bound), ``done`` consumers (final or
        terminated) will never publish again and their content is
        already exact, and a straggler past ``handover_timeout``
        degrades to its last cursor with a loud warning — duplicates
        bounded by the staleness, zero-gap untouched either way.
        ``fresh_ids`` are executor ids admitted by THIS reconfigure: a
        cursor retained under such an id belongs to a dead predecessor
        (the replacement is still blocked waiting for the very plan
        this wait precedes) — waiting on it would stall every
        crash→rejoin handover for the full timeout."""
        res = self.server.reservations
        active = {w["executor_id"] for w in self.workers}
        deadline = time.monotonic() + self.handover_timeout
        while True:
            cursors = res.cursors()
            waiting = sorted(
                eid
                for eid, p in cursors.items()
                if eid in active
                and eid not in fresh_ids
                and not p.get("final")
                and not p.get("done")
                and int(p.get("epoch", 0)) < epoch
            )
            if not waiting:
                return cursors
            if time.monotonic() >= deadline:
                logger.warning(
                    "ingest: handover drain timed out after %.1fs "
                    "waiting for node(s) %s — proceeding with their "
                    "last published cursors (duplicates bounded by the "
                    "staleness; zero-gap unaffected)",
                    self.handover_timeout,
                    waiting,
                )
                return cursors
            time.sleep(0.1)

    def _redistribute_ingest_plan(
        self, epoch: int, fresh_ids: "set[int] | frozenset" = frozenset()
    ) -> None:
        """The tentpole: make the ingest plan follow membership. Wait
        for the cooperative drain, merge every published cursor
        (departed nodes' last publications included), re-split the
        REMAINING records over the surviving workers, and publish the
        new plan keyed by the membership epoch. The whole episode runs
        under the replan lock so a concurrent growth append
        (:meth:`extend_shards`) cannot interleave with the re-split's
        read-modify-write."""
        with self._ingest_replan_lock:
            self._redistribute_ingest_plan_locked(epoch, fresh_ids)

    def _redistribute_ingest_plan_locked(
        self, epoch: int, fresh_ids: "set[int] | frozenset" = frozenset()
    ) -> None:  # lint: holds-lock
        from tensorflowonspark_tpu.feed.manifest import (
            merge_cursor_payloads,
            replan_manifests,
            stream_id,
        )

        cursors = self._await_handover_cursors(epoch, fresh_ids=fresh_ids)
        merged = merge_cursor_payloads(cursors.values())
        active = sorted(w["executor_id"] for w in self.workers)
        if not active:
            logger.warning(
                "ingest: no surviving workers to redistribute to"
            )
            return
        # A TERMINATED consumer (done, not final — deliberate early
        # stop) will never read again: assigning it work would leave
        # that work unread forever. Deal only to workers that still
        # consume; if none remain, fall back to all (the completion
        # check accepts terminated consumers, so nothing hangs).
        consuming = [
            eid
            for eid in active
            if not (
                (p := cursors.get(eid)) is not None
                and p.get("done")
                and not p.get("final")
            )
        ]
        if consuming:
            active = consuming
        with self._ingest_lock:
            old = self._ingest_shards or {}
        # A FINAL publication proves exactly one thing: the shard its
        # publisher CURRENTLY owns is exhausted. Consumers keep
        # consumed-state for streams from earlier plan generations
        # forever (the restart-seeding contract), so a final's cursor
        # may name streams now owned — and still mid-read — by someone
        # else; marking those final would drop their unconsumed
        # remainder (a zero-gap violation). Scope each node's finals
        # to the streams of ITS current shard.
        finals = {
            sid
            for eid, p in cursors.items()
            if p.get("final")
            for sid in (
                {stream_id(m) for m in old.get(eid, ())}
                & set(p.get("cursor") or {})
            )
        }
        # The re-split's header scans (scan_frames — the only point the
        # driver touches data files) run OUTSIDE _ingest_lock: slow or
        # flaky storage must never wedge shutdown()'s force-complete or
        # a concurrent assign behind this lock.
        try:
            new = replan_manifests(old, merged, active, final_streams=finals)
        except (OSError, ValueError) as e:
            # A transient storage blip here — plausibly correlated with
            # the very failure being handled — must degrade, not crash
            # supervise(): republish the CURRENT plan at the new epoch.
            # Consumers drain and re-adopt identical shards; their
            # reseeded cursors dedupe the re-read, so correctness holds
            # and only the redistribution is deferred.
            logger.warning(
                "ingest: re-split failed (%s); republishing the "
                "current plan unchanged at epoch %d",
                e,
                epoch,
            )
            new = old
        with self._ingest_lock:
            if (self._ingest_shards or {}) is not old:
                # a concurrent assign_shards superseded this plan while
                # we were re-planning; its fresh publish wins
                logger.warning(
                    "ingest: plan reassigned mid-redistribution; "
                    "dropping the stale re-split"
                )
                return
            moved = sum(
                1 for eid in new if new[eid] != old.get(eid, [])
            )
            self._ingest_shards = new
        default_registry().counter(
            "ingest_redistributed_shards_total",
            "node shards whose manifest set changed in a live "
            "redistribution",
        ).inc(moved)
        logger.warning(
            "ingest: redistributed remaining records over %d worker(s) "
            "at epoch %d (%d shard(s) changed)",
            len(active),
            epoch,
            moved,
        )
        self._publish_ingest_plan()

    def _maybe_complete_ingest(self) -> None:
        """Supervise-loop completion check: once every active worker's
        latest cursor is FINAL at the current epoch — or the worker
        TERMINATED (deliberate early stop; it will never consume again
        and must not gate the others) — the current plan is as consumed
        as it will ever be: publish the completion marker so lingering
        consumers (waiting to absorb more work) stop. Flag-based, not
        block-math-based: a final publication is the consumer's own
        exhaustion proof."""
        with self._ingest_lock:
            if (
                self._ingest_shards is None
                or self._ingest_complete
                # online mode: the dataset is still growing — never
                # auto-release the lingering consumers (shutdown
                # force-completes regardless)
                or self._ingest_hold_completion
            ):
                return
            seq = self._ingest_seq
        if not self._handover_armed:
            return
        epoch = self.membership_epoch()
        cursors = self.server.reservations.cursors()
        active = [w["executor_id"] for w in self.workers]
        if not active:
            return
        for eid in active:
            p = cursors.get(eid)
            if p is None:
                return
            if p.get("done") and not p.get("final"):
                continue  # terminated: never publishes again
            if not p.get("final") or int(p.get("epoch", 0)) < epoch:
                return
            if int(p.get("plan_seq") or 0) < seq:
                # a final published BEFORE the last append proves only
                # the pre-growth dataset was consumed — the grown plan
                # must earn its own finals
                return
        self._finish_ingest_plan()

    def _finish_ingest_plan(self) -> None:
        """Publish the completion marker (idempotent): lingering
        consumers see ``complete`` on their next plan poll and stop.
        Also forced by :meth:`shutdown` so a teardown without
        supervision can never leave consumers lingering."""
        with self._ingest_lock:
            if self._ingest_shards is None or self._ingest_complete:
                return
            self._ingest_complete = True
        armed = self._handover_armed
        if not armed:
            return
        logger.info("ingest: plan complete — releasing consumers")
        self._publish_ingest_plan(complete=True)

    # ------------------------------------------------------------------
    def membership_epoch(self) -> int:
        """The current membership epoch (0 = the startup roster; bumped
        once per reconfigure — see :meth:`supervise` elastic mode)."""
        return self.server.reservations.epoch()

    def launch_replacement(self, executor_id: int, map_fun, tf_args) -> None:
        """Spawn a replacement node process for a departed executor id
        (local-launcher path). The process registers with the running
        reservation server like any node; elastic :meth:`supervise`
        notices the pending registration and admits it with an epoch
        bump. The replacement's ``map_fun`` typically hydrates via
        ``ElasticTrainer.hydrate()`` before training."""
        if executor_id not in self._snapshot_departed():
            raise ValueError(
                f"executor {executor_id} has not departed; replacements "
                "are for elastically-removed members only"
            )
        launcher = LocalLauncher(env=self._node_env)
        launcher._replaces = executor_id
        launcher.launch(
            1,
            tfnode_runtime.run_node,
            lambda _i: (executor_id, map_fun, tf_args, self.cluster_meta),
        )
        self._replacement_launchers.append(launcher)

    def _snapshot_departed(self) -> set[int]:
        with self._dead_lock:
            return set(self._departed)

    def _reconfigure(
        self,
        departed: list[int],
        joined: list[dict[str, Any]],
    ) -> int:
        """Drive one membership change: remove the departed, admit the
        joiners, bump the epoch (published to every survivor via the
        next heartbeat reply), and leave the audit trail — flight
        record + ``cluster_membership_epoch`` gauge."""
        from tensorflowonspark_tpu.utils.failpoints import failpoint

        failpoint("elastic.epoch_bump")
        res = self.server.reservations
        for eid in departed:
            res.remove(eid)
        with self._dead_lock:
            self._departed.update(departed)
            for m in joined:
                # A readmitted executor id is a full member again: its
                # exit codes count, and a second death must re-count.
                self._departed.discard(m["executor_id"])
                self._counted_dead.discard(m["executor_id"])
        epoch = res.bump_epoch()
        self.cluster_info = res.active()
        reg = default_registry()
        reg.gauge(
            "cluster_membership_epoch",
            "current membership epoch (bumped on every reconfigure)",
        ).set(epoch)
        flightrec.note(
            "elastic_epoch_bump",
            epoch=epoch,
            departed=sorted(departed),
            joined=sorted(m["executor_id"] for m in joined),
            nodes=sorted(n["executor_id"] for n in self.cluster_info),
        )
        flightrec.dump_now("elastic_epoch_bump")
        logger.warning(
            "elastic: membership epoch %d — departed %s, joined %s, "
            "%d node(s) remain",
            epoch,
            sorted(departed),
            sorted(m["executor_id"] for m in joined),
            len(self.cluster_info),
        )
        # Make the ingest plan follow membership. Handover armed (the
        # default): REDISTRIBUTE — wait for the cooperative drain, then
        # re-split the remaining records over the survivors (zero
        # shards left unread by a permanent shrink). Handover off: the
        # PR-8 fallback — re-publish each active id's stable shard
        # (content never changes, so a mid-loop failure is harmless; a
        # replacement fetches its predecessor's shard + disk cursor).
        with self._ingest_lock:
            has_plan = self._ingest_shards is not None
            plan_done = self._ingest_complete
        if has_plan and plan_done:
            # A joiner admitted AFTER dataset completion must still
            # learn the dataset is done — its fresh manager KV has no
            # plan, and it would otherwise block in fetch_ingest_plan.
            self._publish_ingest_plan(complete=True)
        elif has_plan:
            if self._handover_armed:
                self._redistribute_ingest_plan(
                    epoch,
                    fresh_ids={m["executor_id"] for m in joined},
                )
            else:
                try:
                    self._publish_ingest_plan()
                except (ConnectionError, OSError, EOFError) as e:
                    logger.warning(
                        "elastic: ingest plan re-publish failed (%s); "
                        "a rejoining node must wait for the next "
                        "reconfigure to fetch its shard",
                        e,
                    )
        return epoch

    def _elastic_scan(self) -> bool:
        """One elastic supervision round: detect departures (process
        exits + liveness) and pending joins; reconfigure when membership
        moved. Returns True if a reconfigure happened. Raises when the
        surviving membership would fall below ``elastic_min_nodes`` —
        at that point restart (the PR-4 path) is the only recovery."""
        active_ids = {n["executor_id"] for n in self.cluster_info}
        exit_codes = self.launcher.exitcodes()
        departed = set()
        for eid in active_ids:
            if (
                eid < len(exit_codes)
                and exit_codes[eid] is not None
                and exit_codes[eid] != 0
                and not self._is_replacement(eid)
            ):
                departed.add(eid)
        departed.update(
            eid for eid in self.dead_nodes() if eid in active_ids
        )
        joined = self.server.reservations.pending_joins()
        if not departed and not joined:
            return False
        survivors = len(active_ids) - len(departed) + len(joined)
        if survivors < self.elastic_min_nodes:
            raise RuntimeError(
                f"elastic supervision: {sorted(departed)} departed, "
                f"leaving {survivors} node(s) — below elastic_min_nodes="
                f"{self.elastic_min_nodes}; restart is the only recovery"
            )
        self._note_dead(sorted(departed))
        self._reconfigure(sorted(departed), joined)
        return True

    def _is_replacement(self, executor_id: int) -> bool:
        """True when a replacement process owns this executor id (alive,
        or exited cleanly) — the primary launcher's dead exit code for
        that slot is then history, not a departure/pending signal. Only
        the LATEST replacement for the id counts: its predecessors'
        fates are already-handled membership history."""
        for launcher in reversed(self._replacement_launchers):
            if getattr(launcher, "_replaces", None) != executor_id:
                continue
            # launch_replacement launches exactly one process per
            # launcher; alive or exited-0 means the id is owned.
            codes = launcher.exitcodes()
            return bool(codes) and (codes[0] is None or codes[0] == 0)
        return False

    def supervise(self, poll: float = 2.0) -> None:
        """Block until every node reaches a terminal state, failing FAST
        on a dead node — or, in **elastic** mode (``run(elastic=True)``),
        answering membership changes with a reconfigure instead of a
        failure.

        Non-elastic (the default): the watch loop ``run_with_restarts``
        runs between startup and teardown — it raises RuntimeError
        within ~``poll`` seconds of a node process exiting nonzero, and
        within ``heartbeat_grace`` of a node going silent (SIGKILL,
        kernel OOM, network partition — cases where the process table
        can't tell the driver anything). Without it, a dead node
        surfaced only when ``shutdown``'s watchdog expired.

        Elastic: a departed node (process exit or missed heartbeats) is
        REMOVED from membership and the epoch bumps; a pending mid-run
        registration (a replacement or voluntary joiner) is ADMITTED,
        bumping the epoch again. Survivors learn each bump within one
        heartbeat and reshard in place (``compute/elastic.py``).
        Raises only when membership would fall below
        ``elastic_min_nodes``. Returns once every ACTIVE node is
        ``finished``/``error`` (or exited cleanly), at which point
        :meth:`shutdown` completes promptly.
        """
        # Terminal states are cached: a node observed finished/error
        # never needs another manager RPC. Non-terminal nodes are
        # probed IN PARALLEL on a slower cadence than the (cheap)
        # process/liveness checks — one shared probe window per round,
        # so a single wedged node cannot serialize the loop, and far
        # fewer probe threads over a long run.
        terminal: dict[int, str] = {}
        state_poll = max(poll, 5.0)
        next_state_probe = 0.0
        while True:
            if self.elastic:
                if self._elastic_scan():
                    # Membership moved: stale terminal cache entries for
                    # readmitted ids must not mask a fresh process.
                    active = {n["executor_id"] for n in self.cluster_info}
                    terminal = {
                        k: v for k, v in terminal.items() if k in active
                    }
                # Handover consumers LINGER after exhausting their
                # shard (they may yet absorb a dead peer's remainder);
                # once every active consumer is final at the current
                # epoch, release them.
                self._maybe_complete_ingest()
            else:
                failed = self.launcher.poll_failed()
                if failed:
                    raise RuntimeError(
                        f"node process(es) {failed} died mid-run "
                        "(exited nonzero)"
                    )
                self._check_liveness()
            exit_codes = self.launcher.exitcodes()
            pending = [
                n
                for n in self.cluster_info
                if n["executor_id"] not in terminal
                and not (
                    n["executor_id"] < len(exit_codes)
                    and exit_codes[n["executor_id"]] == 0
                    and not self._is_replacement(n["executor_id"])
                )
            ]
            if not pending:
                return
            if time.monotonic() >= next_state_probe:
                next_state_probe = time.monotonic() + state_poll
                for n, state in zip(
                    pending, _probe_node_states(pending, timeout=10.0)
                ):
                    # "hung" (no answer in the window: a wedging node —
                    # liveness passes judgment next poll) and
                    # "unreachable" (manager gone but process not
                    # failed: about to exit cleanly or to miss
                    # heartbeats) both stay pending.
                    if state in ("finished", "error"):
                        terminal[n["executor_id"]] = state
            time.sleep(poll)

    # ------------------------------------------------------------------
    def shutdown(
        self,
        grace_secs: float = 0.0,
        timeout: float = 259200.0,
        ssc=None,
    ) -> None:
        """Graceful teardown with a force-kill watchdog.

        Reference: ``TFCluster.shutdown`` (await streaming termination if
        an ``ssc`` is given → grace sleep → terminal markers on every
        queue → join nodes → watchdog force-terminate → reservation
        STOP). Raises if any node ferried an exception or exited nonzero.
        """
        if self._shutdown_done:
            return
        stream_error: BaseException | None = None
        if ssc is not None:
            ssc.stop()
            try:
                ssc.awaitTermination(timeout=timeout)
            except BaseException as e:  # noqa: BLE001 - raised after teardown
                stream_error = e
        if self._dstream_bridge is not None:
            try:
                self._drain_dstream()
            except BaseException as e:  # noqa: BLE001 - raised after teardown
                stream_error = stream_error or e
        if grace_secs:
            time.sleep(grace_secs)

        # Dead (wedged) nodes are excluded from every manager RPC below:
        # their kernels may still accept the connect and then hang the
        # handshake; the launcher watchdog force-terminates them instead.
        dead = set(self.dead_nodes())
        if dead:
            logger.warning(
                "shutdown: skipping manager RPCs to dead node(s) %s",
                sorted(dead),
            )
        # A teardown must never leave handover consumers lingering for
        # more work: force the completion marker (idempotent; no-op
        # when supervise already published it or no plan exists). The
        # online hold is released first — run_online's growing dataset
        # ends HERE, by definition.
        with self._ingest_lock:
            self._ingest_hold_completion = False
        self._finish_ingest_plan()
        node_errors = self._collect_errors(skip=dead)
        feed_queues = (
            [q for q in self.queues if q not in ("output", "error", "control")]
            if self.input_mode == InputMode.SPARK
            else []
        )
        for node_meta in self.cluster_info:
            # Every node gets the control STOP; feed-queue end markers only
            # go where feeders did (evaluator sidecars have no feed).
            if node_meta["executor_id"] in dead:
                continue
            is_worker = node_meta["job_name"] != "evaluator"
            try:
                tfnode_runtime.shutdown_node(
                    node_meta, queues=feed_queues if is_worker else ()
                )
            except (ConnectionError, OSError, EOFError) as e:
                logger.warning(
                    "could not signal node %s: %s", node_meta["executor_id"], e
                )

        if not self.launcher.wait(timeout=timeout):
            logger.error("shutdown watchdog fired after %ss; terminating", timeout)
            self.launcher.terminate()
        # Replacement nodes got the same STOP as everyone else; a short
        # bounded wait here — the primary wait above already burned the
        # caller's budget.
        for launcher in self._replacement_launchers:
            if not launcher.wait(timeout=min(timeout, 60.0)):
                launcher.terminate()
        self.server.stop()
        self._shutdown_done = True
        # Detach the observability plane: the scrape loop and the
        # registry collector both reference this (now torn down)
        # cluster and would keep refreshing stale series forever.
        if self.aggregator is not None:
            self.aggregator.stop()
        if self._driver_metrics_server is not None:
            self._driver_metrics_server.shutdown()
            self._driver_metrics_server = None
        default_registry().remove_collector(self._liveness_collector)

        # Elastically-departed executors died by design (their nonzero
        # exits ARE the membership change); a replaced slot's primary
        # exit code is history too — judge the replacement's instead.
        departed = self._snapshot_departed()
        exitcodes = self.launcher.exitcodes()
        bad = [
            (i, c)
            for i, c in enumerate(exitcodes)
            if c is not None
            and c != 0
            and i not in departed
            and not self._is_replacement(i)
        ]
        # Only the LAST replacement per executor id is judged: an
        # earlier replacement that crashed triggered its own departure
        # + readmission cycle — that exit IS membership history, and
        # counting it would fail a fully recovered run.
        last_replacement: dict[Any, Any] = {}
        for launcher in self._replacement_launchers:
            last_replacement[getattr(launcher, "_replaces", None)] = launcher
        for eid, launcher in last_replacement.items():
            if eid in departed:
                continue  # the replacement itself departed later
            bad.extend(
                (eid, c)
                for c in launcher.exitcodes()
                if c is not None and c != 0
            )
        if node_errors:
            tracebacks = "\n".join(e["traceback"] for e in node_errors)
            raise RuntimeError(f"cluster node(s) failed:\n{tracebacks}")
        if bad:
            raise RuntimeError(f"node process(es) exited nonzero: {bad}")
        if stream_error is not None:
            raise stream_error

    # ------------------------------------------------------------------
    def _require_spark_mode(self, op: str) -> None:
        if self.input_mode != InputMode.SPARK:
            raise RuntimeError(
                f"cluster.{op}() requires InputMode.SPARK; in "
                "InputMode.TENSORFLOW nodes read data themselves"
            )

    def _collect_errors(
        self, skip: "set[int] | frozenset" = frozenset()
    ) -> list[dict[str, Any]]:
        errors: list[dict[str, Any]] = []
        for node_meta in self.cluster_info:
            if node_meta["executor_id"] in skip:
                continue
            try:
                errors.extend(tfnode_runtime.drain_errors(node_meta))
            except (ConnectionError, OSError, EOFError):
                pass  # node already gone; exitcode check will catch it
        return errors

    def _check_errors(self) -> None:
        # Never open a manager connection to a node the liveness plane
        # already declared dead: a WEDGED (e.g. SIGSTOPped) process's
        # kernel still accepts the TCP connect, and the authkey
        # handshake then blocks forever — the exact hang heartbeats
        # exist to cut short.
        errs = self._collect_errors(skip=set(self.dead_nodes()))
        if errs:
            tracebacks = "\n".join(e["traceback"] for e in errs)
            try:
                self.shutdown(timeout=60)
            except RuntimeError:
                pass
            raise RuntimeError(f"cluster node(s) failed:\n{tracebacks}")


def run(
    map_fun: Callable,
    tf_args: Any,
    num_executors: int,
    num_ps: int = 0,
    tensorboard: bool = False,
    profiler: bool = False,
    metrics: bool = True,
    input_mode: int = InputMode.SPARK,
    log_dir: str | None = None,
    master_node: str | None = None,
    reservation_timeout: float = 600.0,
    queues: Sequence[str] | None = None,
    eval_node: bool = False,
    launcher=None,
    default_fs: str = "",
    working_dir: str | None = None,
    distributed: bool = False,
    queue_maxsize: int = 1024,
    env: dict[str, str] | None = None,
    use_shm_ring: bool = True,
    shm_ring_mb: int = 64,
    heartbeat_interval: float = 2.0,
    heartbeat_grace: float = 60.0,
    columnar: bool = True,
    flightrec_dir: str | None = "logs",
    elastic: bool = False,
    elastic_min_nodes: int = 1,
    ingest_handover: bool = True,
    handover_timeout: float = 30.0,
) -> TFCluster:
    """Start a cluster and return its handle.

    Reference signature parity: ``TFCluster.run(sc, map_fun, tf_args,
    num_executors, num_ps, tensorboard, input_mode, log_dir, driver_ps_nodes,
    master_node, reservation_timeout, queues, eval_node, release_port)`` —
    minus ``sc`` (the launcher replaces Spark) and minus PS knobs.
    """
    if num_ps:
        raise ValueError(
            "num_ps > 0 is not supported on TPU: parameter servers are an "
            "asymmetric-role design that SPMD cannot express. Shard optimizer "
            "state over the mesh instead (FSDP): see "
            "tensorflowonspark_tpu.compute.train and SURVEY.md §2.3."
        )
    if num_executors < 1:
        raise ValueError("num_executors must be >= 1")
    if elastic:
        # Elastic reconfigure replays data from (epoch, step) — nodes
        # must own their readers. A push feed's consumed partitions
        # cannot be reassigned by the driver (same constraint as
        # run_with_restarts).
        if input_mode != InputMode.TENSORFLOW:
            raise ValueError(
                "elastic=True requires input_mode=InputMode.TENSORFLOW "
                "(push-fed partitions cannot be replayed on reconfigure)"
            )
        if heartbeat_interval <= 0:
            raise ValueError(
                "elastic=True requires heartbeats (heartbeat_interval "
                "> 0): membership changes are detected and published "
                "through the liveness plane"
            )

    # Role template (reference: TFCluster.py:run role map). All roles are
    # mesh-symmetric workers on TPU; 'chief' marks process 0 (checkpoint
    # writer, coordinator host), 'evaluator' an optional sidecar.
    n_train = num_executors - (1 if eval_node else 0)
    if n_train < 1:
        raise ValueError("need at least one non-evaluator node")
    cluster_template: dict[str, list[int]] = {"chief": [0]}
    if n_train > 1:
        cluster_template["worker"] = list(range(1, n_train))
    if eval_node:
        cluster_template["evaluator"] = [num_executors - 1]

    server = reservation.Server(num_executors)
    server_addr = server.start()

    # The node runtime itself requires 'error' (exception ferry) and
    # 'control' (STOP); 'output' is needed by inference. Union them in so a
    # reference-style custom queue list can't break the runtime.
    queues = tuple(queues) if queues else ("input",)
    for required in ("output", "error", "control"):
        if required not in queues:
            queues = queues + (required,)
    cluster_meta: dict[str, Any] = {
        "id": secrets.token_hex(4),
        "cluster_template": cluster_template,
        "num_executors": num_executors,
        "server_addr": list(server_addr),
        "authkey": secrets.token_hex(16),
        "queues": list(queues),
        "input_mode": input_mode,
        "default_fs": default_fs,
        "working_dir": working_dir or "",
        "tensorboard": tensorboard,
        "profiler": profiler,
        # per-node Prometheus /metrics endpoint (an unauthenticated
        # read-only listener on the node host; metrics=False for
        # deployments with strict port policies — see metrics_urls())
        "metrics": metrics,
        "log_dir": log_dir,
        "reservation_timeout": reservation_timeout,
        # Liveness plane: every node heartbeats the reservation server
        # at this interval (<= 0 disables); the driver treats a node
        # silent for heartbeat_grace seconds as dead (TFCluster.
        # dead_nodes / supervise and the feed-plane checks).
        "heartbeat_interval": heartbeat_interval,
        "heartbeat_grace": heartbeat_grace,
        # Elastic plane: supervise() reconfigures (epoch bump + reshard)
        # on membership change instead of failing; below
        # elastic_min_nodes survivors it gives up and raises (restart —
        # run_with_restarts — is then the only recovery).
        "elastic": elastic,
        "elastic_min_nodes": elastic_min_nodes,
        # Live shard redistribution (docs/ROBUSTNESS.md): elastic
        # reconfigures RE-SPLIT the remaining ingest records over the
        # survivors (cooperative drain bounded by handover_timeout);
        # False falls back to PR-8 stable per-executor-id shards.
        "ingest_handover": ingest_handover,
        "handover_timeout": handover_timeout,
        "distributed": distributed,
        "queue_maxsize": queue_maxsize,
        "manager_mode": "remote",
        # Ring only pays off when a feeder will attach, i.e. SPARK mode.
        "use_shm_ring": use_shm_ring and input_mode == InputMode.SPARK,
        "shm_ring_mb": shm_ring_mb,
        # Chunk-columnar wire format (feed/columnar.py): driver feeders
        # columnize each chunk once and nodes slice zero-copy column
        # views; False = legacy row-pickle wire. TFOS_COLUMNAR=0 in the
        # driver environment forces it off too (operator escape hatch).
        "columnar": columnar and os.environ.get("TFOS_COLUMNAR", "1") != "0",
        # Run-scoped trace id: every process stamps it into its span
        # exports so driver + node timelines stitch (obs.cluster /
        # tools/trace_merge.py). The cluster id IS the trace id.
        "trace_id": None,  # filled below from "id"
        # Flight-recorder directory (None disables): each node keeps a
        # rolling logs/flightrec-node<id>.json snapshot so a SIGKILL
        # still leaves a postmortem (obs.flightrec).
        "flightrec_dir": flightrec_dir,
    }
    cluster_meta["trace_id"] = cluster_meta["id"]
    logger.info(
        "starting cluster %s: %d nodes, template %s",
        cluster_meta["id"],
        num_executors,
        cluster_template,
    )

    # Driver-side trace context + flight recorder (event-triggered: the
    # driver dumps on dead-node detection and supervised relaunches —
    # it is alive to do so; nodes roll periodic snapshots instead).
    obs_cluster.set_trace_context(cluster_meta["trace_id"], node="driver")
    if flightrec_dir:
        fr_dir = flightrec_dir
        if not os.path.isabs(fr_dir):
            fr_dir = os.path.join(working_dir or os.getcwd(), fr_dir)
        flightrec.install(
            os.path.join(fr_dir, "flightrec-driver.json"), process="driver"
        )

    if launcher is None:
        launcher = LocalLauncher()
    try:
        # env rides the launch call (never mutate a caller's launcher):
        # per-node interpreters must see it at boot, when TPU-plugin
        # sitecustomize hooks run. Custom launchers advertise support by
        # accepting an `env` kwarg; silently dropping it could let boot
        # hooks dial the chip from processes the caller wanted CPU-only,
        # so an env-less launcher + env is a loud error.
        import inspect

        sig = inspect.signature(launcher.launch).parameters
        accepts_env = "env" in sig or any(
            p.kind == p.VAR_KEYWORD for p in sig.values()
        )
        if env and not accepts_env:
            raise ValueError(
                f"launcher {type(launcher).__name__}.launch() does not "
                "accept env=; it cannot carry env vars to node processes"
            )
        launch_kwargs = {"env": env} if accepts_env else {}
        launcher.launch(
            num_executors,
            tfnode_runtime.run_node,
            lambda i: (i, map_fun, tf_args, cluster_meta),
            **launch_kwargs,
        )
    except Exception:
        launcher.terminate()
        server.stop()
        raise

    try:
        cluster_info = server.await_reservations(
            timeout=reservation_timeout,
            status_fn=lambda rem: _abort_if_node_died(launcher, rem),
        )
    except Exception:
        launcher.terminate()
        server.stop()
        raise
    logger.info("cluster %s up: %s", cluster_meta["id"], cluster_info)
    cluster = TFCluster(
        launcher, server, server_addr, cluster_info, cluster_meta, input_mode, queues
    )
    cluster._node_env = dict(env or {})
    return cluster


# Reference-compat: the reference exposes `TFCluster.run(...)` as a module
# function; callers importing our class get the same spelling.
TFCluster.run = staticmethod(run)


def run_with_restarts(
    map_fun: Callable,
    tf_args: Any,
    num_executors: int,
    max_restarts: int = 2,
    launcher_factory: Callable[[], Any] | None = None,
    shutdown_timeout: float = 259200.0,
    **run_kwargs,
) -> int:
    """Supervised whole-cluster auto-restart for ``InputMode.TENSORFLOW``
    jobs; returns the number of restarts that were needed.

    The reference had no elasticity — its recovery story was "Spark
    retries the job; TF restores from checkpoint" (SURVEY.md §5.3). This
    is that story made first-class on the TPU side: run the cluster, and
    if any node dies or ferries an exception, tear the whole cluster
    down, relaunch it (fresh reservation round), and let the user's
    ``map_fun`` resume from its latest orbax checkpoint — the resume
    convention the examples already follow (``CheckpointManager.
    latest_step()`` + restore at startup, e.g. ``examples/llama/
    llama_fsdp.py``). After ``max_restarts`` failed attempts the last
    error propagates.

    Only ``InputMode.TENSORFLOW`` is supervisable: a push feed's consumed
    partitions cannot be replayed by the driver (``InputMode.SPARK`` is
    rejected). Pass ``launcher_factory`` (not a launcher instance) so
    each attempt gets a fresh launcher.
    """
    if run_kwargs.get("input_mode", InputMode.SPARK) != InputMode.TENSORFLOW:
        raise ValueError(
            "run_with_restarts requires input_mode=InputMode.TENSORFLOW "
            "(a push feed's consumed partitions cannot be replayed)"
        )
    if "launcher" in run_kwargs:
        raise ValueError(
            "pass launcher_factory=callable, not launcher=: each restart "
            "attempt needs a fresh launcher"
        )
    restarts = 0
    while True:
        try:
            # run() failures (e.g. a node dying before its reservation)
            # count against the restart budget too: startup flakiness is
            # exactly what the supervisor exists for. run() cleans up its
            # own launcher/server on the way out.
            cluster = run(
                map_fun,
                tf_args,
                num_executors,
                launcher=launcher_factory() if launcher_factory else None,
                **run_kwargs,
            )
            # Supervised wait: liveness + process exits, so a node that
            # is SIGKILLed (or wedges past the heartbeat grace) mid-run
            # triggers the relaunch within seconds instead of after
            # shutdown_timeout. On failure, kill the survivors so the
            # shutdown below reaps the whole attempt promptly.
            supervise_error: RuntimeError | None = None
            try:
                cluster.supervise()
            except RuntimeError as e:
                supervise_error = e
                logger.warning("supervision detected failure: %s", e)
                # postmortem artifact before the relaunch erases state
                flightrec.note("supervise_restart", error=str(e))
                flightrec.dump_now("supervise_restart")
                cluster.launcher.terminate()
            cluster.shutdown(timeout=shutdown_timeout)
            if supervise_error is not None:
                # shutdown absorbed the damage (e.g. every process was
                # terminated back to exit 0 somehow): the supervision
                # verdict still stands — this attempt failed.
                raise supervise_error
            return restarts
        except RuntimeError as e:
            restarts += 1
            if restarts > max_restarts:
                raise
            logger.warning(
                "cluster attempt failed (%s); restarting (%d/%d) — nodes "
                "resume from their latest checkpoint",
                e,
                restarts,
                max_restarts,
            )


def _probe_node_states(
    nodes: list[dict[str, Any]], timeout: float
) -> list[str]:
    """Each node's manager KV ``state``, probed in parallel bounded
    daemon threads sharing ONE ``timeout`` window.

    Manager RPCs have no client-side timeout, and a WEDGED node's kernel
    happily accepts the TCP connect and then hangs the handshake —
    exactly what supervision must not do. Per node, returns the state
    string, ``"unreachable"`` (connect refused/reset: the process is
    gone or going), or ``"hung"`` (no answer inside the window; that
    probe thread is daemon and abandoned)."""
    results: list[list[str]] = [[] for _ in nodes]

    def probe(i: int, node_meta: dict[str, Any]) -> None:
        try:
            mgr = tfnode_runtime.connect_manager(node_meta)
            results[i].append(tfnode_runtime.fetch_node_state(mgr))
        except (ConnectionError, OSError, EOFError):
            results[i].append("unreachable")

    threads = [
        threading.Thread(target=probe, args=(i, n), daemon=True)
        for i, n in enumerate(nodes)
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    return [r[0] if r else "hung" for r in results]


def _abort_if_node_died(launcher, remaining: int) -> None:
    failed = launcher.poll_failed()
    if failed:
        raise RuntimeError(
            f"node process(es) {failed} died during startup "
            f"({remaining} reservations still pending)"
        )


def _as_partitions(
    data: Iterable, num_workers: int, contiguous: bool = False
) -> list[list[Any]]:
    """Normalize user data into a list of record-list partitions.

    Convention (documented in ``TFCluster.train``): if every element is a
    ``list`` or an iterator/generator, the elements ARE the partitions
    (generators are drained); otherwise the whole iterable is a flat
    sequence of records, split into ``num_workers`` partitions so every
    worker receives data — round-robin by default (train: strided
    samples keep per-worker batch statistics close to the input
    distribution), CONTIGUOUS near-equal when ``contiguous=True``
    (inference: results are reassembled in partition order, so
    contiguous splits are what make the order-preserving contract hold
    for flat inputs). Records may be tuples, arrays, dicts, or scalars
    — use tuples (not lists) for row records, exactly as a DataFrame
    ``Row`` would arrive in the reference.
    """
    data = list(data)
    if data and all(
        isinstance(p, list) or isinstance(p, Iterator) for p in data
    ):
        return [list(p) for p in data]
    if len(data) <= num_workers:
        # Per-record partitions: one big partition here would feed ONLY
        # worker 0 and leave every other worker blocking until shutdown
        # (harmless at scale, baffling in smoke tests).
        return [[r] for r in data]
    if not contiguous:
        return [data[i::num_workers] for i in range(num_workers)]
    return contiguous_split(data, num_workers)


def contiguous_split(records: list, n: int) -> list[list[Any]]:
    """Split ``records`` into at most ``n`` contiguous near-equal
    partitions (sizes differ by at most one, empties dropped).
    Contiguity is what makes partition-order reassembly — the
    ``inference``/distributed-``transform`` result path — preserve the
    original record order."""
    k, m = divmod(len(records), n)
    bounds = [i * k + min(i, m) for i in range(n + 1)]
    return [
        records[bounds[i] : bounds[i + 1]]
        for i in range(n)
        if bounds[i] < bounds[i + 1]
    ]
