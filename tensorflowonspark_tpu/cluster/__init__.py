"""Cluster control plane: rendezvous, per-node manager, node runtime,
and the driver-side orchestrator.

Reference parity map (see SURVEY.md §2.1):

- ``reservation.py``  → :mod:`.reservation` (roster rendezvous over TCP)
- ``TFManager.py``    → :mod:`.manager` (per-node queues + KV store)
- ``marker.py``       → :mod:`.marker` (feed sentinels)
- ``TFSparkNode.py``  → :mod:`.node` (node runtime)
- ``TFCluster.py``    → :mod:`.tfcluster` (driver orchestrator)
"""
