"""Launchers: who plays Spark's role of getting node processes running.

The reference leaned on Spark's scheduler (``sc.parallelize(...)
.foreachPartition(TFSparkNode.run)`` — one long-lived task per executor,
SURVEY.md §3.1). With no Spark in the picture, a launcher owns that step:

- :class:`LocalLauncher` — N processes on this host (the test/CI analog of
  the reference's local-mode Spark trick, and the single-TPU-VM path).
- :class:`HostListLauncher` — one process per remote host via a command
  template (ssh by default); the multi-host TPU-pod path where each TPU-VM
  host runs one node process that owns its local chips.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import shlex
import subprocess
import sys
import time
from typing import Callable, Sequence

logger = logging.getLogger(__name__)


class LocalLauncher:
    """Spawn node processes on the local host.

    Uses the ``spawn`` start method: node processes initialize their own
    JAX runtime, and forking a process that may already hold TPU/XLA
    runtime threads is unsafe.
    """

    def __init__(self, env: dict[str, str] | None = None):
        self.env = env or {}
        self._procs: list[mp.Process] = []

    def launch(
        self,
        num_nodes: int,
        target: Callable[..., None],
        args_for: Callable[[int], tuple],
        env: dict[str, str] | None = None,
    ) -> None:
        merged = {**self.env, **(env or {})}
        ctx = mp.get_context("spawn")
        # Env vars must be in place BEFORE the child interpreter boots:
        # sitecustomize-style hooks (e.g. TPU plugin registration) run at
        # interpreter start, long before _child_main gets to apply env.
        # Spawn inherits the parent's environ at exec, so set/restore here.
        saved = {k: os.environ.get(k) for k in merged}
        os.environ.update(merged)
        try:
            for i in range(num_nodes):
                proc = ctx.Process(
                    target=_child_main,
                    args=(merged, target, args_for(i)),
                    name=f"tfos-node-{i}",
                    daemon=False,
                )
                proc.start()
                self._procs.append(proc)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def poll_failed(self) -> list[int]:
        """Indices of processes that already exited nonzero."""
        return [
            i
            for i, p in enumerate(self._procs)
            if p.exitcode is not None and p.exitcode != 0
        ]

    def wait(self, timeout: float | None = None) -> bool:
        """Join all processes; True if all exited within the timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for p in self._procs:
            remaining = (
                None if deadline is None else max(0.0, deadline - time.monotonic())
            )
            p.join(remaining)
        return all(p.exitcode is not None for p in self._procs)

    def exitcodes(self) -> list[int | None]:
        return [p.exitcode for p in self._procs]

    def terminate(self) -> None:
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(5)
        for p in self._procs:
            if p.is_alive():  # pragma: no cover - last resort
                p.kill()


def _child_main(env: dict[str, str], target, args) -> None:
    os.environ.update(env)
    target(*args)


class HostListLauncher:
    """Launch one node process per remote host via a command template.

    Runs ``python -m tensorflowonspark_tpu.cluster.node_main --payload ...``
    on each host through ``cmd_template`` (plain ssh by default; reference
    ``{command}`` unquoted — it is substituted pre-quoted as one shell
    word, see :meth:`launch_command`). This is the spark-submit-shaped
    path for real pods; the user ``map_fun``'s module must be importable
    on every host (the contract Spark imposed on the reference's
    ``map_fun`` too).
    """

    def __init__(
        self,
        hosts: Sequence[str],
        cmd_template: str = "ssh {host} {command}",
        python: str | None = None,
        env: dict[str, str] | None = None,
    ):
        self.hosts = list(hosts)
        self.cmd_template = cmd_template
        # sys.executable, not bare "python": PATH on the remote side may
        # name a different interpreter (or none) — callers with genuinely
        # heterogeneous hosts can still pass python="python3" etc.
        self.python = python or sys.executable
        self.env = dict(env or {})
        self._procs: list[subprocess.Popen] = []

    def launch(
        self,
        num_nodes: int,
        target: Callable[..., None],
        args_for: Callable[[int], tuple],
        env: dict[str, str] | None = None,
    ) -> None:
        from tensorflowonspark_tpu.cluster.node_main import encode_payload

        if num_nodes != len(self.hosts):
            raise ValueError(
                f"{num_nodes} nodes requested but {len(self.hosts)} hosts "
                "configured"
            )
        # Env must be on the remote command line (a local os.environ set
        # would not cross the ssh boundary).
        merged = {**self.env, **(env or {})}
        env_prefix = ""
        if merged:
            assignments = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in merged.items()
            )
            env_prefix = f"env {assignments} "
        commands = []
        for i in range(num_nodes):
            payload = encode_payload(*args_for(i))
            commands.append(
                f"{env_prefix}{self.python} "
                f"-m tensorflowonspark_tpu.cluster.node_main "
                f"--payload {payload}"
            )
        self.launch_command(commands)

    def launch_command(self, commands: Sequence[str]) -> None:
        """Run one command per host through the template.

        ``{command}`` is substituted pre-quoted as ONE shell word, and the
        full line runs through the local shell — so every template sees
        exactly two shell parses: local (strips the quoting; the command
        reaches ssh/sh as a single argument) and remote/inner (parses the
        command itself, where per-value ``shlex.quote``s apply). This is
        what lets env values with spaces survive an ssh hop.
        """
        assert len(commands) == len(self.hosts)
        for host, command in zip(self.hosts, commands):
            full = self.cmd_template.format(
                host=shlex.quote(host), command=shlex.quote(command)
            )
            logger.info("launching on %s: %s", host, full)
            self._procs.append(subprocess.Popen(full, shell=True))

    def wait(self, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        for p in self._procs:
            try:
                remaining = (
                    None
                    if deadline is None
                    else max(0.0, deadline - time.monotonic())
                )
                p.wait(remaining)
            except subprocess.TimeoutExpired:
                return False
        return True

    def poll_failed(self) -> list[int]:
        return [
            i
            for i, p in enumerate(self._procs)
            if p.poll() is not None and p.returncode != 0
        ]

    def exitcodes(self) -> list[int | None]:
        return [p.poll() for p in self._procs]

    def terminate(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.terminate()


def default_launcher(num_nodes: int) -> LocalLauncher:
    return LocalLauncher()
