"""tfos.wire — THE declarative catalog of every cross-process wire
surface, plus the sanctioned codecs that construction and parsing must
route through.

The system's headline guarantees assume *mixed-version coexistence*:
rolling weight rollout keeps old replicas serving while new ones warm,
elastic rejoin replays cursors persisted by a dead incarnation, and the
driver→node KV wires (knobs, plans, timeouts) are read by whatever code
the node happens to be running. Every one of those bytes-cross-a-
boundary formats is declared HERE, once, as a pure-literal schema —
version, field set, and compatibility policy — and every producer and
consumer goes through :func:`encode` / :func:`decode` so a format edit
is a table edit with a machine-checked blast radius, never a silent
fork in some call site.

Enforcement is three-headed (the PR-11 pattern, applied to the
protocol plane):

- ``analysis/wire.py`` — the WR lint family: raw wire-dict
  construction or ``msg["..."]`` parsing outside this module (WR001),
  undeclared message kinds / manager-KV key literals (WR002), fields
  absent from the declared schema (WR003).
- ``tools/wirecheck.py`` — the compat gate: a committed golden corpus
  (``tools/wirecheck_corpus/``) of canonical serialized instances; the
  gate diffs current serialization against the committed shape digest
  (drift must bump the schema version deliberately) and decodes the
  committed OLD bytes with current code — the rolling-upgrade
  guarantee, enforced forever.
- runtime — :func:`encode` rejects undeclared fields and missing
  required ones at the producer; :func:`decode` validates kind/required
  /types at the consumer and IGNORES undeclared extras (that tolerance
  is what lets an old reader survive an add-only-optional publisher).

``WIRE_SCHEMAS`` is a **pure literal** (like ``compute/layout.py``'s
tables and ``utils/failpoints.py``'s SITES) precisely so the analyzer
and the docs drift gate can AST-read it without importing anything;
this module itself imports only the stdlib, so even ``feed/`` modules
on the hot data path can import it without a jax/numpy tax.

Compat policy vocabulary:

- ``"frozen"`` — the field set is immutable at a given version; ANY
  shape change requires a version bump (and the old version's corpus
  bytes must still decode).
- ``"add_only_optional"`` — new OPTIONAL fields may be added at the
  same version (old readers ignore them by construction); removals,
  renames, retypes, and new *required* fields need a version bump.

Schema entry shape::

    "<plane>.<NAME>": {
        "version": 1,               # bumped on deliberate format change
        "compat": "frozen" | "add_only_optional",
        "transport": "message" | "kv" | "frame" | "pointer" | "http"
                     | "entry",
        "fields": {"name": "<type>", ...},   # declared wire order
        "required": ["name", ...],
        # transport == "message" only:
        "kind": "REG", "role": "request" | "reply",
        # transport == "kv" only:
        "kv_key": "ingest_plan",
        # bare-value schemas (scalar KV, cursor entries):
        "codec": "scalar" | "cursor_entry",
        # codec == "scalar" only — the enum of legal values, if closed:
        "values": [...],
    }

Type vocabulary: ``str int float bool list dict bytes any`` with an
optional ``|null`` suffix (``float`` accepts ints; ``bool`` is not an
``int`` here). Field order in ``fields`` is the WIRE order — encode
emits keys in declared order so JSON/pickle bytes stay deterministic
and byte-identical to the pre-catalog writers.
"""

from __future__ import annotations

from typing import Any

__all__ = [
    "WIRE_SCHEMAS",
    "WireError",
    "WireSchemaError",
    "WireDecodeError",
    "encode",
    "decode",
    "message_kind",
    "kind_to_schema",
    "schema",
    "kv_key",
    "encode_cursor_entry",
    "decode_cursor_entry",
    "INGEST_PLAN_KEY",
    "FEED_KNOBS_KEY",
    "FEED_TIMEOUT_KEY",
    "NODE_STATE_KEY",
    "ELASTIC_STATE_KEY",
    "LIVELOG_KEY",
]


# ---------------------------------------------------------------------------
# the catalog (pure literal — AST-read by analysis/wire.py, tools/
# wirecheck.py, and the docs/WIRE.md drift gate; keep it that way)
# ---------------------------------------------------------------------------

WIRE_SCHEMAS = {
    # -- reservation rendezvous protocol (length-prefixed JSON over TCP;
    #    cluster/reservation.py MessageSocket). The whole family is
    #    frozen: requests may come from a node incarnation older OR
    #    newer than the driver, so the shape is load-bearing both ways.
    "reservation.REG": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "REG",
        "role": "request",
        "fields": {"type": "str", "node": "dict"},
        "required": ["type", "node"],
    },
    "reservation.REG.reply": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "OK",
        "role": "reply",
        "fields": {"type": "str"},
        "required": ["type"],
    },
    "reservation.QUERY": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "QUERY",
        "role": "request",
        "fields": {"type": "str"},
        "required": ["type"],
    },
    "reservation.QUERY.reply": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "OK",
        "role": "reply",
        "fields": {"type": "str", "done": "bool"},
        "required": ["type", "done"],
    },
    "reservation.QINFO": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "QINFO",
        "role": "request",
        "fields": {"type": "str"},
        "required": ["type"],
    },
    "reservation.QINFO.reply": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "OK",
        "role": "reply",
        "fields": {"type": "str", "cluster_info": "list"},
        "required": ["type", "cluster_info"],
    },
    "reservation.QNUM": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "QNUM",
        "role": "request",
        "fields": {"type": "str"},
        "required": ["type"],
    },
    "reservation.QNUM.reply": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "OK",
        "role": "reply",
        "fields": {"type": "str", "remaining": "int"},
        "required": ["type", "remaining"],
    },
    "reservation.QEPOCH": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "QEPOCH",
        "role": "request",
        "fields": {"type": "str"},
        "required": ["type"],
    },
    "reservation.QEPOCH.reply": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "OK",
        "role": "reply",
        "fields": {"type": "str", "epoch": "int", "roster": "list"},
        "required": ["type", "epoch", "roster"],
    },
    "reservation.HEARTBEAT": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "HEARTBEAT",
        "role": "request",
        "fields": {"type": "str", "executor_id": "int"},
        "required": ["type", "executor_id"],
    },
    "reservation.HEARTBEAT.reply": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "OK",
        "role": "reply",
        "fields": {
            "type": "str",
            "stop": "bool",
            "epoch": "int",
            "server_unix": "float",
        },
        "required": ["type", "stop", "epoch", "server_unix"],
    },
    "reservation.ICURSOR": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "ICURSOR",
        "role": "request",
        "fields": {"type": "str", "executor_id": "int", "payload": "dict"},
        "required": ["type", "executor_id"],
    },
    "reservation.ICURSOR.reply": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "OK",
        "role": "reply",
        "fields": {"type": "str"},
        "required": ["type"],
    },
    "reservation.STOP": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "STOP",
        "role": "request",
        "fields": {"type": "str"},
        "required": ["type"],
    },
    "reservation.STOP.reply": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "OK",
        "role": "reply",
        "fields": {"type": "str"},
        "required": ["type"],
    },
    "reservation.ERR": {
        "version": 1,
        "compat": "frozen",
        "transport": "message",
        "kind": "ERR",
        "role": "reply",
        "fields": {"type": "str", "error": "str"},
        "required": ["type", "error"],
    },
    # -- manager KV wires (cluster/manager.py kdict; driver ↔ node).
    "kv.ingest_plan": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "kv",
        "kv_key": "ingest_plan",
        "fields": {
            "epoch": "int",
            "plan_id": "str|null",
            "shard_index": "int",
            "num_shards": "int",
            "manifests": "list",
            "handover": "bool",
            "complete": "bool",
            "seq": "int|null",
        },
        "required": ["epoch", "shard_index", "num_shards", "manifests"],
    },
    "kv.feed_knobs": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "kv",
        "kv_key": "feed_knobs",
        "fields": {"seq": "int", "knobs": "dict"},
        "required": ["seq", "knobs"],
    },
    "kv.feed_timeout": {
        "version": 1,
        "compat": "frozen",
        "transport": "kv",
        "kv_key": "feed_timeout",
        "codec": "scalar",
        "fields": {"value": "float"},
        "required": ["value"],
    },
    "kv.node_state": {
        "version": 1,
        "compat": "frozen",
        "transport": "kv",
        "kv_key": "state",
        "codec": "scalar",
        "fields": {"value": "str"},
        "required": ["value"],
        "values": ["running", "terminating", "finished", "error"],
    },
    "kv.elastic_state": {
        "version": 1,
        "compat": "frozen",
        "transport": "kv",
        "kv_key": "elastic:state",
        "codec": "scalar",
        "fields": {"value": "bytes"},
        "required": ["value"],
    },
    # -- replay cursors (persisted beside checkpoints, shipped through
    #    ICURSOR, merged by the driver's shard re-planner). An entry is
    #    a bare int ``seq`` or a two-int ``[seq, skip]`` — both forms
    #    are live on the wire forever.
    "ingest.cursor_entry": {
        "version": 1,
        "compat": "frozen",
        "transport": "entry",
        "codec": "cursor_entry",
        "fields": {"seq": "int", "skip": "int"},
        "required": ["seq"],
    },
    "ingest.cursor_payload": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "message",
        "kind": None,
        "role": None,
        "fields": {
            "epoch": "int",
            "final": "bool",
            "done": "bool",
            "cursor": "dict",
            "records_per_chunk": "int|null",
            "frame_blocks": "bool|null",
            "plan_seq": "int|null",
        },
        "required": ["epoch", "final", "cursor"],
    },
    # -- columnar frame header (feed/columnar.py ``TFC\\x01`` frames:
    #    shm ring, TCP feed, framed shard files). The header dict is
    #    pickled in declared order; payload layout comes from ``cols``.
    "columnar.frame_header": {
        "version": 1,
        "compat": "frozen",
        "transport": "frame",
        "fields": {
            "v": "int",
            "qname": "str|null",
            "kind": "str",
            "n": "int",
            "cols": "list",
            "payload_crc": "int|null",
            "stream": "str|null",
            "seq": "int",
        },
        "required": ["v", "kind", "n", "cols", "seq"],
    },
    # -- weight-rollout publication channel (serving/rollout.py LATEST
    #    pointer: one JSON record, CRC-framed for torn-write rejection).
    "rollout.manifest": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "pointer",
        "fields": {
            "version": "str",
            "kind": "str",
            "path": "str",
            "step": "int|null",
        },
        "required": ["version", "kind", "path"],
    },
    "rollout.latest": {
        "version": 1,
        "compat": "frozen",
        "transport": "pointer",
        "fields": {"crc": "int", "manifest": "dict"},
        "required": ["crc", "manifest"],
    },
    # -- live-traffic log (feed/livelog.py): sealed-segment manifest
    #    files the driver's online loop discovers and appends to the
    #    running ingest plan (docs/ROBUSTNESS.md "Online continual
    #    loop"). The manifest is a JSON file beside the sealed frame
    #    segment; the announce KV is a node→driver discovery hint.
    "livelog.manifest": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "pointer",
        "fields": {
            "path": "str",
            "records": "int",
            "bytes": "int",
            "seq": "int",
            "stream": "str",
            "sealed_unix": "float",
            "first_unix": "float|null",
            "last_unix": "float|null",
        },
        "required": ["path", "records", "bytes", "seq", "stream"],
    },
    "kv.livelog_announce": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "kv",
        "kv_key": "livelog",
        "fields": {
            "dir": "str",
            "seq": "int",
            "records": "int|null",
        },
        "required": ["dir", "seq"],
    },
    # -- online-loop freshness beacon (online.py): one JSON record the
    #    driver loop rewrites each cycle so external probes (bench,
    #    dashboards) can read loop health without the obs registry.
    "online.freshness": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "pointer",
        "fields": {
            "t_unix": "float",
            "cycle": "int",
            "data_age_s": "float|null",
            "loop_lag_s": "float|null",
            "weights_version": "str|null",
            "trained_records": "int|null",
        },
        "required": ["t_unix", "cycle"],
    },
    # -- serve_model HTTP bodies (tools/serve_model.py ↔ serving/
    #    fleet.py + external clients; NDJSON stream lines + trailers).
    "serve.error": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "http",
        "fields": {
            "error": "str",
            "error_type": "str",
            "retry_after_src": "str",
            "outcome": "str",
            "trace": "str",
        },
        "required": ["error"],
    },
    "serve.completion": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "http",
        "fields": {
            "completions": "list",
            "logprobs": "list",
            "weights_versions": "list",
            "trace": "str",
        },
        "required": ["completions"],
    },
    "serve.stream_chunk": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "http",
        "fields": {"token": "any", "logprob": "float"},
        "required": ["token"],
    },
    "serve.stream_trailer": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "http",
        "fields": {
            "done": "bool",
            "completion": "any",
            "logprobs": "list",
            "weights_version": "str",
            "trace": "str",
        },
        "required": ["done", "completion"],
    },
    "serve.stream_error": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "http",
        "fields": {"error": "str", "error_type": "str", "trace": "str"},
        "required": ["error"],
    },
    "serve.reload": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "http",
        "fields": {
            "status": "str",
            "version": "str",
            "swap_seconds": "float",
        },
        "required": ["status"],
    },
    # -- disaggregated cache tier (cachetier/service.py: length-prefixed
    #    pickled header + raw payload bytes over TCP; the fleet-global
    #    prefix L2 and the shared frame cache both speak it). Requests
    #    are add_only_optional: the service is restart-at-will (clients
    #    treat every transport error as a miss), so mixed-version
    #    client/daemon pairs are the NORMAL state during a roll.
    "cachetier.LOOKUP": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "message",
        "kind": "CLOOKUP",
        "role": "request",
        "fields": {
            "type": "str",
            "ns": "str",
            "key": "str",
            "path": "str|null",
            "off": "int|null",
            "span": "int|null",
        },
        "required": ["type", "ns", "key"],
    },
    "cachetier.LOOKUP.reply": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "message",
        "kind": "COK",
        "role": "reply",
        "fields": {"type": "str", "hit": "bool", "nbytes": "int"},
        "required": ["type", "hit", "nbytes"],
    },
    "cachetier.FILL": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "message",
        "kind": "CFILL",
        "role": "request",
        "fields": {
            "type": "str",
            "ns": "str",
            "key": "str",
            "nbytes": "int",
        },
        "required": ["type", "ns", "key", "nbytes"],
    },
    "cachetier.FILL.reply": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "message",
        "kind": "COK",
        "role": "reply",
        "fields": {"type": "str", "stored": "bool"},
        "required": ["type", "stored"],
    },
    "cachetier.INVALIDATE": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "message",
        "kind": "CINVAL",
        "role": "request",
        "fields": {"type": "str", "ns": "str", "prefix": "str"},
        "required": ["type", "ns", "prefix"],
    },
    "cachetier.INVALIDATE.reply": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "message",
        "kind": "COK",
        "role": "reply",
        "fields": {"type": "str", "dropped": "int"},
        "required": ["type", "dropped"],
    },
    "cachetier.STATS": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "message",
        "kind": "CSTATS",
        "role": "request",
        "fields": {"type": "str"},
        "required": ["type"],
    },
    "cachetier.STATS.reply": {
        "version": 1,
        "compat": "add_only_optional",
        "transport": "message",
        "kind": "COK",
        "role": "reply",
        "fields": {
            "type": "str",
            "hits": "int",
            "misses": "int",
            "fills": "int",
            "evictions": "int",
            "entries": "int",
            "bytes": "int",
            "capacity_bytes": "int",
            "backing_read_bytes": "int",
        },
        "required": ["type", "hits", "misses", "entries", "bytes"],
    },
}


# ---------------------------------------------------------------------------
# errors
# ---------------------------------------------------------------------------


class WireError(ValueError):
    """Base for all wire-codec failures (a ValueError so transport
    loops that already treat malformed input as a connection-level
    reject — ``MessageSocket.receive``, ``decode_frame`` — keep
    working)."""


class WireSchemaError(WireError):
    """Producer-side misuse: unknown schema, undeclared field, missing
    required field, bad type AT CONSTRUCTION. Always a programming
    error at the call site — never data-dependent."""


class WireDecodeError(WireError):
    """Consumer-side rejection: the payload does not satisfy the
    declared schema (wrong kind, missing required field, bad type).
    Data-dependent — a torn write or a foreign speaker, not
    necessarily a bug here."""


# ---------------------------------------------------------------------------
# schema lookup
# ---------------------------------------------------------------------------


def schema(name: str) -> dict:
    """The declared schema entry, or raise :class:`WireSchemaError`."""
    try:
        return WIRE_SCHEMAS[name]
    except KeyError:
        raise WireSchemaError(
            f"undeclared wire schema {name!r} — declare it in "
            "cluster/wire.py WIRE_SCHEMAS"
        ) from None


def kv_key(name: str) -> str:
    """The manager-KV key string a ``kv.*`` schema rides on."""
    sc = schema(name)
    try:
        return sc["kv_key"]
    except KeyError:
        raise WireSchemaError(f"{name!r} is not a KV schema") from None


def message_kind(msg: Any) -> str | None:
    """The wire ``type`` tag of a raw reservation message (the ONE
    sanctioned peek at an undecoded message — dispatch on this, then
    :func:`decode` with the kind's schema)."""
    if isinstance(msg, dict):
        kind = msg.get("type")
        return kind if isinstance(kind, str) else None
    return None


def kind_to_schema(kind: str) -> str | None:
    """Schema name for a request-side message kind, or None when the
    kind is undeclared (the server's unknown-type ERR path)."""
    return _REQUEST_KINDS.get(kind)


# ---------------------------------------------------------------------------
# type checking
# ---------------------------------------------------------------------------

_TYPES = {
    "str": str,
    "int": int,
    "float": (int, float),
    "bool": bool,
    "list": (list, tuple),
    "dict": dict,
    "bytes": (bytes, bytearray),
}


def _type_ok(value: Any, typestr: str) -> bool:
    for alt in typestr.split("|"):
        if alt in ("null", "none"):
            if value is None:
                return True
            continue
        if alt == "any":
            return True
        base = _TYPES[alt]
        if isinstance(value, bool) and alt not in ("bool", "any"):
            continue  # bool is an int in Python, not on the wire
        if isinstance(value, base):
            return True
    return False


def _check_field(name: str, field: str, value: Any, typestr: str,
                 exc: type) -> None:
    if not _type_ok(value, typestr):
        raise exc(
            f"{name}.{field}: expected {typestr}, got "
            f"{type(value).__name__} ({value!r})"
        )


# ---------------------------------------------------------------------------
# the sanctioned codecs
# ---------------------------------------------------------------------------


def encode(name: str, **fields: Any) -> Any:
    """Construct one wire value for schema ``name``.

    - message schemas return the dict WITH the ``type`` tag injected
      (callers never spell the kind literal);
    - dict schemas (KV, header, pointer, HTTP) return the dict with
      keys in declared wire order — byte-deterministic under both
      ``json.dumps`` and pickle;
    - scalar schemas take ``value=`` and return the bare value;
    - the cursor-entry schema takes ``seq=``/``skip=`` and returns the
      bare int / two-int list the persisted format uses.

    Undeclared fields, missing required fields, and type mismatches
    raise :class:`WireSchemaError` at the producer — the earliest
    possible moment."""
    sc = schema(name)
    codec = sc.get("codec")
    if codec == "scalar":
        extra = set(fields) - {"value"}
        if extra or "value" not in fields:
            raise WireSchemaError(
                f"{name}: scalar schema takes exactly value=, got "
                f"{sorted(fields)}"
            )
        value = fields["value"]
        _check_field(name, "value", value, sc["fields"]["value"],
                     WireSchemaError)
        values = sc.get("values")
        if values is not None and value not in values:
            raise WireSchemaError(
                f"{name}: value {value!r} not in declared enum {values}"
            )
        return value
    if codec == "cursor_entry":
        extra = set(fields) - {"seq", "skip"}
        if extra or "seq" not in fields:
            raise WireSchemaError(
                f"{name}: cursor entries take seq= and optional skip=, "
                f"got {sorted(fields)}"
            )
        return encode_cursor_entry(fields["seq"], fields.get("skip", 0))
    declared = sc["fields"]
    kind = sc.get("kind")
    if kind is not None and "type" in fields:
        raise WireSchemaError(
            f"{name}: the codec owns the 'type' tag — do not pass it"
        )
    undeclared = [k for k in fields if k not in declared]
    if undeclared:
        raise WireSchemaError(
            f"{name}: undeclared field(s) {undeclared} — declare them "
            "in WIRE_SCHEMAS (and bump the version per the compat "
            "policy) before writing them"
        )
    for req in sc["required"]:
        if req == "type" and kind is not None:
            continue
        if req not in fields:
            raise WireSchemaError(f"{name}: missing required field {req!r}")
    out: dict[str, Any] = {}
    for k, typestr in declared.items():  # declared order == wire order
        if k == "type" and kind is not None:
            out["type"] = kind
            continue
        if k in fields:
            _check_field(name, k, fields[k], typestr, WireSchemaError)
            out[k] = fields[k]
    return out


def decode(name: str, payload: Any) -> dict[str, Any]:
    """Validate one received wire value against schema ``name`` and
    return its declared fields (scalar schemas come back as
    ``{"value": ...}``; cursor entries as ``{"seq", "skip"}``).

    Required fields must be present with declared types; undeclared
    extras are IGNORED — that asymmetry is the rolling-upgrade
    tolerance: an old reader survives an add-only-optional publisher.
    Rejection raises :class:`WireDecodeError`."""
    sc = schema(name)
    codec = sc.get("codec")
    if codec == "scalar":
        _check_field(name, "value", payload, sc["fields"]["value"],
                     WireDecodeError)
        values = sc.get("values")
        if values is not None and payload not in values:
            raise WireDecodeError(
                f"{name}: value {payload!r} not in declared enum {values}"
            )
        return {"value": payload}
    if codec == "cursor_entry":
        seq, skip = decode_cursor_entry(payload)
        return {"seq": seq, "skip": skip}
    if not isinstance(payload, dict):
        raise WireDecodeError(
            f"{name}: expected a dict payload, got "
            f"{type(payload).__name__}"
        )
    kind = sc.get("kind")
    if kind is not None and payload.get("type") != kind:
        raise WireDecodeError(
            f"{name}: expected type {kind!r}, got "
            f"{payload.get('type')!r}"
        )
    for req in sc["required"]:
        if req not in payload:
            raise WireDecodeError(
                f"{name}: missing required field {req!r}"
            )
    out: dict[str, Any] = {}
    for k, typestr in sc["fields"].items():
        if k in payload:
            _check_field(name, k, payload[k], typestr, WireDecodeError)
            out[k] = payload[k]
    return out


def encode_cursor_entry(seq: Any, skip: Any = 0):
    """One replay-cursor entry in its persisted wire form: the bare int
    ``seq`` when no mid-block skip exists, else the two-int
    ``[seq, skip]`` pair — exactly the two forms
    :func:`decode_cursor_entry` accepts forever."""
    seq = int(seq)
    skip = int(skip)
    return seq if skip == 0 else [seq, skip]


def decode_cursor_entry(v: Any) -> tuple[int, int]:
    """Canonical ``(seq, skip)`` of one replay-cursor entry — THE
    serialization both data planes (and the driver's shard re-planner)
    agree on. Accepts the plain-int ``seq`` form (push plane) and the
    ``[seq, skip]`` pair (pull plane's record-exact mid-block form);
    anything else is malformed."""
    if isinstance(v, (list, tuple)):
        if len(v) != 2:
            raise WireDecodeError(
                f"malformed cursor entry {v!r}: want [seq, skip]"
            )
        return int(v[0]), int(v[1])
    return int(v), 0


# ---------------------------------------------------------------------------
# KV key registry (derived from the table so the string exists ONCE;
# analysis/wire.py resolves these names back to their keys by AST)
# ---------------------------------------------------------------------------


def _kv_key_of(name: str) -> str:
    return WIRE_SCHEMAS[name]["kv_key"]


INGEST_PLAN_KEY = _kv_key_of("kv.ingest_plan")
FEED_KNOBS_KEY = _kv_key_of("kv.feed_knobs")
FEED_TIMEOUT_KEY = _kv_key_of("kv.feed_timeout")
NODE_STATE_KEY = _kv_key_of("kv.node_state")
ELASTIC_STATE_KEY = _kv_key_of("kv.elastic_state")
LIVELOG_KEY = _kv_key_of("kv.livelog_announce")


# ---------------------------------------------------------------------------
# table sanity (import-time: a malformed catalog entry is a programming
# error that must not survive to a wire call)
# ---------------------------------------------------------------------------


def _validate_table() -> dict[str, str]:
    request_kinds: dict[str, str] = {}
    kv_keys: dict[str, str] = {}
    for name, sc in WIRE_SCHEMAS.items():
        assert isinstance(sc.get("version"), int) and sc["version"] >= 1, name
        assert sc.get("compat") in ("frozen", "add_only_optional"), name
        fields = sc.get("fields")
        assert isinstance(fields, dict) and fields, name
        for f, t in fields.items():
            for alt in t.split("|"):
                assert alt in _TYPES or alt in ("any", "null"), (name, f, t)
        assert set(sc.get("required", ())) <= set(fields), name
        kind = sc.get("kind")
        if kind is not None and sc.get("role") == "request":
            assert kind not in request_kinds, f"duplicate kind {kind}"
            request_kinds[kind] = name
        key = sc.get("kv_key")
        if key is not None:
            assert key not in kv_keys, f"duplicate kv key {key}"
            kv_keys[key] = name
    return request_kinds


_REQUEST_KINDS = _validate_table()
