"""Node runtime — runs inside each cluster process.

Reference parity: ``tensorflowonspark/TFSparkNode.py`` (``_mapfn``: device
allocation → manager start → port reservation → reservation register →
roster barrier → TF_CONFIG → run ``map_fun``; plus ``_train``/
``_inference``/``_shutdown`` feeder-side partition functions).

Structural difference (deliberate): the reference ran inside borrowed Spark
tasks, so ``InputMode.SPARK`` had to fork the TF process into the background
to free the executor slot for later feed tasks. Our launcher owns the node
processes outright and the driver feeds queues over TCP, so ``map_fun``
always runs in the node process itself — one fewer process hop on the feed
path.
"""

from __future__ import annotations

import logging
import os
import pickle
import queue as _queue
import socket
import threading
import time
import traceback
from typing import Any, Callable

import numpy as np

from tensorflowonspark_tpu.cluster import manager as tf_manager
from tensorflowonspark_tpu.cluster import reservation
from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.cluster.context import TFNodeContext
from tensorflowonspark_tpu.cluster.marker import EndOfFeed, EndPartition
from tensorflowonspark_tpu.utils import util
from tensorflowonspark_tpu.utils.failpoints import failpoint
from tensorflowonspark_tpu.utils.retry import RetryPolicy

logger = logging.getLogger(__name__)

# Chunk size for remote queue puts (records per proxied put).
FEED_CHUNK = 512

# Control-queue message asking the node process to exit.
STOP = "STOP"


def _assign_role(
    executor_id: int, cluster_template: dict[str, list[int]]
) -> tuple[str, int]:
    """Map an executor id to (job_name, task_index) per the role template.

    Reference: the role map built in ``TFCluster.py:run`` and consumed in
    ``TFSparkNode._mapfn``.
    """
    for job_name, ids in cluster_template.items():
        if executor_id in ids:
            return job_name, ids.index(executor_id)
    raise ValueError(f"executor {executor_id} not in cluster template")


def run_node(
    executor_id: int,
    map_fun: Callable[[Any, TFNodeContext], Any],
    tf_args: Any,
    cluster_meta: dict[str, Any],
) -> None:
    """Entry point of one node process (reference: ``TFSparkNode._mapfn``)."""
    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s [node{executor_id}] %(levelname)s %(name)s: %(message)s",
    )
    # NOTE: unlike the reference, executor identity is launcher-assigned (the
    # arg above), not rediscovered from a cwd file — co-located local nodes
    # share a cwd, so the reference's write_executor_id pinning would
    # clobber itself here. util.write/read_executor_id remain for remote
    # launchers whose retries do land in a per-node working dir.

    failpoint("node.startup")

    # Cross-process trace context: the cluster id is the run's trace_id
    # (stamped into every SpanTracer export on this node), so driver-
    # and node-side spans of one run stitch into one timeline
    # (obs.cluster / tools/trace_merge.py).
    from tensorflowonspark_tpu.obs import cluster as obs_cluster
    from tensorflowonspark_tpu.obs import flightrec

    obs_cluster.set_trace_context(
        str(cluster_meta.get("trace_id") or cluster_meta.get("id", "")),
        node=f"node{executor_id}",
    )

    job_name, task_index = _assign_role(
        executor_id, cluster_meta["cluster_template"]
    )
    authkey = bytes.fromhex(cluster_meta["authkey"])

    # 1. data-plane manager (queues + KV), reachable by remote feeders
    mgr = tf_manager.start(
        authkey,
        queues=cluster_meta.get("queues") or tf_manager.DEFAULT_QUEUES,
        mode=cluster_meta.get("manager_mode", "remote"),
        maxsize=cluster_meta.get("queue_maxsize", tf_manager.DEFAULT_MAXSIZE),
    )

    # 1b. same-host feed fast path: a shared-memory ring that co-located
    #     feeders use instead of the TCP manager proxy (the reference's
    #     per-item pickle+socket put was its dominant feed overhead —
    #     SURVEY.md §3.2). A drain thread forwards ring records into the
    #     in-process queues so consumers (DataFeed) are oblivious.
    ring_name = None
    if cluster_meta.get("use_shm_ring", True):
        ring_name = _start_ring_drain(
            str(cluster_meta.get("id", "c")),
            executor_id,
            mgr,
            capacity=int(cluster_meta.get("shm_ring_mb", 64)) * 1024 * 1024,
        )

    # 2. reserve a port: the chief's becomes the jax.distributed coordinator
    #    address (replaces the reference's TF server port in TF_CONFIG)
    port = util.find_free_port()
    host = util.get_ip_address()

    # 3. optional tensorboard on chief (reference: _mapfn tensorboard spawn).
    #    The log dir resolves exactly like ctx.metrics_writer's, so the
    #    chief's TB aggregates what the nodes write.
    log_dir = cluster_meta.get("log_dir")
    if log_dir:
        log_dir = util.resolve_path(
            log_dir,
            cluster_meta.get("default_fs", ""),
            cluster_meta.get("working_dir", ""),
        )
    tb_port, tb_pid = None, 0
    if cluster_meta.get("tensorboard") and executor_id == 0:
        tb_port, tb_pid = _maybe_start_tensorboard(log_dir)

    # 3b. optional per-host jax.profiler trace server (SURVEY.md §5.1: the
    #     coordinator-knows-every-host's-profiler-URL pattern; the TPU
    #     equivalent of the reference's per-node tf.profiler endpoints).
    prof_port = None
    if cluster_meta.get("profiler"):
        prof_port = _maybe_start_profiler_server()

    # 3c. per-node Prometheus endpoint: GET /metrics renders the
    #     process-global obs registry (MetricsWriter mirrors, feed/train
    #     instrumentation) so a scraper — or a curl-ing operator — can
    #     read any node's counters without TensorBoard. Advertised in
    #     the reservation roster as metrics_port.
    metrics_port = None
    if cluster_meta.get("metrics", True):
        metrics_port = _maybe_start_metrics_server(host)

    # 3d. failure flight recorder: a rolling atomic snapshot of this
    #     process's recent spans/metrics/events on the heartbeat
    #     cadence, so even a SIGKILL (no goodbye possible) leaves the
    #     last interval at logs/flightrec-node<id>.json for the
    #     postmortem (obs.flightrec; docs/OBSERVABILITY.md).
    fr_dir = cluster_meta.get("flightrec_dir")
    if fr_dir:
        fr_dir = util.resolve_path(
            fr_dir,
            cluster_meta.get("default_fs", ""),
            cluster_meta.get("working_dir", ""),
        )
        rec = flightrec.install(
            os.path.join(fr_dir, f"flightrec-node{executor_id}.json"),
            process=f"node{executor_id}",
            interval=max(
                1.0, float(cluster_meta.get("heartbeat_interval", 2.0) or 2.0)
            ),
        )
        rec.note("node_start", executor_id=executor_id, host=host)
        rec.start()

    # 4. register + roster barrier
    client = reservation.Client(cluster_meta["server_addr"])
    client.register(
        {
            "executor_id": executor_id,
            "host": host,
            "port": port,
            "job_name": job_name,
            "task_index": task_index,
            "addr": list(mgr.address),
            "authkey": cluster_meta["authkey"],
            "tb_port": tb_port,
            "tb_pid": tb_pid,
            "prof_port": prof_port,
            "metrics_port": metrics_port,
            "pid": os.getpid(),
            "shm_ring": ring_name,
        }
    )
    # 4b. liveness plane: a background beat refreshes this node's
    #     last-seen stamp on the driver so a SIGKILL here is detected
    #     within the heartbeat grace, not a feed/shutdown timeout.
    #     Started BEFORE the roster barrier: a straggler can hold the
    #     barrier for minutes, and a node whose only stamp were its
    #     registration would look grace-expired the moment the barrier
    #     completed.
    hb_interval = float(cluster_meta.get("heartbeat_interval", 2.0) or 0)
    if hb_interval > 0:
        _start_heartbeater(
            cluster_meta["server_addr"], executor_id, hb_interval
        )

    cluster_info = client.await_reservations(
        timeout=cluster_meta.get("reservation_timeout", 600)
    )

    chief = next(
        n
        for n in cluster_info
        if n["job_name"] == "chief"
        or (n["job_name"] == "worker" and n["task_index"] == 0)
    )
    ctx = TFNodeContext(
        executor_id=executor_id,
        job_name=job_name,
        task_index=task_index,
        cluster_info=cluster_info,
        num_workers=cluster_meta["num_executors"],
        default_fs=cluster_meta.get("default_fs", ""),
        working_dir=cluster_meta.get("working_dir", os.getcwd()),
        mgr=mgr,
        coordinator_address=f"{chief['host']}:{chief['port']}",
        distributed=cluster_meta.get("distributed", False),
        tb_port=tb_port,
        log_dir=log_dir,
    )
    # The handover protocol's cursor wire needs the reservation server
    # address (cursors must outlive this process — see
    # publish_ingest_cursor); ctx.get_ingest_feed wires it up.
    ctx.extras["server_addr"] = list(cluster_meta["server_addr"])

    # 5. run the user fn; ferry exceptions to the driver via the error queue
    #    (reference: the 'error' queue contract in TFSparkNode)
    try:
        if cluster_meta.get("auto_initialize_distributed", True):
            ctx.initialize_distributed()
        map_fun(tf_args, ctx)
        publish_node_state(mgr, "finished")
    except Exception as map_err:
        tb = traceback.format_exc()
        logger.error("map_fun failed:\n%s", tb)
        flightrec.note("map_fun_error", error=repr(map_err))
        flightrec.dump_now("map_fun_error")
        publish_node_state(mgr, "error")
        try:
            mgr.get_queue("error").put(
                {"executor_id": executor_id, "traceback": tb}, timeout=10
            )
        except _queue.Full:
            pass
        _await_stop(mgr, timeout=cluster_meta.get("error_linger_secs", 60))
        raise
    # 6. linger until the driver collected results and posted STOP, so the
    #    output queue (which lives in this process) survives until drained
    _await_stop(mgr, timeout=cluster_meta.get("linger_secs", 1800))


def _start_heartbeater(
    server_addr, executor_id: int, interval: float
) -> threading.Thread:
    """Daemon thread beating HEARTBEAT every ``interval`` seconds.

    Deliberately fail-fast (no RPC retries): the beat IS the liveness
    signal, so a missed beat should age this node's last-seen stamp,
    not hide inside a backoff loop. Any error just skips the beat;
    the thread exits when the server acks with its stop flag set or
    becomes permanently unreachable after the cluster stops (process
    exit kills the daemon thread anyway).

    Elastic plane: the beat reply piggybacks the driver's membership
    epoch. When it moves, this thread refetches the active roster
    (``QEPOCH``) and publishes both to the process-local watcher
    (``compute.elastic.notify_membership``) — the training loop's
    ``ElasticTrainer.changed()`` flips within one beat of a
    reconfigure.
    """
    client = reservation.Client(
        server_addr, retry=RetryPolicy(max_attempts=1)
    )
    from tensorflowonspark_tpu.obs import cluster as obs_cluster

    def note_epoch(reply: dict) -> int | None:
        epoch = reply.get("epoch")
        if epoch is None:
            return None
        epoch = int(epoch)
        try:
            info = client.membership()
            # Lazy: compute.elastic stays unimported on the (common)
            # epoch-0-forever path.
            from tensorflowonspark_tpu.compute import elastic

            elastic.notify_membership(info["epoch"], info["roster"])
        except Exception as e:  # noqa: BLE001 - next beat retries
            logger.warning("membership refetch failed: %s", e)
            return None
        return epoch

    def beat() -> None:
        last_epoch = 0
        while True:
            try:
                t0 = time.time()
                reply = client.heartbeat(executor_id)
                t1 = time.time()
                # NTP-style clock sample off the beat we already pay
                # for: offset = driver wall clock minus the round-trip
                # midpoint; obs.cluster keeps the minimum-RTT sample
                # (tightest error bound) for trace alignment.
                server_unix = reply.get("server_unix")
                if server_unix is not None:
                    obs_cluster.note_clock_sync(
                        float(server_unix) - (t0 + t1) / 2.0, t1 - t0
                    )
                if int(reply.get("epoch") or 0) > last_epoch:
                    got = note_epoch(reply)
                    if got is not None:
                        last_epoch = got
                if reply.get("stop"):
                    return  # cluster kill: no point beating on
            except Exception as e:  # noqa: BLE001 - a missed beat is the signal
                logger.debug("heartbeat skipped: %s", e)
            time.sleep(interval)

    t = threading.Thread(target=beat, daemon=True, name="heartbeater")
    t.start()
    return t


def _await_stop(mgr, timeout: float) -> None:
    """Block until the driver posts STOP on the control queue (or timeout)."""
    control = mgr.get_queue("control")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            msg = control.get(block=True, timeout=1.0)
            control.task_done()
            if msg == STOP:
                return
        except _queue.Empty:
            continue
    logger.warning("node linger timeout (%ss) without STOP; exiting", timeout)


def _start_ring_drain(
    cluster_id: str, executor_id: int, mgr, capacity: int
) -> str | None:
    """Create this node's shm ring and start the drain thread.

    Ring records are either COLUMNAR FRAMES (``feed/columnar.py``; the
    drain decodes them into zero-copy column views over the ring memory
    — the refcounted frame keeps the slot alive until the batch is
    consumed or transferred) or pickled ``(qname, payload)`` tuples (the
    row-pickle fallback and all markers). Either way the drain forwards
    into the named in-process queue (bounded, so queue backpressure
    propagates to the ring and from there to the producer's ``push``
    timeout). Returns the ring name to advertise in the reservation
    roster, or None when native support is unavailable.
    """
    try:
        from tensorflowonspark_tpu.native.shmring import ShmRing, available
    except Exception:  # pragma: no cover - import guard
        return None
    if not available():
        return None
    name = f"/tfos_{cluster_id[:12]}_{executor_id}"
    try:
        ring = ShmRing.create(name, capacity)
    except OSError as e:
        logger.warning("shm ring unavailable (%s); TCP feed only", e)
        return None
    # The segment must not outlive this node process even if no producer
    # ever attaches (close() is idempotent and unlinks as owner).
    import atexit

    atexit.register(ring.close)

    def drain() -> None:
        from tensorflowonspark_tpu.feed import columnar

        try:
            data = chunk = None
            while True:
                # drop the previous frame's refs BEFORE blocking: a view
                # held across the wait would pin its ring slot and
                # deadlock a producer waiting for that space
                data = chunk = None
                try:
                    data = ring.pop_frame(timeout=1.0)
                except TimeoutError:
                    continue
                if data is None:  # producer closed and ring drained
                    return
                if columnar.is_frame(data):
                    if failpoint("columnar.frame") == "drop":
                        # chaos: frame lost mid-stream — the consumer's
                        # per-stream sequence check surfaces the gap
                        continue
                    chunk = columnar.decode_frame(data, path="shm")
                    zero_copy = isinstance(data, np.ndarray)
                    nbytes = data.nbytes if zero_copy else len(data)
                    data = None
                    if zero_copy and (
                        nbytes > ring.capacity // 4
                        or ring.outstanding_bytes() > ring.capacity // 2
                    ):
                        # liveness guard: a consumer assembling one
                        # batch pins the views of ALL its frames while
                        # blocking for the next, so pinned views nearing
                        # ring capacity (a batch bigger than the ring,
                        # or one unsplittable over-quarter frame) would
                        # starve the producer of push space forever.
                        # Copy out — releases the slot now; costs one
                        # memcpy only under backlog.
                        chunk = chunk.materialize()
                    mgr.get_queue(chunk.qname or "input").put(chunk)
                    continue
                qname, payload = pickle.loads(data)
                mgr.get_queue(qname).put(payload)
        except Exception:
            # Ferry the real error to the driver; dying silently would
            # surface as an opaque feed timeout on the producer side.
            tb = traceback.format_exc()
            logger.error("ring drain failed:\n%s", tb)
            try:
                mgr.get_queue("error").put(
                    {"executor_id": executor_id, "traceback": tb}, timeout=10
                )
            except _queue.Full:
                pass
        finally:
            ring.close()

    threading.Thread(target=drain, daemon=True, name="ring-drain").start()
    logger.info("shm ring %s ready (%d MiB)", name, capacity // (1024 * 1024))
    return name


# Producer-side cache: one ring handle per advertised name, shared by all
# driver threads so pushes are serialized by the handle's lock.
_ring_cache: dict[str, Any] = {}  # guarded-by: _ring_cache_lock
_ring_cache_lock = threading.Lock()


def _node_ring(node: dict[str, Any] | None):
    """Return an attached ShmRing for a co-located node, else None."""
    if not node or not node.get("shm_ring"):
        return None
    try:
        from tensorflowonspark_tpu.native.shmring import ShmRing, available
    except Exception:  # pragma: no cover - import guard
        return None
    if not available() or node["host"] != util.get_ip_address():
        return None
    name = node["shm_ring"]
    with _ring_cache_lock:
        ring = _ring_cache.get(name)
        if ring is None:
            try:
                ring = ShmRing.open(name)
            except OSError:
                return None
            _ring_cache[name] = ring
        return ring


def _maybe_start_metrics_server(host: str) -> int | None:
    """Serve the process-global obs registry at ``GET /metrics``
    (Prometheus text format) on a free port; returns the port, or None
    when the server cannot bind. Runs in a daemon thread; the endpoint
    is read-only and allocation-free per scrape beyond the rendered
    text. This is what the driver's MetricsAggregator scrapes on the
    heartbeat cadence (``TFCluster.cluster_stats()``)."""
    from tensorflowonspark_tpu.obs.cluster import serve_text
    from tensorflowonspark_tpu.obs.registry import default_registry

    _server, port = serve_text(
        lambda: default_registry().render(), host=host
    )
    return port


# The profiler server object must outlive this module scope: jax tears the
# server down when the object is garbage-collected.
_profiler_server = None


def _maybe_start_profiler_server() -> int | None:
    """Start an in-process ``jax.profiler`` trace server on a free port.

    Every node runs one, so a TensorBoard profile session (or
    ``jax.profiler.trace``) can capture any host in the cluster; the port
    is advertised through the reservation roster
    (:meth:`TFCluster.profiler_urls`).
    """
    global _profiler_server
    try:
        import jax.profiler
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        return None
    port = util.find_free_port()
    try:
        _profiler_server = jax.profiler.start_server(port)
    except Exception as e:  # pragma: no cover - e.g. double start
        logger.warning("profiler server unavailable: %s", e)
        return None
    return port


def _maybe_start_tensorboard(log_dir: str | None) -> tuple[int | None, int]:
    """Spawn a tensorboard subprocess if the binary exists (chief only).

    Reference: ``TFSparkNode._mapfn`` tensorboard block
    (``util.find_in_path`` + subprocess + record tb_port/tb_pid).
    """
    import subprocess

    tb_bin = util.find_in_path(os.environ.get("PATH", ""), "tensorboard")
    if tb_bin is None or not log_dir:
        return None, 0
    tb_port = util.find_free_port()
    try:
        proc = subprocess.Popen(
            [tb_bin, "--logdir", log_dir, "--port", str(tb_port), "--bind_all"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return tb_port, proc.pid
    except OSError:
        return None, 0


# ---------------------------------------------------------------------------
# Feeder-side partition functions (driver/feeder process side).
# Reference: TFSparkNode.train/_train, inference/_inference, shutdown/_shutdown.
# ---------------------------------------------------------------------------


def connect_manager(node: dict[str, Any]) -> tf_manager.ManagerHandle:
    """Reconnect to a node's long-lived manager (reference: ``_get_manager``)."""
    return tf_manager.connect(node["addr"], bytes.fromhex(node["authkey"]))


def publish_node_state(mgr: tf_manager.ManagerHandle, state: str) -> None:
    """Publish this node's lifecycle state to its manager KV (schema
    ``kv.node_state`` — a closed enum, so a typo'd state string dies at
    the producer instead of silently never matching a reader's
    comparison)."""
    mgr.set(wire.NODE_STATE_KEY, wire.encode("kv.node_state", value=state))


def fetch_node_state(mgr: tf_manager.ManagerHandle) -> str:
    """The node's current lifecycle state (``"running"`` when nothing
    was ever published — the manager seeds the key at startup)."""
    raw = mgr.get(wire.NODE_STATE_KEY)
    if raw is None:
        return "running"
    return wire.decode("kv.node_state", str(raw))["value"]


# Manager KV key carrying a node's pull-plane shard assignment
# (TFCluster.assign_shards publishes it; fetch_ingest_plan probes it).
# Declared in cluster/wire.py (schema ``kv.ingest_plan``); re-exported
# here because this module is the wire's producer/consumer home.
INGEST_PLAN_KEY = wire.INGEST_PLAN_KEY


def publish_ingest_plan(
    mgr: tf_manager.ManagerHandle,
    manifests,
    epoch: int = 0,
    shard_index: int = 0,
    num_shards: int = 1,
    plan_id: str | None = None,
    handover: bool = False,
    complete: bool = False,
    seq: int | None = None,
) -> None:
    """Driver side of the pull-plane handshake: publish one node's
    shard plan to its manager KV, keyed by the membership ``epoch``.
    THE owner of the plan's wire shape — `TFCluster._publish_ingest_plan`
    and the feed-plane bench's staggered mode both go through here, so
    the dict :func:`fetch_ingest_plan` returns cannot fork between
    producers. ``handover`` arms the consumer's live-redistribution
    protocol (``ctx.get_ingest_feed`` wires the watcher + cursor
    publisher); ``complete`` is the driver's end-of-dataset marker —
    lingering consumers stop instead of waiting for more work. ``seq``
    is the plan GENERATION within one membership epoch (the growing-
    dataset wire — ``TFCluster.extend_shards`` bumps it so a lingering
    consumer adopts appended shards without a membership bump)."""
    mgr.set(
        INGEST_PLAN_KEY,
        wire.encode(
            "kv.ingest_plan",
            epoch=int(epoch),
            plan_id=plan_id,
            shard_index=int(shard_index),
            num_shards=int(num_shards),
            manifests=list(manifests),
            handover=bool(handover),
            complete=bool(complete),
            seq=None if seq is None else int(seq),
        ),
    )


def fetch_ingest_plan(
    mgr: tf_manager.ManagerHandle,
    timeout: float = 600.0,
    poll: float = 0.25,
    min_epoch: int = 0,
) -> dict[str, Any]:
    """Node side of the pull plane's control handshake: block until the
    driver publishes this node's shard plan (``TFCluster.assign_shards``
    — a dict of manifests + epoch, O(files) bytes, the ONLY thing that
    crosses the driver on the pull plane) and return it.

    Probed rather than pushed: ``map_fun`` typically asks for its feed
    before the driver has planned shards, exactly like the feed-timeout
    KV. ``min_epoch`` is the handover protocol's adoption wait: plans
    stamped with an older membership epoch (the pre-reconfigure shard
    this consumer just drained) are skipped until the driver publishes
    the re-split. Raises TimeoutError after ``timeout`` seconds — an
    ingest consumer on a cluster whose driver never planned shards is a
    programming error that must not block forever.
    """
    failpoint("ingest.manifest_fetch")
    deadline = time.monotonic() + timeout
    while True:
        raw = mgr.get(INGEST_PLAN_KEY)
        if raw is not None:
            plan = wire.decode("kv.ingest_plan", raw)
            if plan["epoch"] >= int(min_epoch):
                return plan
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"no ingest plan (epoch >= {min_epoch}) published within "
                f"{timeout}s — did the driver call "
                "TFCluster.assign_shards()?"
            )
        time.sleep(poll)


# Manager KV key carrying driver-pushed feed knobs (autotune): the
# driver-side controller re-publishes tuned node-side knobs here;
# IngestFeed polls it at block boundaries and adopts by seq.
# Declared in cluster/wire.py (schema ``kv.feed_knobs``).
FEED_KNOBS_KEY = wire.FEED_KNOBS_KEY


def publish_feed_knobs(
    mgr: tf_manager.ManagerHandle,
    knobs: dict[str, Any],
    seq: int = 0,
) -> None:
    """Driver side of the feed-knob wire, beside
    :func:`publish_ingest_plan`: publish tuned node-side feed knobs
    (currently ``publish_blocks``) to one node's manager KV. ``seq``
    must be monotonically increasing per node — the consumer adopts a
    publication exactly once and ignores stale republishes, so a
    controller's revert is just the next publication."""
    mgr.set(
        FEED_KNOBS_KEY,
        wire.encode("kv.feed_knobs", seq=int(seq), knobs=dict(knobs)),
    )


def fetch_feed_knobs(
    mgr: tf_manager.ManagerHandle,
) -> dict[str, Any] | None:
    """Node side of the feed-knob wire: one non-blocking KV read —
    ``{"seq", "knobs"}`` or None when the driver never tuned anything.
    Unlike :func:`fetch_ingest_plan` this never probes: knobs are an
    optimization, not a dependency, so a feed with no publication just
    keeps its constructor values."""
    raw = mgr.get(FEED_KNOBS_KEY)
    if raw is None:
        return None
    pub = wire.decode("kv.feed_knobs", raw)
    return {
        "seq": int(pub["seq"]),
        "knobs": dict(pub["knobs"]),
    }


def publish_ingest_cursor(
    client: reservation.Client, executor_id: int, payload: dict[str, Any]
) -> None:
    """Node side of the handover protocol's cursor wire, beside
    :func:`publish_ingest_plan`: ship one consumer's replay cursor to
    the DRIVER-side table (``reservation.Server`` ``ICURSOR``) — the
    one store that survives this node being SIGKILLed, which is exactly
    what the crash-handover path seeds a redistribution from. Payload:
    ``{"epoch", "final", "cursor", "records_per_chunk",
    "frame_blocks"}`` (see ``IngestFeed._publish_cursor``)."""
    if failpoint("ingest.cursor_publish") == "drop":
        # chaos: a lost publication — the driver falls back to the
        # previous cursor; duplicates widen by the staleness, zero-gap
        # is untouched (the documented degradation)
        return
    client.publish_cursor(executor_id, payload)


def feed_partition(
    mgr: tf_manager.ManagerHandle,
    partition,
    feed_timeout: float = 600.0,
    qname: str = "input",
    chunk: int = FEED_CHUNK,
    node: dict[str, Any] | None = None,
    columnar: bool = True,
    stream: str | None = None,
) -> int | None:
    """Push one data partition into a node's input queue, chunked.

    Pass the node's roster entry via ``node`` to enable the shared-memory
    fast path when the feeder is co-located with the node; otherwise (or
    when native support is missing) chunks go through the TCP manager
    proxy. With ``columnar=True`` (the default) each chunk is columnized
    ONCE here — per-field contiguous buffers, CRC-framed
    (``feed/columnar.py``) — and ships as a single frame: scatter-pushed
    straight from numpy memory on the ring path, one bytes payload on the
    TCP path. Chunks that cannot columnize (ragged/object records) fall
    back to the versioned row-pickle wire, chunk by chunk. Returns the
    number of records fed, or ``None`` if the node is terminating and the
    partition was skipped (distinct from feeding an empty partition,
    which returns 0). Raises TimeoutError if the consumer stopped pulling
    (reference: "Timeout while feeding partition").

    ``stream`` names the columnar stream explicitly (default: a fresh
    random id per call, so independent partitions can never collide in
    the consumer's sequence tracking). An elastic RE-FEED of a
    partition a consumer partially consumed must pass the SAME stream
    id — and the same ``chunk`` size, so the frame boundaries line up —
    as the original feed: the consumer's replay cursor
    (``DataFeed.cursor``/``seed_cursor``) then recognizes the
    already-consumed prefix as duplicates and drops it, giving
    exactly-once consumption through the replay.
    """
    from tensorflowonspark_tpu.feed import columnar as col
    from tensorflowonspark_tpu.obs import spans as obs_spans

    if fetch_node_state(mgr) in ("terminating", "finished", "error"):
        # Early-stop path: consume and discard remaining partitions
        # (reference: the state check at the top of ``_train``; 'finished'
        # and 'error' additionally, since our map_fun may have already
        # returned — feeding a consumer-less queue would only fill it up).
        for _ in partition:
            pass
        return None
    ring = _node_ring(node)
    if ring is not None:

        def put(obj, _cap=ring.capacity):
            payload = pickle.dumps((qname, obj), protocol=pickle.HIGHEST_PROTOCOL)
            if len(payload) + 4 > _cap and isinstance(obj, list) and len(obj) > 1:
                # Chunk pickles bigger than the whole ring (huge records):
                # split recursively so the fast path keeps working. The TCP
                # path has no such limit, but mixing paths mid-partition
                # would break record ordering.
                mid = len(obj) // 2
                put(obj[:mid], _cap)
                put(obj[mid:], _cap)
                return
            ring.push(payload, timeout=feed_timeout)

    else:
        q = mgr.get_queue(qname)
        put = lambda obj: q.put(obj, timeout=feed_timeout)  # noqa: E731

    seq = 0
    if not columnar:
        stream = None
    elif stream is None:
        stream = os.urandom(8).hex()

    def put_columnar(ck, buf) -> None:
        """Ship one columnar chunk as frame ``seq`` of this partition's
        stream; recurses into halves when a frame outgrows a QUARTER of
        the ring. The quarter cap is a liveness requirement, not tuning:
        consumers hold zero-copy views of frame N while blocking for
        frame N+1, so a frame sized near the whole ring deadlocks the
        plane (producer waits on space only the consumer's next pull
        would free). At cap/4 several frames coexist in flight."""
        nonlocal seq
        if ring is not None:
            # crc=False: same-host shm — the ring's length framing +
            # always-verified header CRC cover truncation, and skipping
            # the payload checksum keeps both sides single-pass
            parts = col.encode_parts(
                ck, qname=qname, stream=stream, seq=seq, crc=False
            )
            if col.parts_nbytes(parts) + 4 > ring.capacity // 4 and len(buf) > 1:
                mid = len(buf) // 2
                put_columnar(ck.view(0, mid), buf[:mid])
                put_columnar(ck.view(mid, len(buf)), buf[mid:])
                return
            # stream/seq args mirror the frame header: the consumer's
            # feed.queue_get span carries the same pair, so
            # tools/trace_merge.py links producer->consumer per frame
            with obs_spans.span(
                "feed.send", stream=stream, seq=seq, path="shm"
            ):
                ring.push_parts(parts, timeout=feed_timeout)
        else:
            with obs_spans.span(
                "feed.send", stream=stream, seq=seq, path="tcp"
            ):
                put(
                    col.ColumnarFrame(
                        col.frame_bytes(
                            ck, qname=qname, stream=stream, seq=seq
                        )
                    )
                )
        seq += 1

    def send(buf: list) -> None:
        if columnar:
            with obs_spans.span(
                "feed.columnize", records=len(buf), stream=stream
            ):
                ck = col.columnize_records(buf)
            if ck is not None:
                put_columnar(ck, buf)
                return
            col.metrics()["fallback"].inc(reason="not_columnizable")
        put(buf)

    count = 0
    buf: list[Any] = []
    try:
        for item in partition:
            buf.append(item)
            if len(buf) >= chunk:
                send(buf)
                count += len(buf)
                buf = []
        if buf:
            send(buf)
            count += len(buf)
        put(EndPartition())
    except (_queue.Full, TimeoutError):
        raise TimeoutError(
            f"timeout while feeding partition (feed_timeout={feed_timeout}s); "
            "consumer appears to have stopped pulling"
        ) from None
    return count


def collect_results(
    mgr: tf_manager.ManagerHandle,
    count: int,
    timeout: float = 600.0,
    qname: str = "output",
) -> list[Any]:
    """Pull exactly ``count`` results off a node's output queue.

    Results arrive as chunks (lists) — the equal-count contract of the
    reference's ``_inference`` (one result per input record, in order).
    """
    out: list[Any] = []
    deadline = time.monotonic() + timeout
    q = mgr.get_queue(qname)
    while len(out) < count:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(
                f"timeout collecting inference results ({len(out)}/{count})"
            )
        try:
            item = q.get(block=True, timeout=min(remaining, 5.0))
        except _queue.Empty:
            # Fail fast if the consumer crashed instead of blocking for the
            # whole feed_timeout; the driver will surface its traceback
            # from the error queue.
            if fetch_node_state(mgr) == "error":
                raise RuntimeError(
                    "node entered error state while collecting results"
                ) from None
            continue
        q.task_done()
        if isinstance(item, list):
            out.extend(item)
        else:
            out.append(item)
    if len(out) > count:
        raise RuntimeError(
            f"inference produced {len(out)} results for {count} inputs; "
            "map_fun must emit exactly one result per record"
        )
    return out


def _push_end_of_feed(
    node: dict[str, Any],
    qnames,
    timeout: float,
    must_deliver: bool,
) -> None:
    """Push EndOfFeed markers behind any in-flight data (via the shm ring
    when this driver fed through it — the marker must not overtake records
    still in the ring), then close the ring's write side.

    ``must_deliver=True`` raises on a push timeout: a dropped marker means
    the consumer never sees end-of-stream and blocks forever.
    """
    with _ring_cache_lock:
        ring = _ring_cache.get(node.get("shm_ring") or "")
    for qname in qnames:
        try:
            if failpoint("node.close_feed") == "drop":
                # Chaos: simulate a lost end-of-feed marker — the
                # must_deliver contract below is exactly what a real
                # drop would violate, so surface it as the timeout.
                raise TimeoutError("failpoint dropped EndOfFeed")
            if ring is not None:
                ring.push(
                    pickle.dumps(
                        (qname, EndOfFeed()), protocol=pickle.HIGHEST_PROTOCOL
                    ),
                    timeout=timeout,
                )
            else:
                mgr = connect_manager(node)
                mgr.get_queue(qname).put(EndOfFeed(), timeout=timeout)
        except (_queue.Full, TimeoutError):
            if must_deliver:
                raise TimeoutError(
                    f"could not deliver EndOfFeed to node "
                    f"{node['executor_id']} queue {qname!r} within "
                    f"{timeout}s (consumer stopped pulling?)"
                ) from None
            logger.warning(
                "could not push EndOfFeed to node %s queue %s (full)",
                node["executor_id"],
                qname,
            )
    if ring is not None:
        ring.close_write()
        # Drop the producer handle: keeping it mapped would pin the (now
        # unlinked) segment's pages for the driver's whole lifetime.
        with _ring_cache_lock:
            _ring_cache.pop(node.get("shm_ring"), None)
        ring.close()


def close_feed(
    node: dict[str, Any], qname: str = "input", timeout: float = 600.0
) -> None:
    """Mark a node's feed complete: EndOfFeed behind any in-flight data,
    leaving the node *running* so it finishes consuming. Unlike
    :func:`shutdown_node` the state is untouched — the training loop sees
    a clean end-of-stream, not early termination. After this no more data
    may be fed to ``qname`` (the shm ring's write side is closed).

    This is what lets multi-controller SPARK-mode workers use
    ``DataFeed.synchronized_batch_stream``: feeds must actually END for
    the all-hosts exhaustion agreement to trigger (a merely-quiet feed
    blocks in the queue, never reaching the agreement). Raises
    TimeoutError if the marker cannot be delivered — a silently dropped
    marker would hang every process in that agreement.
    """
    _push_end_of_feed(node, (qname,), timeout=timeout, must_deliver=True)


def shutdown_node(node: dict[str, Any], queues=("input",)) -> None:
    """Signal one node to finish: EndOfFeed on data queues, STOP on control.

    Reference: ``TFSparkNode._shutdown`` (set state, push terminal markers).
    """
    mgr = connect_manager(node)
    state = fetch_node_state(mgr)
    if state == "running":
        publish_node_state(mgr, "terminating")
    # Best-effort markers: the 'terminating' state already makes the node
    # drain, so a full queue here is a warning, not a hang.
    _push_end_of_feed(node, queues, timeout=30, must_deliver=False)
    mgr.get_queue("control").put(STOP)


def drain_errors(node: dict[str, Any]) -> list[dict[str, Any]]:
    """Non-blocking read of a node's error queue (exception ferry)."""
    mgr = connect_manager(node)
    errors = []
    q = mgr.get_queue("error")
    while True:
        try:
            errors.append(q.get_nowait())
            q.task_done()
        except _queue.Empty:
            return errors


def _hostname() -> str:  # pragma: no cover - trivial
    return socket.gethostname()
