"""Standalone CLI to query or stop a running reservation server.

Reference parity: ``tensorflowonspark/reservation_client.py`` — the
out-of-band cluster kill switch.

Usage::

    python -m tensorflowonspark_tpu.cluster.reservation_client <host> <port> [stop]
"""

from __future__ import annotations

import sys

from tensorflowonspark_tpu.cluster.reservation import Client


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print(__doc__)
        return 2
    host, port = argv[0], int(argv[1])
    client = Client((host, port))
    if len(argv) > 2 and argv[2] == "stop":
        client.request_stop()
        print("requested stop")
    else:
        for node in client.get_reservations():
            print(node)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
