"""Per-node IPC manager: named queues + a KV store, reachable over TCP.

Reference parity: ``tensorflowonspark/TFManager.py`` (``start``, ``connect``,
proxies ``get_queue``/``get``/``set``, modes ``'local'``/``'remote'``).

Design difference from the reference (deliberate, TPU-first): the reference
started the manager in a *separate* server process (fork) and both the
Spark task and the TF child paid pickle-proxy cost per queue op — SURVEY.md
§3.2 flags that as the dominant overhead. Here the manager server runs as a
*thread inside the node process that owns the training loop*, so the
consumer (`DataFeed`) reads plain in-process queues with zero IPC; only
remote producers (feeder tasks / the driver) pay the proxy cost, and they
amortize it by putting whole batches per call.

``mode='local'`` binds loopback only; ``mode='remote'`` binds all
interfaces (needed when the driver on another host feeds this node).
"""

from __future__ import annotations

import logging
import queue
import threading
from multiprocessing.managers import BaseManager
from typing import Any, Iterable

logger = logging.getLogger(__name__)

DEFAULT_QUEUES = ("input", "output", "error", "control")
DEFAULT_MAXSIZE = 1024


class _ManagerBase(BaseManager):
    """Registry holder; per-call subclasses bind instance state."""


class ManagerHandle:
    """Uniform handle over a local (in-process) or remote (proxied) manager.

    API parity with the reference's manager usage:
    ``get_queue(qname)`` → queue-like with put/get/task_done/join;
    ``get(key)`` / ``set(key, value)`` → KV store (holds ``'state'``:
    ``'running'`` | ``'terminating'`` | ``'stopped'``).
    """

    def __init__(
        self,
        *,
        address: tuple[str, int],
        authkey: bytes,
        qdict: dict[str, queue.Queue] | None = None,
        kdict: dict[str, Any] | None = None,
        remote_mgr: BaseManager | None = None,
        server: object | None = None,
    ):
        self.address = address
        self._authkey = authkey
        self._qdict = qdict
        self._kdict = kdict
        self._remote = remote_mgr
        self._server = server

    @property
    def is_local(self) -> bool:
        return self._qdict is not None

    def get_queue(self, qname: str):
        if self._qdict is not None:
            return self._qdict[qname]
        return self._remote.get_queue(qname)  # type: ignore[union-attr]

    def get(self, key: str) -> Any:
        if self._kdict is not None:
            return self._kdict.get(key)
        return self._remote.get_kv().get(key)  # type: ignore[union-attr]

    def set(self, key: str, value: Any) -> None:
        if self._kdict is not None:
            self._kdict[key] = value
        else:
            self._remote.get_kv().update({key: value})  # type: ignore[union-attr]

    def stop(self) -> None:
        """Stop the server thread and release its port (local handles only).

        ``Server.serve_forever`` installs a *fresh* ``stop_event`` when the
        thread starts, so the event must be read off the server at stop
        time, not captured at start.
        """
        if self._server is None:
            return
        stop_event = getattr(self._server, "stop_event", None)
        if stop_event is not None:
            stop_event.set()
        listener = getattr(self._server, "listener", None)
        if listener is not None:
            try:
                listener.close()  # unblock the accepter thread
            except OSError:
                pass


def start(
    authkey: bytes,
    queues: Iterable[str] = DEFAULT_QUEUES,
    mode: str = "local",
    maxsize: int = DEFAULT_MAXSIZE,
) -> ManagerHandle:
    """Start a manager server thread in this process; return a local handle.

    Reference: ``TFManager.py:start``. The returned handle's ``address`` and
    the ``authkey`` are what remote producers need for :func:`connect`; the
    node registers them with the reservation server.
    """
    qdict: dict[str, queue.Queue] = {
        name: queue.Queue(maxsize=maxsize) for name in queues
    }
    kdict: dict[str, Any] = {"state": "running"}

    class _Mgr(_ManagerBase):
        pass

    # Registered callables run in server worker threads of THIS process and
    # close over qdict/kdict directly; BaseManager returns proxies to callers.
    _Mgr.register("get_queue", callable=lambda qname: qdict[qname])
    _Mgr.register("get_kv", callable=lambda: kdict)

    host = "127.0.0.1" if mode == "local" else ""
    mgr = _Mgr(address=(host, 0), authkey=authkey)
    server = mgr.get_server()

    thread = threading.Thread(
        target=server.serve_forever, name="tfmanager-server", daemon=True
    )
    thread.start()

    addr = server.address
    advertised = addr[0]
    if advertised in ("", "0.0.0.0"):
        from tensorflowonspark_tpu.utils.util import get_ip_address

        advertised = get_ip_address()
    logger.info("manager serving on %s:%d (mode=%s)", advertised, addr[1], mode)
    return ManagerHandle(
        address=(advertised, addr[1]),
        authkey=authkey,
        qdict=qdict,
        kdict=kdict,
        server=server,
    )


def connect(address: tuple[str, int] | list, authkey: bytes) -> ManagerHandle:
    """Connect to a manager started elsewhere; return a remote handle.

    Reference: ``TFManager.py:connect``. Queue operations on the returned
    handle are proxied over TCP — producers should put *batches*, not items.
    """

    class _Mgr(_ManagerBase):
        pass

    _Mgr.register("get_queue")
    _Mgr.register("get_kv")
    mgr = _Mgr(address=(address[0], int(address[1])), authkey=authkey)
    mgr.connect()
    return ManagerHandle(
        address=(address[0], int(address[1])), authkey=authkey, remote_mgr=mgr
    )
