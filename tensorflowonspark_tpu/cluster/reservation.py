"""Rendezvous: turn N anonymous worker processes into an addressed cluster.

Reference parity: ``tensorflowonspark/reservation.py`` (``Reservations``,
``MessageSocket``, ``Server``, ``Client``). Same protocol shape — a driver-
side TCP server that nodes register with, a barrier until the roster is
complete, and an out-of-band STOP — but TPU-native payload: instead of
TF_CONFIG ps/worker role maps, the roster carries what
``jax.distributed.initialize`` needs (coordinator address, process ids) plus
per-node manager addresses for the data plane.

Wire format: 4-byte big-endian length prefix + JSON (the reference used
pickle; JSON avoids arbitrary-code deserialization from the network and is
plenty for roster dicts). Message SHAPES are declared in
``cluster/wire.py`` (the ``reservation.*`` schemas) and every
construction/parse here routes through its codecs — the protocol is
frozen-by-policy because a registering node may be running an older or
newer incarnation than the driver.
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from typing import Any

from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.utils.failpoints import failpoint
from tensorflowonspark_tpu.utils.retry import RetryPolicy

logger = logging.getLogger(__name__)

# Client-side default: absorb transient connect flaps (a driver mid-GC,
# a SYN dropped during coordinator restart) without failing the node.
DEFAULT_CLIENT_RETRY = RetryPolicy(
    max_attempts=5, base_delay=0.1, max_delay=2.0, deadline_s=30.0
)

_LEN = struct.Struct(">I")
_MAX_MSG = 64 * 1024 * 1024


class Reservations:
    """Thread-safe roster of registered nodes, plus per-node liveness.

    Reference: ``reservation.py:Reservations`` (add/done/remaining).
    Liveness is new surface: registration stamps ``last_seen`` for the
    node's ``executor_id`` and every ``HEARTBEAT`` refreshes it, so the
    driver can ask :meth:`dead_nodes` — "which registered nodes have
    been silent longer than the grace window" — instead of inferring
    death from a wedged feed timeout.

    Membership is also new surface (the elastic plane): the roster has a
    monotonic *membership epoch*. Epoch 0 is the startup barrier roster
    (:meth:`seal`); every reconfigure — a node declared dead and removed
    (:meth:`remove`), or a joiner registering mid-run — is published by
    :meth:`bump_epoch`, which re-derives the ACTIVE roster and
    increments the epoch. Heartbeat replies piggyback the epoch, so
    every surviving node learns of a membership change within one beat
    and can reshard instead of the driver restarting the world.
    """

    def __init__(self, required: int):
        self.required = required
        self._lock = threading.RLock()
        self._reservations: list[dict[str, Any]] = []  # guarded-by: self._lock
        self._last_seen: dict[int, float] = {}  # guarded-by: self._lock
        self._epoch = 0  # guarded-by: self._lock
        # Active membership (executor ids). None until seal(): before the
        # startup barrier completes, "membership" is just the roster.
        self._active_ids: list[int] | None = None  # guarded-by: self._lock
        # Pull-plane replay cursors, by executor id (the handover
        # protocol's durable store — docs/ROBUSTNESS.md "Live shard
        # redistribution"). Lives HERE, on the driver, precisely so a
        # SIGKILLed node's last published cursor survives it: remove()
        # deliberately leaves this table alone, because a dead node's
        # cursor is the seed its orphaned shard is redistributed from.
        self._cursors: dict[int, dict[str, Any]] = {}  # guarded-by: self._lock

    def add(self, meta: dict[str, Any]) -> None:
        # Idempotent per executor_id: Client._call retries the REG when
        # the ack is lost, and the replay must update the roster entry,
        # not duplicate it (a duplicate would complete the barrier with
        # a node missing).
        with self._lock:
            eid = meta.get("executor_id")
            if eid is not None:
                for i, existing in enumerate(self._reservations):
                    if existing.get("executor_id") == eid:
                        self._reservations[i] = meta
                        break
                else:
                    self._reservations.append(meta)
                self._last_seen[int(eid)] = time.monotonic()
            else:
                self._reservations.append(meta)

    def heartbeat(self, executor_id: int) -> None:
        with self._lock:
            self._last_seen[int(executor_id)] = time.monotonic()

    def last_seen(self) -> dict[int, float]:
        """{executor_id: seconds since the last heartbeat/registration}."""
        now = time.monotonic()
        with self._lock:
            return {eid: now - ts for eid, ts in self._last_seen.items()}

    def dead_nodes(self, grace: float) -> list[int]:
        """Executor ids silent for longer than ``grace`` seconds.

        Registration counts as the first heartbeat, so a node is never
        "dead" before it ever existed; a node that exited after a clean
        shutdown is the caller's business (stop polling once the
        cluster is being torn down).
        """
        now = time.monotonic()
        with self._lock:
            return sorted(
                eid
                for eid, ts in self._last_seen.items()
                if now - ts > grace
            )

    # -- membership epoch (elastic plane) ------------------------------

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def seal(self) -> None:
        """Freeze the startup-barrier roster as epoch-0 membership.

        Called once the barrier completes; until then every registered
        node IS a member. Idempotent — a second seal is a no-op so a
        reconstructed driver handle cannot reset membership."""
        with self._lock:
            if self._active_ids is None:
                self._active_ids = sorted(
                    int(m["executor_id"])
                    for m in self._reservations
                    if m.get("executor_id") is not None
                )

    def active(self) -> list[dict[str, Any]]:
        """The CURRENT membership roster (executor-id order). Before
        :meth:`seal`, every reservation; after, only sealed/bumped-in
        members — a mid-run registration (a joiner) stays pending until
        the driver publishes it via :meth:`bump_epoch`."""
        with self._lock:
            if self._active_ids is None:
                return list(self._reservations)
            ids = set(self._active_ids)
            return sorted(
                (
                    m
                    for m in self._reservations
                    if m.get("executor_id") in ids
                ),
                key=lambda m: m["executor_id"],
            )

    def pending_joins(self) -> list[dict[str, Any]]:
        """Registrations that are not (or no longer) members — a
        replacement node re-registering after its predecessor was
        removed, or a brand-new voluntary joiner. The driver's elastic
        supervision turns these into an epoch bump."""
        with self._lock:
            if self._active_ids is None:
                return []
            ids = set(self._active_ids)
            return sorted(
                (
                    m
                    for m in self._reservations
                    if m.get("executor_id") is not None
                    and m["executor_id"] not in ids
                ),
                key=lambda m: m["executor_id"],
            )

    def remove(self, executor_id: int) -> None:
        """Drop a (dead or departing) node from the roster AND the
        liveness table — a removed node must stop tripping
        :meth:`dead_nodes` forever, and its stale roster entry must not
        shadow a replacement's re-registration."""
        with self._lock:
            eid = int(executor_id)
            self._reservations = [
                m
                for m in self._reservations
                if m.get("executor_id") != eid
            ]
            self._last_seen.pop(eid, None)
            if self._active_ids is not None:
                self._active_ids = [i for i in self._active_ids if i != eid]

    def bump_epoch(self, active_ids: list[int] | None = None) -> int:
        """Publish a new membership epoch.

        ``active_ids`` pins the new membership explicitly; None means
        "every currently registered node" (removals already happened via
        :meth:`remove`, joins via their registration). Returns the new
        epoch — strictly monotonic, so consumers can order reconfigures
        even across driver log gaps."""
        with self._lock:
            if active_ids is None:
                self._active_ids = sorted(
                    int(m["executor_id"])
                    for m in self._reservations
                    if m.get("executor_id") is not None
                )
            else:
                self._active_ids = sorted(int(i) for i in active_ids)
            self._epoch += 1
            return self._epoch

    # -- pull-plane replay cursors (live shard redistribution) ---------

    def put_cursor(self, executor_id: int, payload: dict[str, Any]) -> None:
        """Record one node's latest ingest replay cursor (latest wins —
        consumption claims only ever grow, so the newest publication
        supersedes)."""
        with self._lock:
            self._cursors[int(executor_id)] = dict(payload)

    def cursors(self) -> dict[int, dict[str, Any]]:
        """Every node's latest cursor payload — departed nodes
        included (their last publication is the redistribution seed)."""
        with self._lock:
            return {k: dict(v) for k, v in self._cursors.items()}

    def membership(self) -> dict[str, Any]:
        """{"epoch": int, "roster": active roster} in one locked read —
        the QEPOCH payload (an epoch and someone ELSE's roster would
        tear)."""
        with self._lock:
            return {"epoch": self._epoch, "roster": self.active()}

    def done(self) -> bool:
        with self._lock:
            return len(self._reservations) >= self.required

    def get(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._reservations)

    def remaining(self) -> int:
        with self._lock:
            return self.required - len(self._reservations)


class MessageSocket:
    """Length-prefixed JSON messages over a stream socket.

    Reference: ``reservation.py:MessageSocket`` (which framed pickle the
    same way: 4-byte length prefix + payload).
    """

    @staticmethod
    def send(sock: socket.socket, msg: dict[str, Any]) -> None:
        data = json.dumps(msg).encode("utf-8")
        sock.sendall(_LEN.pack(len(data)) + data)

    @staticmethod
    def receive(sock: socket.socket) -> dict[str, Any]:
        header = MessageSocket._recv_exact(sock, _LEN.size)
        (length,) = _LEN.unpack(header)
        if length > _MAX_MSG:
            raise ValueError(f"message too large: {length}")
        data = MessageSocket._recv_exact(sock, length)
        return json.loads(data.decode("utf-8"))

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("socket closed mid-message")
            buf.extend(chunk)
        return bytes(buf)


class Server:
    """Driver-side rendezvous server.

    Message types (reference: ``reservation.py:Server`` REG/QUERY/QINFO/STOP):

    - ``REG``   {node: {...}} → ack; adds the node to the roster
    - ``QUERY`` → {done: bool} — is the roster complete?
    - ``QINFO`` → {cluster_info: [...]} — the full roster (valid once done)
    - ``QNUM``  → {remaining: int}
    - ``QEPOCH`` → {epoch: int, roster: [...]} — the current membership
      epoch and ACTIVE roster (the elastic plane: nodes refetch this
      when a heartbeat reply shows the epoch moved)
    - ``HEARTBEAT`` {executor_id} → {stop: bool, epoch: int,
      server_unix: float};
      refreshes the node's last-seen stamp (the liveness plane — see
      ``Reservations.dead_nodes``) and piggybacks the out-of-band stop
      flag so heartbeaters learn of a cluster kill within one beat.
      ``server_unix`` is the driver's wall clock at reply time: the
      node heartbeater turns (send time, reply time, server_unix) into
      an NTP-style clock-offset estimate (``obs.cluster.
      note_clock_sync``) that ``tools/trace_merge.py`` uses to align
      per-node trace timelines
    - ``ICURSOR`` {executor_id, payload} → ack; records the node's
      latest pull-plane replay cursor in the driver-side table
      (``Reservations.put_cursor`` — the live-shard-redistribution
      protocol's durable cursor store, which must outlive the
      publishing node)
    - ``STOP``  → ack; raises the stop flag that `Client.await_stop` and
      node watchdogs observe (out-of-band cluster kill)
    """

    def __init__(self, count: int):
        self.reservations = Reservations(count)
        self.done = threading.Event()
        self._stop = threading.Event()
        self._sock: socket.socket | None = None
        self._thread: threading.Thread | None = None

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def dead_nodes(self, grace: float) -> list[int]:
        """Registered nodes whose last heartbeat is older than ``grace``."""
        return self.reservations.dead_nodes(grace)

    def start(self, host: str = "", port: int = 0) -> tuple[str, int]:
        """Bind, spawn the listener thread, return the advertised address."""
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        addr = self._sock.getsockname()
        advertised = addr[0] if addr[0] not in ("0.0.0.0", "") else _local_ip()
        self._thread = threading.Thread(
            target=self._serve, name="reservation-server", daemon=True
        )
        self._thread.start()
        logger.info("reservation server listening on %s:%d", advertised, addr[1])
        return (advertised, addr[1])

    def _serve(self) -> None:
        assert self._sock is not None
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._handle, args=(conn,), daemon=True)
            t.start()
        try:
            self._sock.close()
        except OSError:
            pass

    def _handle(self, conn: socket.socket) -> None:
        with conn:
            conn.settimeout(60)
            while True:
                try:
                    msg = MessageSocket.receive(conn)
                except (ConnectionError, socket.timeout, ValueError):
                    return
                mtype = wire.message_kind(msg)
                try:
                    if mtype == "REG":
                        req = wire.decode("reservation.REG", msg)
                        self.reservations.add(req["node"])
                        if self.reservations.done():
                            self.done.set()
                        MessageSocket.send(
                            conn, wire.encode("reservation.REG.reply")
                        )
                    elif mtype == "QUERY":
                        MessageSocket.send(
                            conn,
                            wire.encode(
                                "reservation.QUERY.reply",
                                done=self.reservations.done(),
                            ),
                        )
                    elif mtype == "QINFO":
                        MessageSocket.send(
                            conn,
                            wire.encode(
                                "reservation.QINFO.reply",
                                cluster_info=self.reservations.get(),
                            ),
                        )
                    elif mtype == "QNUM":
                        MessageSocket.send(
                            conn,
                            wire.encode(
                                "reservation.QNUM.reply",
                                remaining=self.reservations.remaining(),
                            ),
                        )
                    elif mtype == "QEPOCH":
                        MessageSocket.send(
                            conn,
                            wire.encode(
                                "reservation.QEPOCH.reply",
                                **self.reservations.membership(),
                            ),
                        )
                    elif mtype == "ICURSOR":
                        # pull-plane cursor publication (handover
                        # protocol): stored driver-side so it survives
                        # the publisher
                        req = wire.decode("reservation.ICURSOR", msg)
                        self.reservations.put_cursor(
                            req["executor_id"], req.get("payload") or {}
                        )
                        MessageSocket.send(
                            conn, wire.encode("reservation.ICURSOR.reply")
                        )
                    elif mtype == "HEARTBEAT":
                        req = wire.decode("reservation.HEARTBEAT", msg)
                        self.reservations.heartbeat(req["executor_id"])
                        MessageSocket.send(
                            conn,
                            wire.encode(
                                "reservation.HEARTBEAT.reply",
                                stop=self._stop.is_set(),
                                # elastic plane: the beat a node already
                                # pays for is how it learns membership
                                # moved
                                epoch=self.reservations.epoch(),
                                server_unix=time.time(),
                            ),
                        )
                    elif mtype == "STOP":
                        self._stop.set()
                        MessageSocket.send(
                            conn, wire.encode("reservation.STOP.reply")
                        )
                        return
                    else:
                        MessageSocket.send(
                            conn,
                            wire.encode(
                                "reservation.ERR",
                                error=f"unknown type {mtype!r}",
                            ),
                        )
                except wire.WireDecodeError as e:
                    # a malformed request (foreign speaker, version
                    # skew beyond the frozen contract): reject THIS
                    # message loudly, keep the connection's loop —
                    # same containment as an unknown kind
                    MessageSocket.send(
                        conn, wire.encode("reservation.ERR", error=str(e))
                    )

    def await_reservations(
        self,
        timeout: float = 600.0,
        status_fn=None,
        poll_interval: float = 1.0,
    ) -> list[dict[str, Any]]:
        """Block until all nodes registered, else raise.

        Reference: ``reservation.py:Server.await_reservations`` — the
        ``reservation_timeout`` (default 600 s) is the cluster-startup
        failure detector: one lost node fails the job loudly instead of
        hanging it.
        """
        deadline = time.monotonic() + timeout
        while not self.done.wait(poll_interval):
            if self._stop.is_set():
                raise RuntimeError("reservation server stopped while waiting")
            if status_fn is not None:
                status_fn(self.reservations.remaining())
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"timed out waiting for {self.reservations.remaining()} of "
                    f"{self.reservations.required} nodes to register "
                    f"(reservation_timeout={timeout}s)"
                )
        return self.reservations.get()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class Client:
    """Node-side rendezvous client.

    Reference: ``reservation.py:Client`` (register, get_reservations,
    await_reservations with a 1 s poll loop, request_stop).

    Every RPC (one connect + send + receive) runs under ``retry`` —
    exponential backoff with full jitter — so a transient connect flap
    (driver mid-GC, listen backlog burst at cluster boot) is absorbed
    instead of failing the whole node. Pass ``retry=RetryPolicy(
    max_attempts=1)`` for the old fail-fast behavior (heartbeaters do:
    a missed beat just ages the node's last-seen stamp, and a retry
    loop inside the beat thread would mask the very signal liveness
    detection reads).
    """

    def __init__(
        self,
        server_addr: tuple[str, int] | list,
        retry: RetryPolicy | None = None,
    ):
        self.server_addr = (server_addr[0], int(server_addr[1]))
        self.retry = DEFAULT_CLIENT_RETRY if retry is None else retry

    def _call(self, msg: dict[str, Any], timeout: float = 60.0) -> dict[str, Any]:
        def roundtrip() -> dict[str, Any]:
            failpoint("reservation.call")
            with socket.create_connection(
                self.server_addr, timeout=timeout
            ) as sock:
                MessageSocket.send(sock, msg)
                return MessageSocket.receive(sock)

        from tensorflowonspark_tpu.utils.failpoints import FailpointError

        reply = self.retry.call(
            roundtrip,
            retry_on=(ConnectionError, TimeoutError, OSError, FailpointError),
            site="reservation.call",
        )
        if wire.message_kind(reply) == "ERR":
            err = wire.decode("reservation.ERR", reply)
            raise RuntimeError(f"reservation server error: {err['error']}")
        return reply

    def register(self, node_meta: dict[str, Any]) -> None:
        failpoint("reservation.register")
        self._call(wire.encode("reservation.REG", node=node_meta))

    def heartbeat(self, executor_id: int) -> dict[str, Any]:
        """One liveness beat; the reply carries the server's stop flag."""
        failpoint("reservation.heartbeat")
        return wire.decode(
            "reservation.HEARTBEAT.reply",
            self._call(
                wire.encode(
                    "reservation.HEARTBEAT", executor_id=int(executor_id)
                ),
                timeout=10.0,
            ),
        )

    def get_reservations(self) -> list[dict[str, Any]]:
        reply = wire.decode(
            "reservation.QINFO.reply",
            self._call(wire.encode("reservation.QINFO")),
        )
        return reply["cluster_info"]

    def publish_cursor(
        self, executor_id: int, payload: dict[str, Any]
    ) -> None:
        """Publish this node's pull-plane replay cursor to the driver's
        durable table (``ICURSOR``). Payloads must be JSON-shaped —
        cursors are ``{stream: seq | [seq, skip]}`` dicts, which are."""
        self._call(
            wire.encode(
                "reservation.ICURSOR",
                executor_id=int(executor_id),
                payload=payload,
            ),
            timeout=10.0,
        )

    def membership(self) -> dict[str, Any]:
        """Current membership: ``{"epoch": int, "roster": [...]}`` —
        fetched by node heartbeaters when a beat reply's epoch moves."""
        reply = wire.decode(
            "reservation.QEPOCH.reply",
            self._call(wire.encode("reservation.QEPOCH"), timeout=10.0),
        )
        return {
            "epoch": int(reply["epoch"]),
            "roster": reply["roster"],
        }

    def await_reservations(
        self, timeout: float = 600.0, poll_interval: float = 1.0
    ) -> list[dict[str, Any]]:
        deadline = time.monotonic() + timeout
        while True:
            reply = wire.decode(
                "reservation.QUERY.reply",
                self._call(wire.encode("reservation.QUERY")),
            )
            if reply["done"]:
                return self.get_reservations()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "timed out waiting for cluster roster "
                    f"(reservation_timeout={timeout}s)"
                )
            time.sleep(poll_interval)

    def request_stop(self) -> None:
        self._call(wire.encode("reservation.STOP"))


def _local_ip() -> str:
    from tensorflowonspark_tpu.utils.util import get_ip_address

    return get_ip_address()
