"""Per-node context handed to the user's ``map_fun``.

Reference parity: ``tensorflowonspark/TFSparkNode.py:TFNodeContext``
(fields ``executor_id``/``worker_num``, ``job_name``, ``task_index``,
``cluster_spec``, ``num_workers``, ``defaultFS``, ``working_dir``, ``mgr``;
methods ``get_data_feed``, ``absolute_path``, ``start_cluster_server``,
``export_saved_model``).

TPU-native differences: instead of a TF ``ClusterSpec``/``TF_CONFIG``, the
context carries the ``jax.distributed`` coordinator address and exposes
:meth:`initialize_distributed` + :meth:`mesh` — the SPMD replacement for
both the PS and MultiWorkerMirroredStrategy wiring.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any

from tensorflowonspark_tpu.feed.datafeed import DataFeed

logger = logging.getLogger(__name__)


@dataclass
class TFNodeContext:
    executor_id: int
    job_name: str  # 'chief' | 'worker' | 'evaluator'
    task_index: int
    cluster_info: list[dict[str, Any]]
    num_workers: int
    default_fs: str
    working_dir: str
    mgr: Any = None  # ManagerHandle
    coordinator_address: str | None = None
    distributed: bool = False
    tb_port: int | None = None
    log_dir: str | None = None
    extras: dict[str, Any] = field(default_factory=dict)

    # --- reference-compat aliases -------------------------------------
    @property
    def worker_num(self) -> int:
        """Reference alias for executor_id."""
        return self.executor_id

    @property
    def num_processes(self) -> int:
        return self.num_workers

    @property
    def cluster_spec(self) -> dict[str, list[str]]:
        """TF_CONFIG-shaped view of the roster: {job: ["host:port", ...]}.

        Provided for reference-API compatibility; TPU code should use
        ``coordinator_address`` / ``mesh()`` instead.
        """
        spec: dict[str, list[str]] = {}
        for node in sorted(self.cluster_info, key=lambda n: n["executor_id"]):
            spec.setdefault(node["job_name"], []).append(
                f"{node['host']}:{node['port']}"
            )
        return spec

    # --- data plane ----------------------------------------------------
    def get_data_feed(
        self,
        train_mode: bool = True,
        qname_in: str = "input",
        qname_out: str = "output",
        input_mapping: dict[str, str] | None = None,
        feed_timeout: float | None = None,
    ) -> DataFeed:
        """Reference: ``TFNodeContext.get_data_feed``. ``feed_timeout``
        overrides the driver-published pull-loop policy (see
        ``DataFeed.feed_timeout``)."""
        return DataFeed(
            self.mgr,
            train_mode,
            qname_in,
            qname_out,
            input_mapping,
            feed_timeout=feed_timeout,
            worker_index=self.executor_id,
        )

    def get_ingest_feed(
        self,
        input_mapping: dict[str, str] | None = None,
        reader=None,
        timeout: float = 600.0,
        **kwargs,
    ):
        """The pull plane's feed (``InputMode.TENSORFLOW`` default):
        block for this node's driver-published shard plan
        (``TFCluster.assign_shards``) and return an
        :class:`~tensorflowonspark_tpu.feed.ingest.IngestFeed` reading
        the shard executor-locally — same ``next_batch``/
        ``batch_stream``/``DevicePrefetcher.from_feed`` surface as
        :meth:`get_data_feed`, no driver in the data loop. ``reader``
        overrides manifest expansion (custom formats); extra kwargs
        reach the ``IngestFeed`` constructor (``records_per_chunk``,
        ``retry``, ``publish_blocks``, ``adopt_timeout``).

        Plans published by an elastic cluster carry ``handover: True``:
        the returned feed is then wired into the live-shard-
        redistribution protocol — it watches the membership epoch via
        the elastic watcher, publishes its replay cursor to the
        driver's durable table, and adopts driver re-splits on epoch
        bumps (docs/ROBUSTNESS.md "Live shard redistribution")."""
        from tensorflowonspark_tpu.cluster.node import (
            fetch_feed_knobs,
            fetch_ingest_plan,
        )
        from tensorflowonspark_tpu.feed.ingest import IngestFeed

        plan = fetch_ingest_plan(self.mgr, timeout=timeout)
        # Driver-pushed feed knobs (autotune): wired unconditionally —
        # one non-blocking KV read per (time-gated) poll; a cluster
        # that never tunes simply never publishes the key.
        wires: dict[str, Any] = {
            "knob_fetch": lambda: fetch_feed_knobs(self.mgr),
        }
        server_addr = self.extras.get("server_addr")
        if plan.get("handover") and server_addr is not None:
            from tensorflowonspark_tpu.cluster import reservation
            from tensorflowonspark_tpu.cluster.node import (
                publish_ingest_cursor,
            )
            from tensorflowonspark_tpu.compute import elastic

            client = reservation.Client(server_addr)
            eid = self.executor_id

            def _publish(payload: dict[str, Any]) -> None:
                publish_ingest_cursor(client, eid, payload)

            def _plan_fetch(min_epoch: int, fetch_timeout: float):
                try:
                    return fetch_ingest_plan(
                        self.mgr,
                        timeout=fetch_timeout,
                        min_epoch=min_epoch,
                    )
                except TimeoutError:
                    return None

            wires.update(
                plan_fetch=_plan_fetch,
                cursor_publish=_publish,
                epoch_watch=elastic.current_epoch,
            )
        return IngestFeed(
            plan["manifests"],
            input_mapping=input_mapping,
            reader=reader,
            plan_epoch=int(plan.get("epoch", 0)),
            plan_seq=int(plan.get("seq") or 0),
            worker_index=self.executor_id,
            **wires,
            **kwargs,
        )

    # --- paths ----------------------------------------------------------
    def absolute_path(self, path: str) -> str:
        """Resolve a user path against default_fs / working_dir.

        Reference: ``TFNode.py:hdfs_path`` resolution matrix — scheme-
        qualified paths pass through; absolute paths go under default_fs;
        relative paths resolve against the working dir.
        """
        from tensorflowonspark_tpu.utils.util import resolve_path

        return resolve_path(path, self.default_fs, self.working_dir)

    # --- distributed runtime --------------------------------------------
    def initialize_distributed(self) -> None:
        """Join the jax.distributed coordination service.

        This is the TPU-native replacement for the reference's
        ``TFNode.start_cluster_server`` (which built a ``tf.train.Server``
        from the ClusterSpec): the roster agreed through the reservation
        server already names a coordinator (chief's reserved port), so every
        process just calls ``jax.distributed.initialize`` with it.
        """
        if not self.distributed:
            logger.info("single-process mode; skipping jax.distributed")
            return
        import jax

        jax.distributed.initialize(
            coordinator_address=self.coordinator_address,
            num_processes=self.num_workers,
            process_id=self.executor_id,
        )
        logger.info(
            "jax.distributed initialized: process %d/%d, coordinator %s",
            self.executor_id,
            self.num_workers,
            self.coordinator_address,
        )

    # Reference-compat name.
    def start_cluster_server(self, *_args, **_kwargs) -> None:
        self.initialize_distributed()

    # --- elastic membership ----------------------------------------------
    def membership(self) -> tuple[int, list[dict[str, Any]]]:
        """(membership epoch, active roster) as last published by the
        driver (``compute/elastic.py`` watcher, fed by the heartbeater).
        Before any reconfigure: ``(0, cluster_info)`` — the startup
        barrier roster IS epoch 0."""
        from tensorflowonspark_tpu.compute import elastic

        epoch, roster = elastic.membership()
        return epoch, (self.cluster_info if roster is None else roster)

    def reinitialize_distributed(
        self, roster: list[dict[str, Any]]
    ) -> None:
        """Rebind this process to a reconfigured cluster (elastic plane).

        Updates the context's roster bookkeeping (``cluster_info``,
        ``num_workers``, ``coordinator_address``) and — in
        multi-controller mode — leaves the old ``jax.distributed``
        collective and re-initializes against the new topology: the
        lowest surviving executor hosts the coordinator, and process
        ids are the roster order (``jax.distributed`` requires a dense
        0..n−1 id space, which executor ids no longer are after a
        departure). Single-controller-per-node runs (``distributed=
        False``) only update the bookkeeping — their local runtime
        never spanned the dead peer.
        """
        roster = sorted(roster, key=lambda n: n["executor_id"])
        if not roster:
            raise ValueError("cannot reconfigure to an empty roster")
        ids = [n["executor_id"] for n in roster]
        if self.executor_id not in ids:
            # A node the driver removed (false-positive death verdict,
            # voluntary leave) must not rebind as if it were a member —
            # and must get a clear diagnosis, not a StopIteration.
            raise RuntimeError(
                f"executor {self.executor_id} is not in the new "
                f"membership {ids}; this node was removed — rejoin via "
                "registration, do not reconfigure"
            )
        self.cluster_info = roster
        self.num_workers = len(roster)
        chief = roster[0]
        self.coordinator_address = f"{chief['host']}:{chief['port']}"
        if not self.distributed:
            return
        import jax

        process_id = ids.index(self.executor_id)
        jax.distributed.shutdown()
        jax.distributed.initialize(
            coordinator_address=self.coordinator_address,
            num_processes=len(roster),
            process_id=process_id,
        )
        logger.info(
            "jax.distributed re-initialized: process %d/%d, coordinator %s",
            process_id,
            len(roster),
            self.coordinator_address,
        )

    def mesh(self, axis_shapes: dict[str, int] | None = None):
        """Build the device mesh for this cluster (all global devices).

        Delegates to :func:`tensorflowonspark_tpu.compute.mesh.make_mesh`;
        defaults to pure data-parallel over every device.
        """
        from tensorflowonspark_tpu.compute.mesh import make_mesh

        return make_mesh(axis_shapes)

    def metrics_writer(self, log_dir: str | None = None):
        """Per-node step-metrics writer (SURVEY.md §5.5).

        Writes under ``{log_dir}/node{N}/`` so the chief's tensorboard
        (``run(tensorboard=True, log_dir=...)``) aggregates every node's
        scalars — the host-0-aggregator pattern. TB event files when
        TensorFlow is importable, JSONL otherwise (same API).
        """
        from tensorflowonspark_tpu.utils.metrics import MetricsWriter

        base = log_dir or self.log_dir
        if base is None:
            raise ValueError(
                "no log_dir: pass one here or to TFCluster.run(log_dir=...)"
            )
        return MetricsWriter(
            f"{self.absolute_path(base).rstrip('/')}/node{self.executor_id}"
        )

    def export_saved_model(self, state, export_dir: str, **kwargs) -> str:
        """Chief-only model export (reference: ``TFNodeContext.export_saved_model``).

        Writes an orbax checkpoint usable by ``TFModel``/AOT inference.
        """
        from tensorflowonspark_tpu.compute.checkpoint import save_checkpoint

        if self.is_chief:
            return save_checkpoint(self.absolute_path(export_dir), state, **kwargs)
        return export_dir

    @property
    def is_chief(self) -> bool:
        """True on exactly one node: the 'chief' role, or worker:0 only in
        rosters that have no explicit chief (reference convention)."""
        if self.job_name == "chief":
            return True
        has_chief = any(n["job_name"] == "chief" for n in self.cluster_info)
        return (
            not has_chief
            and self.job_name == "worker"
            and self.task_index == 0
        )
