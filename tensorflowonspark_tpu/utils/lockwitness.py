"""tfsan runtime head: the lock witness.

The static head (``analysis/lockorder.py`` + ``analysis/blocking.py``)
reasons about code; this module watches the *process*. Under
``TFOS_TFSAN=1`` (the import hook in ``utils/__init__``), every
``threading.Lock()`` / ``threading.RLock()`` created from package code
returns a :class:`WitnessLock` — a drop-in wrapper (``with``,
``acquire``/``release``, Condition-compatible) that additionally:

- **records the lock-order graph**: acquiring B while holding A adds an
  A→B edge keyed by *creation site* (the same role aggregation the
  kernel's lockdep uses — every ``Registry._lock`` is one node). A new
  edge that closes a cycle is reported immediately as a potential ABBA
  deadlock, with the acquisition stacks of both directions — the moment
  the *second* order is first exercised, long before two threads happen
  to interleave into the actual hang;
- **detects real deadlocks online instead of hanging**: an unbounded
  ``acquire`` degrades to a probe loop; while blocked, the
  waits-for chain (thread → lock → owner → lock …) is checked, and a
  cycle raises :class:`LockWitnessDeadlock` in one participant — the
  witness report IS the test failure, not a 900 s suite timeout;
- **cross-validates ``# guarded-by:`` annotations** (:func:`watch`):
  the PR-3 static rule checks the *lexical* discipline; the witness
  checks it is *true* — a watched object's guarded attribute touched by
  a thread that does not hold the declared lock is a finding, with the
  touching site. Reads on lines carrying the ``# lint: lockfree-read:``
  escape are exempt, mirroring the static rule.

Findings accumulate in-process (:func:`findings`), mirror into the obs
flight recorder (event kind ``tfsan``), and dump as a JSON report
(:func:`dump_json`) gated by ``tools/tfsan.py --gate`` against the
multiset baseline ``tools/tfsan_baseline.json`` — the tfoslint ratchet
pattern applied to runtime evidence. ``tests/plugins/tfsan.py`` wires
dump+gate into instrumented pytest runs (``tools/run_tier1.py --slow``
runs the chaos/elastic suites this way).

Cost model (the failpoint bar): with the witness **disabled**, the
factories are one flag check over the real constructor (<1.5 µs,
micro-benched in ``tests/test_tfsan.py``) and nothing is patched unless
the import hook ran. Instrumented acquires cost one small-dict
bookkeeping under an internal lock — witness runs are a scheduled CI
tier, not the production path.
"""

from __future__ import annotations

import ast
import json
import logging
import os
import sys
import threading
import time
from typing import Any

logger = logging.getLogger(__name__)

__all__ = [
    "LockWitnessDeadlock",
    "WitnessLock",
    "disable",
    "dump_json",
    "enable",
    "enabled",
    "findings",
    "guarded_attrs",
    "install",
    "installed",
    "new_lock",
    "new_rlock",
    "reset",
    "uninstall",
    "watch",
]

# the REAL factories, captured before any patching
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_THIS_FILE = os.path.abspath(__file__)
_PROBE_S = 0.05  # unbounded-acquire probe slice (deadlock check cadence)

# -- witness state (all guarded by _WITNESS_LOCK unless noted) ---------------
_WITNESS_LOCK = _REAL_LOCK()
_enabled = False  # read lock-free on every fast path (one flag check)
_installed = False
_graph: dict[str, set[str]] = {}  # site -> sites acquired while held
_held: dict[int, list["WitnessLock"]] = {}  # thread id -> held stack
_waiting: dict[int, "WitnessLock"] = {}  # thread id -> blocked-on lock
_findings: list[dict[str, Any]] = []
_reported: set = set()  # dedup keys
_locks_created = 0


class LockWitnessDeadlock(RuntimeError):
    """Raised out of a blocked ``acquire`` whose waits-for chain closed
    into a cycle — the witnessed alternative to hanging forever."""


def _site_parts(site: str) -> tuple[str, int]:
    if ":" in site:
        path, _, line = site.rpartition(":")
        try:
            return path, int(line)
        except ValueError:
            pass
    return site, 0


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear the order graph and findings (tests; a fresh witness run).
    Held-lock bookkeeping of live locks is preserved."""
    with _WITNESS_LOCK:
        _graph.clear()
        _findings.clear()
        _reported.clear()


def findings() -> list[dict[str, Any]]:
    with _WITNESS_LOCK:
        return [dict(f) for f in _findings]


def locks_created() -> int:
    """Witness-wrapped locks constructed so far (coverage assertion for
    instrumented runs: zero means the hook never fired)."""
    return _locks_created


def _caller_site(skip_threading: bool = True) -> str:
    """creation/access site of the nearest frame outside this module
    (and outside threading.py, so ``threading.Condition()``'s internal
    ``RLock()`` is attributed to the Condition's creator)."""
    f = sys._getframe(2)
    threading_file = getattr(threading, "__file__", "")
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _THIS_FILE and (not skip_threading or fn != threading_file):
            rel = fn
            if rel.startswith(_PKG_ROOT):
                rel = os.path.relpath(fn, os.path.dirname(_PKG_ROOT))
            return f"{rel}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _caller_frame_outside_witness():
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == _THIS_FILE:
        f = f.f_back
    return f


def _stack_text(limit: int = 8) -> str:
    import traceback

    frames = traceback.extract_stack()[:-2]
    keep = [
        fr for fr in frames if os.path.abspath(fr.filename) != _THIS_FILE
    ][-limit:]
    return "".join(traceback.format_list(keep))


def _report(rule: str, message: str, dedup: Any, **details: Any) -> None:
    """Record one finding (idempotent per ``dedup`` key), mirror it to
    the log and — best effort — the obs flight recorder."""
    with _WITNESS_LOCK:
        if dedup in _reported:
            return
        _reported.add(dedup)
        finding = {
            "rule": rule,
            "message": message,
            "path": details.get("path", "runtime"),
            "line": int(details.get("line", 0)),
            "details": {
                k: v for k, v in details.items() if k not in ("path", "line")
            },
        }
        _findings.append(finding)
    logger.error("tfsan: %s %s", rule, message)
    # obs mirrors are optional wiring, never a dependency: findings ride
    # the flight recorder (a SIGKILLed instrumented node's witness
    # events persist in its rolling flightrec dump) and the metrics
    # registry (node /metrics → the driver-side aggregator sees a child
    # process's findings without reading its report file).
    try:
        from tensorflowonspark_tpu.obs import flightrec

        flightrec.note("tfsan", rule=rule, message=message)
    except Exception:  # pragma: no cover - obs must never break witness
        pass
    try:
        from tensorflowonspark_tpu.obs.registry import default_registry

        default_registry().counter(
            "tfsan_findings_total",
            "lock-witness findings reported by this process, by rule",
        ).inc(rule=rule)
    except Exception:  # pragma: no cover - obs must never break witness
        pass


# -- the instrumented lock ---------------------------------------------------


class WitnessLock:
    """Witness-instrumented ``threading.Lock``/``RLock`` stand-in.

    Owner/reentrance bookkeeping lives here (a plain Lock has no owner
    concept — the witness adds one) so the guarded-by validator can ask
    "does the current thread hold this?" for either kind, and the
    deadlock probe can walk owner chains. Condition-protocol methods
    (``_release_save``/``_acquire_restore``/``_is_owned``) are provided
    so ``threading.Condition(witness_lock)`` — including the implicit
    RLock a bare ``Condition()`` creates under the import hook — works
    unchanged."""

    def __init__(self, kind: str, site: str):
        self._real = _REAL_LOCK() if kind == "lock" else _REAL_RLOCK()
        self.kind = kind
        self.site = site
        # racy-by-design reads (diagnostics + guard checks): a stale
        # owner read can only miss a report, never corrupt the lock
        self._owner: int | None = None
        self._count = 0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = f"owner={self._owner}" if self._owner else "unlocked"
        return f"<WitnessLock {self.kind} {self.site} {state}>"

    # -- order graph / deadlock machinery ------------------------------

    def _note_order(self, tid: int) -> None:
        held = _held.get(tid)
        if not held:
            return
        for h in list(held):
            a, b = h.site, self.site
            if a == b:
                continue  # same role (two instances of one site): skip
            with _WITNESS_LOCK:
                targets = _graph.setdefault(a, set())
                new_edge = b not in targets
                if new_edge:
                    targets.add(b)
                cycle = _find_path(b, a) if new_edge else None
            if cycle is not None:
                # _report takes the witness lock itself: call it OUTSIDE.
                # The stack is captured only here — on the cycle-closing
                # edge — so ordinary edge recording never pays a
                # traceback walk. cycle is the path b..a; drop its
                # trailing a — the ring closes back onto it when
                # rendered.
                ring = [a] + cycle[:-1]
                lo = ring.index(min(ring))
                canonical = ring[lo:] + ring[:lo]
                path, line = _site_parts(self.site)
                _report(
                    "TFSAN-ORDER",
                    "lock-order cycle (potential ABBA deadlock): "
                    + " -> ".join(canonical + [canonical[0]]),
                    ("order", tuple(sorted(set(ring)))),
                    path=path,
                    line=line,
                    closing_stack=_stack_text(),
                    reverse_edge=f"{b}->...->{a}",
                )

    def _deadlock_chain(self, tid: int) -> list[str] | None:
        """waits-for cycle through this blocked acquire, or None."""
        with _WITNESS_LOCK:
            chain = [self.site]
            lock = self
            seen = set()
            while True:
                owner = lock._owner
                if owner is None:
                    return None
                if owner == tid:
                    return chain
                if owner in seen:
                    return None
                seen.add(owner)
                nxt = _waiting.get(owner)
                if nxt is None:
                    return None
                chain.append(nxt.site)
                lock = nxt

    # -- lock protocol --------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _enabled:
            return self._real.acquire(blocking, timeout)
        tid = threading.get_ident()
        reentrant = self._owner == tid
        if reentrant and self.kind == "lock" and blocking and timeout < 0:
            # guaranteed self-deadlock on a plain Lock: report and
            # refuse to hang
            path, line = _site_parts(self.site)
            _report(
                "TFSAN-DEADLOCK",
                f"self-deadlock: non-reentrant lock {self.site} "
                "re-acquired by its owner",
                ("self", self.site, "reacquire"),
                path=path,
                line=line,
                stack=_stack_text(),
            )
            raise LockWitnessDeadlock(
                f"non-reentrant lock {self.site} re-acquired by its "
                "owning thread (witnessed self-deadlock)"
            )
        if not reentrant:
            self._note_order(tid)
        # the acquisition itself
        if not blocking:
            ok = self._real.acquire(False)
        elif timeout is not None and timeout >= 0:
            ok = self._real.acquire(True, timeout)
        else:
            ok = self._real.acquire(True, _PROBE_S)
            if not ok:
                with _WITNESS_LOCK:
                    _waiting[tid] = self
                try:
                    while True:
                        chain = self._deadlock_chain(tid)
                        if chain is not None:
                            path, line = _site_parts(self.site)
                            _report(
                                "TFSAN-DEADLOCK",
                                "deadlock: waits-for cycle "
                                + " -> ".join(chain + [chain[0]]),
                                ("deadlock", tuple(sorted(set(chain)))),
                                path=path,
                                line=line,
                                stack=_stack_text(),
                            )
                            raise LockWitnessDeadlock(
                                "witnessed waits-for cycle: "
                                + " -> ".join(chain + [chain[0]])
                            )
                        ok = self._real.acquire(True, _PROBE_S)
                        if ok:
                            break
                finally:
                    with _WITNESS_LOCK:
                        _waiting.pop(tid, None)
        if ok:
            with _WITNESS_LOCK:
                if reentrant:
                    self._count += 1
                else:
                    self._owner = tid
                    self._count = 1
                    _held.setdefault(tid, []).append(self)
        return ok

    def release(self) -> None:
        if not _enabled:
            # still clear any bookkeeping from when the witness WAS
            # enabled: a stale _owner surviving a disable-while-held
            # would later masquerade as a self-deadlock on a perfectly
            # legal re-acquire after re-enable
            if self._owner is not None:
                with _WITNESS_LOCK:
                    owner = self._owner
                    self._owner = None
                    self._count = 0
                    if owner is not None:
                        stack = _held.get(owner)
                        if stack and self in stack:
                            stack.remove(self)
            return self._real.release()
        tid = threading.get_ident()
        with _WITNESS_LOCK:
            if self._owner == tid:
                self._count -= 1
                if self._count <= 0:
                    self._owner = None
                    self._count = 0
                    stack = _held.get(tid)
                    if stack and self in stack:
                        stack.remove(self)
            else:
                # released by a non-owner thread (Lock-as-semaphore):
                # clear bookkeeping wherever it lives
                self._count = 0
                owner = self._owner
                self._owner = None
                if owner is not None:
                    stack = _held.get(owner)
                    if stack and self in stack:
                        stack.remove(self)
        self._real.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._real.locked()

    # -- Condition protocol ---------------------------------------------

    def _is_owned(self) -> bool:
        if self.kind == "rlock":
            return self._real._is_owned()
        return self._owner == threading.get_ident()

    def _release_save(self):
        if self.kind != "rlock":
            self.release()
            return None
        tid = threading.get_ident()
        with _WITNESS_LOCK:
            count = self._count
            self._count = 0
            self._owner = None
            stack = _held.get(tid)
            if stack and self in stack:
                stack.remove(self)
        return (self._real._release_save(), count)

    def _acquire_restore(self, state) -> None:
        if state is None:
            self.acquire()
            return
        real_state, count = state
        self._real._acquire_restore(real_state)
        tid = threading.get_ident()
        with _WITNESS_LOCK:
            self._owner = tid
            self._count = count
            _held.setdefault(tid, []).append(self)

    def _at_fork_reinit(self) -> None:  # pragma: no cover - fork safety
        self._real._at_fork_reinit()
        self._owner = None
        self._count = 0


def _find_path(src: str, dst: str) -> list[str] | None:
    """DFS in the order graph; caller holds ``_WITNESS_LOCK``. Returns
    the node path src..dst when dst is reachable."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in sorted(_graph.get(node, ())):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


# -- factories + the import hook ---------------------------------------------


def _from_package() -> bool:
    f = _caller_frame_outside_witness()
    threading_file = getattr(threading, "__file__", "")
    while f is not None and f.f_code.co_filename == threading_file:
        f = f.f_back
    return f is not None and os.path.abspath(
        f.f_code.co_filename
    ).startswith(_PKG_ROOT)


def new_lock():
    """``threading.Lock`` replacement: one flag check when the witness
    is disabled (micro-benched <1.5 µs); a :class:`WitnessLock` for
    package-code creators when enabled."""
    if not _enabled:
        return _REAL_LOCK()
    if not _from_package():
        return _REAL_LOCK()
    global _locks_created
    _locks_created += 1
    return WitnessLock("lock", _caller_site())


def new_rlock():
    if not _enabled:
        return _REAL_RLOCK()
    if not _from_package():
        return _REAL_RLOCK()
    global _locks_created
    _locks_created += 1
    return WitnessLock("rlock", _caller_site())


def installed() -> bool:
    return _installed


def install() -> None:
    """Patch ``threading.Lock``/``RLock`` with the witness factories and
    enable recording — the ``TFOS_TFSAN=1`` entry point. Only locks
    created by package code after this call are instrumented; stdlib
    internals (queue, multiprocessing) keep real locks."""
    global _installed
    if _installed:
        enable()
        return
    threading.Lock = new_lock
    threading.RLock = new_rlock
    _installed = True
    enable()
    logger.warning(
        "tfsan lock witness installed (TFOS_TFSAN); package locks are "
        "instrumented — scheduled-tier cost, not for production serving"
    )


def uninstall() -> None:
    global _installed
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _installed = False
    disable()


# -- guarded-by dynamic validation -------------------------------------------


_guard_cache: dict[type, tuple[dict, set] | None] = {}
_watched_cache: dict[type, type] = {}


def guarded_attrs(cls: type) -> dict[str, str]:
    """``{attr: lock_attr}`` parsed from the ``# guarded-by:``
    annotations in ``cls``'s source module (the PR-3 static
    convention), restricted to ``self.<lock>`` guards resolvable on an
    instance."""
    info = _guard_info(cls)
    return dict(info[0]) if info else {}


def _guard_info(cls: type) -> tuple[dict, set] | None:
    """(attr→lock map, exempt (file,line) set) or None when the class's
    module carries no usable annotations."""
    if cls in _guard_cache:
        return _guard_cache[cls]
    result = None
    try:
        import inspect

        from tensorflowonspark_tpu.analysis.core import Module, _comment_map
        from tensorflowonspark_tpu.analysis.locks import (
            LOCKFREE_RE,
            _GuardCollector,
        )

        path = os.path.abspath(inspect.getsourcefile(cls))
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        tree = ast.parse(src, filename=path)
        comments = _comment_map(src)
        mod = Module(path, path, cls.__module__, tree, src, comments)
        collector = _GuardCollector(mod)
        collector.visit(tree)
        guards = {
            attr: lock.split(".", 1)[1]
            for attr, (lock, _fn) in collector.attr_guards.items()
            if lock.startswith("self.")
        }
        exempt = {
            (path, line)
            for line, c in comments.items()
            if LOCKFREE_RE.search(c) and LOCKFREE_RE.search(c).group(1).strip()
        }
        if guards:
            result = (guards, exempt)
    except Exception:  # pragma: no cover - source unavailable (REPL)
        result = None
    _guard_cache[cls] = result
    return result


def _holds(lock: Any) -> bool:
    """Does the current thread hold ``lock``? Exact for WitnessLock and
    RLock/Condition; a plain raw Lock degrades to ``locked()`` (no
    owner concept — a held-by-someone-else false negative is accepted
    over a false report)."""
    if isinstance(lock, WitnessLock):
        return lock._owner == threading.get_ident()
    if isinstance(lock, threading.Condition):
        return _holds(lock._lock)
    is_owned = getattr(lock, "_is_owned", None)
    if is_owned is not None:
        try:
            return bool(is_owned())
        except Exception:  # pragma: no cover
            pass
    locked = getattr(lock, "locked", None)
    return bool(locked()) if locked is not None else True


def _check_guard(obj: Any, attr: str, guards: dict, exempt: set) -> None:
    lock_attr = guards[attr]
    try:
        lock = object.__getattribute__(obj, lock_attr)
    except AttributeError:
        return  # mid-construction: the lock does not exist yet
    if _holds(lock):
        return
    frame = _caller_frame_outside_witness()
    site_file = os.path.abspath(frame.f_code.co_filename) if frame else "?"
    site_line = frame.f_lineno if frame else 0
    if (site_file, site_line) in exempt:
        return  # justified '# lint: lockfree-read:' site
    cls_name = type(obj).__name__.replace("TFSanWatched_", "", 1)
    rel = site_file
    if rel.startswith(_PKG_ROOT):
        rel = os.path.relpath(rel, os.path.dirname(_PKG_ROOT))
    _report(
        "TFSAN-GUARD",
        f"guarded attribute {cls_name}.{attr} touched without its "
        f"declared lock self.{lock_attr} at {rel}:{site_line}",
        ("guard", cls_name, attr, rel, site_line),
        path=rel,
        line=site_line,
        thread=threading.current_thread().name,
    )


def watch(obj: Any) -> Any:
    """Swap ``obj``'s class for a witness subclass that validates every
    guarded-attribute access against its declared lock at runtime.
    Returns ``obj`` (unchanged when its module has no annotations).
    Apply AFTER construction — ``__init__`` is exempt by convention
    (the object is not yet published)."""
    cls = type(obj)
    if cls.__name__.startswith("TFSanWatched_"):
        return obj
    info = _guard_info(cls)
    if not info:
        return obj
    guards, exempt = info
    watched = _watched_cache.get(cls)
    if watched is None:
        names = frozenset(guards)

        def __getattribute__(self, name):  # noqa: N807
            if name in names and _enabled:
                _check_guard(self, name, guards, exempt)
            return object.__getattribute__(self, name)

        def __setattr__(self, name, value):  # noqa: N807
            if name in names and _enabled:
                # writes get NO lockfree-read exemption: the escape
                # only argues a stale READ is benign (static rule's
                # asymmetry, mirrored here)
                _check_guard(self, name, guards, frozenset())
            object.__setattr__(self, name, value)

        watched = type(
            f"TFSanWatched_{cls.__name__}",
            (cls,),
            {
                # empty __slots__ keeps the instance layout identical to
                # the base (slotted or not), so __class__ assignment is
                # legal either way
                "__slots__": (),
                "__getattribute__": __getattribute__,
                "__setattr__": __setattr__,
                "__tfsan_guards__": dict(guards),
            },
        )
        _watched_cache[cls] = watched
    obj.__class__ = watched
    return obj


def unwatch(obj: Any) -> Any:
    cls = type(obj)
    if cls.__name__.startswith("TFSanWatched_"):
        obj.__class__ = cls.__mro__[1]
    return obj


# -- report dump --------------------------------------------------------------


def dump_json(path: str) -> str:
    """Write the witness findings as the tfsan report format
    ``tools/tfsan.py --gate`` consumes; returns the path."""
    data = {
        "version": 1,
        "kind": "tfsan-witness",
        "pid": os.getpid(),
        "time": time.time(),
        "locks_created": _locks_created,
        "findings": findings(),
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    return path
