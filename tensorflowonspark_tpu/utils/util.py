"""Small host-side utilities.

Reference parity: ``tensorflowonspark/util.py`` (get_ip_address,
find_in_path, write_executor_id/read_executor_id, single_node_env).
"""

from __future__ import annotations

import errno
import os
import socket


EXECUTOR_ID_FILE = "executor_id"


def resolve_path(path: str, default_fs: str = "", working_dir: str = "") -> str:
    """Resolve a user path against a default FS / working dir.

    Reference: ``TFNode.py:hdfs_path`` resolution matrix — scheme-qualified
    paths pass through; absolute paths go under default_fs (when it is a
    scheme URI); relative paths resolve against the working dir (cwd when
    unset). Shared by ``TFNodeContext.absolute_path`` and the node
    runtime's tensorboard/log-dir handling so they always agree.
    """
    if "://" in path:  # fully qualified (hdfs://, gs://, file://, ...)
        return path
    if path.startswith("/"):
        fs = default_fs.rstrip("/")
        return f"{fs}{path}" if fs and "://" in default_fs else path
    base = (working_dir or os.getcwd()).rstrip("/")
    return f"{base}/{path}"


def get_ip_address() -> str:
    """Best-effort externally-routable IP of this host.

    Uses the UDP-connect trick (no packets are actually sent): connect a
    datagram socket to a public address and read the local endpoint the
    kernel chose. Falls back to loopback in fully isolated environments.
    Reference: ``util.py:get_ip_address``.
    """
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 53))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def find_in_path(path: str, file_name: str) -> str | None:
    """Find ``file_name`` in the ``os.pathsep``-separated ``path`` string.

    Reference: ``util.py:find_in_path`` (used to locate the tensorboard
    binary on executors).
    """
    for p in path.split(os.pathsep):
        candidate = os.path.join(p, file_name)
        if os.path.exists(candidate) and os.path.isfile(candidate):
            return candidate
    return None


def write_executor_id(num: int, cwd: str | None = None) -> None:
    """Pin this executor's logical id to a file in its working dir.

    Task retries land in the same working directory, so a retried feed task
    rediscovers which logical node it belongs to instead of grabbing a fresh
    partition id. Reference: ``util.py:write_executor_id``.
    """
    path = os.path.join(cwd or os.getcwd(), EXECUTOR_ID_FILE)
    with open(path, "w") as f:
        f.write(str(num))


def read_executor_id(cwd: str | None = None) -> int | None:
    """Read the pinned executor id, or None if this is the first task here.

    Reference: ``util.py:read_executor_id``.
    """
    path = os.path.join(cwd or os.getcwd(), EXECUTOR_ID_FILE)
    try:
        with open(path) as f:
            return int(f.read())
    except (OSError, ValueError):
        return None


def cpu_only_env(num_cpu_devices: int | None = None) -> dict[str, str]:
    """Env vars that force a subprocess to boot pure-CPU JAX.

    Besides ``JAX_PLATFORMS=cpu``, TPU-plugin autoload hooks (sitecustomize
    entries keyed on ``PALLAS_AXON_POOL_IPS``-style vars) must be disabled —
    they dial the accelerator at *interpreter start*, before any user code,
    and concurrent subprocess dials can wedge a single-chip runtime. Empty
    string disables them (falsy to the hook) while remaining inheritable.
    """
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "PALLAS_AXON_REMOTE_COMPILE": "",
    }
    if num_cpu_devices is not None:
        env["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={num_cpu_devices}"
        ).strip()
    return env


def single_node_env(num_cpu_devices: int | None = None) -> None:
    """Configure env vars for a single-process, host-only JAX run.

    Used by inference/transform workers and tests that must not grab the TPU.
    Reference: ``util.py:single_node_env`` (which hid GPUs and capped
    threads for single-node TF).
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if num_cpu_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        opt = f"--xla_force_host_platform_device_count={num_cpu_devices}"
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (flags + " " + opt).strip()


def find_free_port(host: str = "") -> int:
    """Reserve an OS-assigned free TCP port and release it immediately.

    Mirrors the reference's reserve-then-release port dance
    (``TFSparkNode.py:_mapfn``: bind on port 0, hand the port to the
    reservation, close the socket just before the engine binds it). There is
    an inherent race window; callers must tolerate rebinding.
    """
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


def ensure_dir(path: str) -> str:
    """mkdir -p that tolerates concurrent creation across hosts."""
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:  # pragma: no cover - exotic FS races
        if e.errno != errno.EEXIST:
            raise
    return path
