"""Accelerator discovery/allocation helpers.

Reference parity: ``tensorflowonspark/gpu_info.py`` (``get_gpus`` parsed
nvidia-smi, randomly picked free GPUs with retries, and emitted
``CUDA_VISIBLE_DEVICES``). On TPU there is no multi-tenant allocation race
to dodge: libtpu owns the host's chips and hands each process its local
set. What remains useful is discovery, visibility control for
tests/colocated processes, and a capability probe.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

MAX_RETRIES = 3  # kept for API parity; TPU allocation does not race


def get_gpus(num_gpu: int = 1, worker_index: int = -1) -> str:
    """Compatibility shim for reference callers: returns a CSV of local
    device ordinals (the string the reference put in CUDA_VISIBLE_DEVICES).

    On TPU hosts this is ``TPU_VISIBLE_CHIPS`` material; on CPU it is
    informational only.
    """
    devices = get_local_devices()
    n = min(num_gpu, len(devices))
    return ",".join(str(i) for i in range(n))


def get_local_devices() -> list:
    import jax

    return jax.local_devices()


def is_gpu_available() -> bool:
    """Reference name; answers 'is an accelerator available'."""
    return is_tpu_available()


def is_tpu_available() -> bool:
    import jax

    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except RuntimeError:
        return False


_MULTIPROCESS_PROBE = """
import sys
import jax

jax.distributed.initialize(
    coordinator_address="127.0.0.1:%d", num_processes=2, process_id=%d
)
import numpy as np
from jax.experimental import multihost_utils

out = multihost_utils.process_allgather(np.ones((1,), np.int32))
assert int(np.asarray(out).sum()) == 2
"""

_multiprocess_supported: bool | None = None


def multiprocess_collectives_supported(timeout: float = 120.0) -> bool:
    """Can THIS host's backend actually run cross-process collectives?

    Some jaxlib builds reject multiprocess computations on the CPU
    backend ("Multiprocess computations aren't implemented on the CPU
    backend"), which makes every multi-controller e2e test fail for an
    environmental reason that is not a bug in this repo. This probe
    answers the question empirically — two short-lived CPU-only
    subprocesses join one ``jax.distributed`` coordinator and run a
    real allgather — and caches the verdict for the process lifetime.
    ``tests/test_distributed.py`` gates itself on it (``pytest.skip``
    instead of 7 pre-baselined failures). ``TFOS_MULTIPROCESS_OK=0/1``
    overrides the probe (CI images that already know their backend).
    """
    global _multiprocess_supported
    if _multiprocess_supported is not None:
        return _multiprocess_supported
    forced = os.environ.get("TFOS_MULTIPROCESS_OK")
    if forced is not None:
        _multiprocess_supported = forced not in ("0", "false", "")
        return _multiprocess_supported
    import subprocess
    import sys

    from tensorflowonspark_tpu.utils.util import cpu_only_env, find_free_port

    port = find_free_port()
    env = dict(os.environ, **cpu_only_env(num_cpu_devices=1))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _MULTIPROCESS_PROBE % (port, pid)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        for pid in (0, 1)
    ]
    ok = True
    deadline = None
    try:
        import time as _time

        deadline = _time.monotonic() + timeout
        for p in procs:
            remaining = max(0.1, deadline - _time.monotonic())
            try:
                ok = p.wait(timeout=remaining) == 0 and ok
            except subprocess.TimeoutExpired:
                ok = False
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    _multiprocess_supported = ok
    logger.info(
        "multiprocess collectives %s on this backend",
        "supported" if ok else "NOT supported",
    )
    return ok


def set_visible_chips(chips: str | None) -> None:
    """Restrict which TPU chips this process binds (set BEFORE jax init).

    The moral replacement for the reference writing CUDA_VISIBLE_DEVICES in
    ``TFSparkNode._mapfn``: on multi-process-per-host TPU setups each
    process pins its chip subset.
    """
    if chips is None:
        os.environ.pop("TPU_VISIBLE_CHIPS", None)
    else:
        os.environ["TPU_VISIBLE_CHIPS"] = chips
        os.environ.setdefault("TPU_CHIPS_PER_PROCESS_BOUNDS", "1,1,1")
