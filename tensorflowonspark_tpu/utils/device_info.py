"""Accelerator discovery/allocation helpers.

Reference parity: ``tensorflowonspark/gpu_info.py`` (``get_gpus`` parsed
nvidia-smi, randomly picked free GPUs with retries, and emitted
``CUDA_VISIBLE_DEVICES``). On TPU there is no multi-tenant allocation race
to dodge: libtpu owns the host's chips and hands each process its local
set. What remains useful is discovery, visibility control for
tests/colocated processes, and a capability probe.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

MAX_RETRIES = 3  # kept for API parity; TPU allocation does not race


def get_gpus(num_gpu: int = 1, worker_index: int = -1) -> str:
    """Compatibility shim for reference callers: returns a CSV of local
    device ordinals (the string the reference put in CUDA_VISIBLE_DEVICES).

    On TPU hosts this is ``TPU_VISIBLE_CHIPS`` material; on CPU it is
    informational only.
    """
    devices = get_local_devices()
    n = min(num_gpu, len(devices))
    return ",".join(str(i) for i in range(n))


def get_local_devices() -> list:
    import jax

    return jax.local_devices()


def is_gpu_available() -> bool:
    """Reference name; answers 'is an accelerator available'."""
    return is_tpu_available()


def is_tpu_available() -> bool:
    import jax

    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except RuntimeError:
        return False


def set_visible_chips(chips: str | None) -> None:
    """Restrict which TPU chips this process binds (set BEFORE jax init).

    The moral replacement for the reference writing CUDA_VISIBLE_DEVICES in
    ``TFSparkNode._mapfn``: on multi-process-per-host TPU setups each
    process pins its chip subset.
    """
    if chips is None:
        os.environ.pop("TPU_VISIBLE_CHIPS", None)
    else:
        os.environ["TPU_VISIBLE_CHIPS"] = chips
        os.environ.setdefault("TPU_CHIPS_PER_PROCESS_BOUNDS", "1,1,1")
