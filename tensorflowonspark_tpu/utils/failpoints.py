"""Failpoint registry: deterministic fault injection at named sites.

The recovery story (liveness plane, retry discipline, engine watchdog —
see ``docs/ROBUSTNESS.md``) is only trustworthy if it can be *exercised*:
a fault path that has never fired is a fault path that does not work.
This module gives every load-bearing failure site a NAME, and lets tests
(or an operator reproducing an incident) arm that site to raise, delay,
or drop — count- or probability-gated, with a seeded RNG so chaos runs
are reproducible.

Design constraints, in order:

1. **Zero cost disarmed.** ``failpoint("x")`` with nothing armed is one
   global truthiness check (sub-µs; asserted by a tier-1 micro-bench in
   ``tests/test_chaos.py``) — it is threaded through hot paths
   (engine dispatch/fetch, feed pulls) and must stay invisible there.
2. **Registered literal names only.** Sites are declared in :data:`SITES`
   and call sites must pass a literal from it (``tools/tfoslint.py``
   rule FP001 enforces this), so ``TFOS_FAILPOINTS=resrvation.register=…``
   cannot silently no-op on a typo: :func:`arm` rejects unknown names.
3. **Deterministic.** ``count`` gates trip exactly-N-times semantics;
   ``probability`` draws from a per-arm ``random.Random(seed)``.

Arming::

    failpoints.arm("reservation.call", "raise", exc=ConnectionError,
                   count=2)                      # first 2 hits raise
    failpoints.arm("engine.fetch", "delay", delay_s=1.5, count=1)
    failpoints.arm("node.close_feed", "drop")    # site-defined skip

or from the environment (parsed once at import, same grammar per spec,
``;``-separated)::

    TFOS_FAILPOINTS="reservation.call=raise:ConnectionError*2;engine.fetch=delay:1.5*1"

Spec grammar: ``site=kind[:param][*count][~probability][@seed]`` where
``param`` is the exception class name for ``raise`` (default
:class:`FailpointError`) or the sleep seconds for ``delay``.

Call sites::

    failpoints.failpoint("reservation.register")        # raise/delay
    if failpoints.failpoint("node.close_feed") == "drop":
        return                                          # drop-aware site
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any

logger = logging.getLogger(__name__)

__all__ = [
    "SITES",
    "FailpointError",
    "arm",
    "arm_from_spec",
    "armed",
    "disarm",
    "disarm_all",
    "failpoint",
]

# The registered failure sites. Adding a site means: add the literal
# here, thread ``failpoint("<name>")`` through the code path, and
# document it in docs/ROBUSTNESS.md. tfoslint rule FP001 fails the
# build on a call site whose name is not in this set.
SITES = frozenset(
    {
        # control plane
        "reservation.register",  # Client.register, before the RPC
        "reservation.call",  # every Client._call connect+roundtrip
        "reservation.heartbeat",  # Client.heartbeat, before the RPC
        "node.startup",  # run_node, before manager/reservation
        "node.close_feed",  # _push_end_of_feed per queue ("drop" aware)
        # data plane
        "datafeed.get",  # DataFeed._next_raw queue pull
        "datafeed.put_results",  # DataFeed.batch_results push
        "columnar.frame",  # columnar frame decode points ("drop" aware:
        # a dropped frame is surfaced by the consumer's seq-gap check)
        "prefetch.producer",  # DevicePrefetcher producer thread
        # pull plane (feed/ingest.py executor-local sharded readers)
        "ingest.manifest_fetch",  # node, fetching the driver-published plan
        "ingest.open_shard",  # ShardReader, before opening one shard
        "ingest.read_block",  # ShardReader, per block read ("drop" aware:
        # a dropped block is surfaced by the replay cursor's gap check)
        # live shard redistribution (the handover protocol — see
        # docs/ROBUSTNESS.md "Live shard redistribution")
        "ingest.handover_drain",  # IngestFeed, draining to a block
        # boundary on the old plan ("drop" aware: a dropped drain skips
        # the cursor publication — the stale-cursor duplicate bound)
        "ingest.cursor_publish",  # node, publishing a replay cursor to
        # the driver KV ("drop" aware: a lost publication widens the
        # crash-handover duplicate window, never breaks zero-gap)
        "ingest.plan_adopt",  # IngestFeed, before adopting a re-split
        # serving plane
        "engine.submit",  # ContinuousBatcher enqueue (caller thread)
        "engine.dispatch",  # scheduler, before a decode-block dispatch
        "engine.fetch",  # scheduler, before a block fetch
        # serving fleet (serving/fleet.py + router.py — see
        # docs/ROBUSTNESS.md "Serving fleet")
        "fleet.dispatch",  # router, before handing a request to a
        # replica ("drop" aware: a lost dispatch surfaces as a LOUD
        # terminal/failover via ReplicaGone — never a hang)
        "fleet.replica_probe",  # fleet probe loop, per replica round
        # (a raised probe is a missed beat toward DRAINING)
        "fleet.replica_spawn",  # replica (re)spawn, before the engine/
        # process is built (a raise exercises respawn retry/DEAD)
        # zero-downtime weight rollout (serving/rollout.py — see
        # docs/ROBUSTNESS.md "Rolling weight updates")
        "rollout.publish",  # channel manifest write ("drop" aware: a
        # lost publication is bounded staleness — watchers keep serving
        # the prior version, never a torn pointer)
        "rollout.swap",  # controller, before swapping one seat (a
        # raise triggers automatic rollback of already-swapped seats)
        "rollout.verify",  # controller, post-swap verification of a
        # seat (a raise = failed warmup/health regression → rollback)
        # checkpoint plane
        "checkpoint.save",  # orbax save (inside the retry)
        "checkpoint.restore",  # orbax restore (inside the retry)
        # elastic plane (compute/elastic.py + TFCluster supervise)
        "elastic.epoch_bump",  # driver, before publishing a new epoch
        "elastic.reshard_gather",  # node, gathering state to host memory
        "elastic.rejoin_init",  # joining node, before peer/ckpt hydration
        # online knob tuning (autotune/registry.py — docs/AUTOTUNE.md)
        "autotune.apply",  # KnobRegistry.set, before the actuation
        # callback ("drop" aware: a lost apply leaves the knob at its
        # readback value — the controller observes no movement and
        # reverts cleanly; the registry never wedges)
        # online continual loop (feed/livelog.py + online.py — see
        # docs/ROBUSTNESS.md "Online continual loop")
        "online.log_append",  # TrafficLog.append, before buffering a
        # record ("drop" aware: a dropped record is LOST and counted in
        # online_records_dropped_total{reason=failpoint} — never lied
        # about, never blocks the serve path)
        "online.manifest_publish",  # TrafficLog seal, before writing
        # the frame manifest ("drop" aware: a lost publication leaves a
        # sealed segment undiscovered until recovery republishes it)
        "online.discover",  # driver loop, before scanning the manifest
        # directory (a raise = one missed discovery poll; the next
        # cycle covers it)
        "online.train_stall",  # driver loop, trainer-progress check
        # ("drop" aware: simulates a stalled trainer — the loop must
        # bound log growth and cut an online_stall flightrec event)
        # disaggregated cache tier (cachetier/ — docs/SERVING.md
        # "Cache tier"; the cache is an optimization, never a liveness
        # dependency, and every site here is shaped to prove it)
        "cachetier.lookup",  # CacheTier.lookup, before probing the
        # store ("drop" aware: a dropped lookup IS a miss — the caller
        # recomputes/refetches; never a hang)
        "cachetier.fill",  # CacheTier.fill, before storing an entry
        # ("drop" aware: a dropped fill is simply not cached — the next
        # lookup misses and the consumer read-throughs again)
        "cachetier.evict",  # CacheTier eviction loop, per evicted
        # entry ("drop" aware: a dropped eviction ends the round —
        # the store runs transiently over budget, never corrupts)
    }
)

# Exceptions an env spec may name (a curated map, not eval()).
_EXC_BY_NAME: dict[str, type[BaseException]] = {
    "ConnectionError": ConnectionError,
    "IOError": IOError,
    "OSError": OSError,
    "RuntimeError": RuntimeError,
    "TimeoutError": TimeoutError,
    "ValueError": ValueError,
}


class FailpointError(RuntimeError):
    """Default exception an armed ``raise`` site throws."""


class _Arm:
    __slots__ = ("site", "kind", "exc", "delay_s", "count", "probability", "rng")

    def __init__(
        self,
        site: str,
        kind: str,
        exc: type[BaseException] | BaseException | None,
        delay_s: float,
        count: int | None,
        probability: float | None,
        seed: int | None,
    ):
        self.site = site
        self.kind = kind
        self.exc = exc
        self.delay_s = delay_s
        self.count = count  # remaining trips; None = unlimited
        self.probability = probability
        self.rng = random.Random(seed if seed is not None else 0)


_armed: dict[str, _Arm] = {}  # guarded-by: _lock
_lock = threading.Lock()
# The disarmed fast path reads ONLY this flag — deliberately without
# the lock (a stale read is benign: at worst one hit right at arm time
# misses, and hits after the arm's memory settles always see it). Kept
# separate from _armed so the dict itself stays strictly lock-guarded.
_any_armed: bool = False


def failpoint(name: str) -> str | None:
    """Hit a failpoint site. Disarmed (the overwhelmingly common case):
    one global truthiness check, no locking, returns None. Armed: apply
    the site's action — raise its exception, sleep its delay, or return
    ``"drop"`` for the call site to interpret."""
    if not _any_armed:
        return None
    return _trip(name)


def _trip(name: str) -> str | None:
    global _any_armed
    with _lock:
        a = _armed.get(name)
        if a is None:
            return None
        if a.probability is not None and a.rng.random() >= a.probability:
            return None
        if a.count is not None:
            a.count -= 1
            if a.count <= 0:
                del _armed[name]
                _any_armed = bool(_armed)
        kind, exc, delay_s = a.kind, a.exc, a.delay_s
    _trips_counter().inc(site=name, action=kind)
    logger.warning("failpoint %r tripped (%s)", name, kind)
    if kind == "raise":
        if exc is None:
            raise FailpointError(f"failpoint {name!r} armed")
        if isinstance(exc, BaseException):
            raise exc
        raise exc(f"failpoint {name!r} armed")
    if kind == "delay":
        time.sleep(delay_s)
        return None
    return "drop"


def arm(
    name: str,
    action: str = "raise",
    *,
    exc: type[BaseException] | BaseException | None = None,
    delay_s: float = 0.0,
    count: int | None = None,
    probability: float | None = None,
    seed: int | None = None,
) -> None:
    """Arm a registered site. ``count``: trip at most N times then
    auto-disarm. ``probability``: trip each hit with this probability
    (seeded — pass ``seed`` for a reproducible sequence). Unknown site
    names are a loud error, never a silent no-op."""
    if name not in SITES:
        raise ValueError(
            f"unknown failpoint site {name!r}; registered sites: "
            f"{sorted(SITES)}"
        )
    if action not in ("raise", "delay", "drop"):
        raise ValueError(f"unknown failpoint action {action!r}")
    if count is not None and count < 1:
        raise ValueError(f"count must be >= 1, got {count}")
    if probability is not None and not 0.0 < probability <= 1.0:
        raise ValueError(f"probability must be in (0, 1], got {probability}")
    if action == "delay" and delay_s < 0:
        raise ValueError(f"delay_s must be >= 0, got {delay_s}")
    global _any_armed
    with _lock:
        _armed[name] = _Arm(name, action, exc, delay_s, count, probability, seed)
        _any_armed = True


def disarm(name: str) -> None:
    global _any_armed
    with _lock:
        _armed.pop(name, None)
        _any_armed = bool(_armed)


def disarm_all() -> None:
    global _any_armed
    with _lock:
        _armed.clear()
        _any_armed = False


def armed() -> list[str]:
    """Currently armed site names (for /stats-style surfaces and tests)."""
    with _lock:
        return sorted(_armed)


def arm_from_spec(spec: str) -> list[str]:
    """Arm sites from a ``TFOS_FAILPOINTS``-grammar string; returns the
    site names armed. Grammar per ``;``-separated entry:
    ``site=kind[:param][*count][~probability][@seed]``."""
    armed_now: list[str] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        site, _, rest = entry.partition("=")
        site = site.strip()
        if not rest:
            raise ValueError(f"failpoint spec {entry!r} missing '=action'")
        seed = None
        if "@" in rest:
            rest, _, s = rest.rpartition("@")
            seed = int(s)
        probability = None
        if "~" in rest:
            rest, _, p = rest.rpartition("~")
            probability = float(p)
        count = None
        if "*" in rest:
            rest, _, c = rest.rpartition("*")
            count = int(c)
        kind, _, param = rest.partition(":")
        kind = kind.strip()
        exc: type[BaseException] | None = None
        delay_s = 0.0
        if kind == "raise" and param:
            try:
                exc = _EXC_BY_NAME[param]
            except KeyError:
                raise ValueError(
                    f"failpoint spec {entry!r}: unknown exception "
                    f"{param!r} (one of {sorted(_EXC_BY_NAME)})"
                ) from None
        elif kind == "delay":
            delay_s = float(param) if param else 0.0
        arm(
            site,
            kind,
            exc=exc,
            delay_s=delay_s,
            count=count,
            probability=probability,
            seed=seed,
        )
        armed_now.append(site)
    return armed_now


def _trips_counter():
    """The obs-registry trip counter, resolved lazily so importing this
    module never drags in the obs package on the disarmed path."""
    from tensorflowonspark_tpu.obs.registry import default_registry

    return default_registry().counter(
        "failpoint_trips_total", "armed failpoint trips, by site and action"
    )


_env_spec = os.environ.get("TFOS_FAILPOINTS", "")
if _env_spec:
    try:
        logger.warning(
            "TFOS_FAILPOINTS armed: %s", arm_from_spec(_env_spec)
        )
    except ValueError:
        # A typo'd env spec must fail the process loudly, not no-op:
        # an operator who armed chaos wants chaos, not a healthy run.
        raise
