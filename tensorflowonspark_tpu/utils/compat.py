"""Version shims (reference parity: ``tensorflowonspark/compat.py``).

The reference papered over TF 2.0/2.1 API drift (``export_saved_model``,
``disable_auto_shard``, ``is_gpu_available``). The rebuild's equivalents:

- ``export_saved_model`` → orbax checkpoint export (the SavedModel analog)
- ``disable_auto_shard`` → a no-op by construction: the queue feed already
  delivers distinct per-host data, and jit+NamedSharding splits the global
  batch by sharding, so there is no competing auto-shard machinery to turn
  off. Kept callable so reference-shaped user code ports unchanged.
- ``is_gpu_available`` → accelerator probe.
"""

from __future__ import annotations

from tensorflowonspark_tpu.utils.device_info import (  # noqa: F401
    is_gpu_available,
    is_tpu_available,
)


def export_saved_model(state, export_dir: str, **kwargs) -> str:
    from tensorflowonspark_tpu.compute.checkpoint import save_checkpoint

    return save_checkpoint(export_dir, state, **kwargs)


def disable_auto_shard(options=None) -> None:
    """No-op (see module docstring); accepts and ignores tf.data options."""
    return None
