"""Version shims (reference parity: ``tensorflowonspark/compat.py``).

The reference papered over TF 2.0/2.1 API drift (``export_saved_model``,
``disable_auto_shard``, ``is_gpu_available``). The rebuild's equivalents:

- ``export_saved_model`` → orbax checkpoint export (the SavedModel analog)
- ``disable_auto_shard`` → a no-op by construction: the queue feed already
  delivers distinct per-host data, and jit+NamedSharding splits the global
  batch by sharding, so there is no competing auto-shard machinery to turn
  off. Kept callable so reference-shaped user code ports unchanged.
- ``is_gpu_available`` → accelerator probe.

This module is also the ONE sanctioned home for jax private/moved-API
access (``tools/tfoslint.py`` rule JX002 enforces it): symbols that have
moved between jax releases — ``shard_map`` graduated from
``jax.experimental.shard_map`` to top-level ``jax.shard_map`` with its
``check_rep`` kwarg renamed to ``check_vma`` — are imported from here,
never spelled directly at call sites. A jax too old for either location
raises at CALL time with an actionable message instead of an
``AttributeError`` at import/trace time.
"""

from __future__ import annotations

from tensorflowonspark_tpu.utils.device_info import (  # noqa: F401
    is_gpu_available,
    is_tpu_available,
)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map`` (new-style keyword signature).

    jax >= 0.5 exposes ``jax.shard_map`` (replication checking under
    ``check_vma``); 0.4.x only has ``jax.experimental.shard_map`` whose
    equivalent kwarg is ``check_rep``. Callers use the new spelling and
    this shim maps it back for old jax.
    """
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        # The top-level promotion and the check_rep→check_vma rename
        # landed in DIFFERENT jax releases: probe the accepted kwarg,
        # don't infer it from the symbol's location.
        try:
            return fn(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma,
            )
        except TypeError as e:
            if "check_vma" not in str(e):
                raise
            return fn(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=check_vma,
            )
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError as e:  # pragma: no cover - ancient jax
        raise RuntimeError(
            "this jax has neither jax.shard_map nor "
            "jax.experimental.shard_map; install jax >= 0.4.30"
        ) from e
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def axis_size(axis_name: str) -> int:
    """Static size of a named mesh axis, inside a ``shard_map``/vmapped
    body. ``jax.lax.axis_size`` only exists on newer jax; on 0.4.x the
    long-standing idiom ``lax.psum(1, axis)`` constant-folds to the same
    static int (the input is a Python scalar, so no collective runs).
    """
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def export_saved_model(state, export_dir: str, **kwargs) -> str:
    from tensorflowonspark_tpu.compute.checkpoint import save_checkpoint

    return save_checkpoint(export_dir, state, **kwargs)


def disable_auto_shard(options=None) -> None:
    """No-op (see module docstring); accepts and ignores tf.data options."""
    return None
