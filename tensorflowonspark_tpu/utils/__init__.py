"""Utility helpers (reference: ``tensorflowonspark/util.py``, ``compat.py``)."""

from tensorflowonspark_tpu.utils.util import (
    get_ip_address,
    find_in_path,
    read_executor_id,
    write_executor_id,
    single_node_env,
)

__all__ = [
    "get_ip_address",
    "find_in_path",
    "read_executor_id",
    "write_executor_id",
    "single_node_env",
]
