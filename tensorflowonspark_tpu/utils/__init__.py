"""Utility helpers (reference: ``tensorflowonspark/util.py``, ``compat.py``)."""

import os as _os

# tfsan import hook: with TFOS_TFSAN=1 in the environment, the lock
# witness patches threading.Lock/RLock BEFORE any package module
# constructs its locks (every package module imports utils early —
# failpoints, retry, metrics all live here). Opt-in only; the disabled
# path never patches anything. See utils/lockwitness.py and
# docs/STATIC_ANALYSIS.md "Concurrency sanitizer".
if _os.environ.get("TFOS_TFSAN") == "1":
    from tensorflowonspark_tpu.utils import lockwitness as _lockwitness

    _lockwitness.install()

from tensorflowonspark_tpu.utils.util import (
    get_ip_address,
    find_in_path,
    read_executor_id,
    write_executor_id,
    single_node_env,
)

__all__ = [
    "get_ip_address",
    "find_in_path",
    "read_executor_id",
    "write_executor_id",
    "single_node_env",
]
