"""Step-metric writing: TensorBoard scalars with per-node aggregation.

Reference parity: the reference had no metrics pipeline of its own
(SURVEY.md §5.5) — per-process ``logging`` plus whatever the user's TF code
wrote to TensorBoard. The rebuild makes the common case first-class: every
node gets a :class:`MetricsWriter` under ``log_dir/node{N}/``, and the
chief's tensorboard (``TFCluster.run(tensorboard=True, log_dir=...)``)
aggregates all nodes' runs — the "host-0 aggregator" pattern with zero
extra plumbing.

Backend: ``tf.summary`` event files when TensorFlow is importable (so plain
TensorBoard reads them), else a JSONL fallback with the same API.

One metrics system, not two (``obs/``): the writer is a *sink* of the
:mod:`tensorflowonspark_tpu.obs.registry` —
``registry.publish(writer, step)`` snapshots every counter/gauge/
histogram series into scalar writes — and every direct ``scalar()``
call mirrors its value into the registry as a gauge (name sanitized to
Prometheus rules), so the node runtime's ``/metrics`` endpoint and the
chief's TensorBoard can never tell different stories.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any

logger = logging.getLogger(__name__)

__all__ = ["MetricsWriter"]


class MetricsWriter:
    """Write scalar step metrics; TB event files or JSONL fallback."""

    def __init__(
        self,
        log_dir: str,
        use_tensorboard: bool | None = None,
        registry=None,
    ):
        """``registry``: the obs registry scalars mirror into (default:
        the process-global one; pass ``False`` to disable mirroring)."""
        if registry is None:
            from tensorflowonspark_tpu.obs.registry import default_registry

            registry = default_registry()
        self._registry = registry or None
        self.log_dir = log_dir
        remote = "://" in log_dir  # gs://, hdfs://, ... — TF filesystems
        if not remote:
            os.makedirs(log_dir, exist_ok=True)
        self._tb = None
        self._jsonl = None
        if use_tensorboard is None or use_tensorboard:
            try:
                import tensorflow as tf
            except Exception as e:  # broken installs raise non-ImportErrors
                if use_tensorboard:
                    raise
                logger.warning(
                    "tensorflow unavailable (%s); metrics fall back to JSONL",
                    e,
                )
                tf = None
            if tf is not None:
                # Writer-creation failures (bad URI, missing filesystem
                # plugin, permissions) must propagate — silently degrading
                # to JSONL would hide scalars from the chief's TB.
                self._tb = tf.summary.create_file_writer(log_dir)
        if self._tb is None:
            if remote:
                raise ValueError(
                    f"metrics log_dir {log_dir!r} is a filesystem URI; the "
                    "JSONL fallback only writes local paths (install/enable "
                    "TensorFlow for remote filesystems)"
                )
            self._jsonl = open(
                os.path.join(log_dir, "metrics.jsonl"), "a", buffering=1
            )

    def scalar(
        self, name: str, value: Any, step: int, mirror: bool = True
    ) -> None:
        if mirror and self._registry is not None:
            # keep the pull side (Prometheus /metrics) in sync with the
            # push side; Registry.publish passes mirror=False so the
            # bridge cannot echo registry-born series back as gauges
            from tensorflowonspark_tpu.obs.registry import sanitize_name

            try:
                self._registry.gauge(  # lint: metric-name-ok (mirror of arbitrary scalar names)
                    sanitize_name(name), "mirrored from MetricsWriter"
                ).set(float(value))
            except ValueError:
                # a non-gauge metric already owns the sanitized name;
                # the mirror is best-effort observability, the write
                # itself must proceed
                pass
        if self._tb is not None:
            import tensorflow as tf

            with self._tb.as_default():
                tf.summary.scalar(name, float(value), step=step)
        else:
            self._jsonl.write(
                json.dumps(
                    {
                        "name": name,
                        "value": float(value),
                        "step": int(step),
                        "ts": time.time(),
                    }
                )
                + "\n"
            )

    def scalars(self, values: dict[str, Any], step: int) -> None:
        for name, value in values.items():
            self.scalar(name, value, step)

    def flush(self) -> None:
        if self._tb is not None:
            self._tb.flush()
        else:
            self._jsonl.flush()

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
        else:
            self._jsonl.close()

    def __enter__(self) -> "MetricsWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
