"""Retry discipline: exponential backoff with full jitter, deadline-aware.

Before this module every transient-failure site in the repo either died
on the first error (reservation connects during a coordinator restart,
orbax IO against a flaky shared filesystem) or hand-rolled its own
``while``/``sleep`` loop. :class:`RetryPolicy` centralizes the policy —
the AWS-style *full jitter* schedule (``uniform(0, min(cap, base·mult^i))``,
which de-synchronizes retry herds better than equal or decorrelated
jitter for the same worst-case delay) plus an overall deadline so a
retry loop can never outlive the budget its caller is accountable to.

Retries are observable: every sleep increments
``retry_attempts_total{site=...}`` in the process-global obs registry,
so a cluster quietly riding through connect flaps shows up on the node
``/metrics`` endpoints instead of only in debug logs.

Seeded (``seed=``) the jitter sequence is deterministic — chaos tests
assert exact schedules instead of sleeping through real backoff.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Any, Callable, Iterator

logger = logging.getLogger(__name__)

__all__ = ["RetryPolicy", "DEFAULT_RETRYABLE"]

# The transient-failure classes network/IO sites retry by default.
# FailpointError is deliberately NOT here: a site opts into retrying
# injected faults by naming it in retry_on (chaos tests rely on that).
DEFAULT_RETRYABLE: tuple[type[BaseException], ...] = (
    ConnectionError,
    TimeoutError,
    OSError,
)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry schedule; share one instance across call sites.

    ``max_attempts`` counts *calls* (1 = no retries). ``deadline_s``
    bounds the whole :meth:`call` — elapsed time plus the next planned
    sleep must fit inside it, so a policy can never sleep through its
    budget and then fail anyway. ``seed`` pins the jitter RNG (tests);
    None draws system entropy per :meth:`call`.
    """

    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    deadline_s: float | None = None
    seed: int | None = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay < 0 or self.max_delay < 0 or self.multiplier < 1:
            raise ValueError(
                f"invalid backoff shape (base={self.base_delay}, "
                f"max={self.max_delay}, multiplier={self.multiplier})"
            )
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {self.deadline_s}")

    def delays(self, rng: random.Random | None = None) -> Iterator[float]:
        """The jittered backoff schedule: one delay per retry (so
        ``max_attempts - 1`` values). Full jitter — each delay is
        uniform over ``[0, min(max_delay, base·multiplier^i)]``."""
        rng = rng if rng is not None else random.Random(self.seed)
        for i in range(self.max_attempts - 1):
            cap = min(self.max_delay, self.base_delay * self.multiplier**i)
            yield rng.uniform(0.0, cap)

    def call(
        self,
        fn: Callable[[], Any],
        *,
        retry_on: tuple[type[BaseException], ...] = DEFAULT_RETRYABLE,
        site: str = "",
        sleep: Callable[[float], None] = time.sleep,
        on_retry: Callable[[int, BaseException, float], None] | None = None,
    ) -> Any:
        """Run ``fn()`` under this policy.

        Retries only on ``retry_on`` exceptions; anything else (and the
        last retryable failure once attempts or deadline are exhausted)
        propagates unchanged so callers keep their original error
        classes. ``site`` labels the ``retry_attempts_total`` series and
        the warning log; ``on_retry(attempt, exc, delay)`` is a test
        hook. Deadline clipping: a sleep is trimmed to the remaining
        budget, and once the budget is spent the failure propagates
        immediately — no retry fires at or past the deadline.
        """
        rng = random.Random(self.seed)
        deadline = (
            None if self.deadline_s is None else time.monotonic() + self.deadline_s
        )
        schedule = self.delays(rng)
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except retry_on as e:
                delay = next(schedule, None)
                if delay is None:  # attempts exhausted
                    raise
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise
                    delay = min(delay, remaining)
                _retry_counter().inc(site=site or "unlabeled")
                logger.warning(
                    "retry %d/%d%s after %s: %s (backoff %.3fs)",
                    attempt,
                    self.max_attempts,
                    f" [{site}]" if site else "",
                    type(e).__name__,
                    e,
                    delay,
                )
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                sleep(delay)


def _retry_counter():
    from tensorflowonspark_tpu.obs.registry import default_registry

    return default_registry().counter(
        "retry_attempts_total",
        "transient-failure retries taken, by call site",
    )
