"""Shared fixed-size batching core.

Both batch producers — the push plane's ``DataFeed.batch_stream`` and the
pull plane's ``readers.column_batches`` — need the same contract: every
batch exactly ``batch_size`` records (rounded down to ``multiple_of`` so
batches shard over the mesh), tail trimmed to the largest multiple, the
sub-multiple remainder dropped loudly. One implementation, two callers.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterable, Iterator

logger = logging.getLogger(__name__)


def fixed_size_batches(
    records: Iterable[Any],
    batch_size: int,
    multiple_of: int,
    assemble: Callable[[list[Any]], Any],
) -> Iterator[Any]:
    batch_size -= batch_size % multiple_of
    if batch_size == 0:
        raise ValueError(
            f"batch_size < multiple_of ({multiple_of}); nothing to yield"
        )
    pending: list[Any] = []
    for record in records:
        pending.append(record)
        if len(pending) == batch_size:
            yield assemble(pending)
            pending = []
    tail = len(pending) - len(pending) % multiple_of
    if len(pending) % multiple_of:
        logger.warning(
            "dropping %d tail records (not a multiple of %d)",
            len(pending) % multiple_of,
            multiple_of,
        )
    if tail:
        yield assemble(pending[:tail])
