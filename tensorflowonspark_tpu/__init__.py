"""tensorflowonspark_tpu — a TPU-native cluster ML framework.

A ground-up rebuild of the capabilities of TensorFlowOnSpark
(reference: ``tensorflowonspark/`` in yahoo/TensorFlowOnSpark; see SURVEY.md)
designed for TPU hardware: rendezvous hands out a ``jax.distributed``
coordinator instead of TF_CONFIG roles, the push-based data plane feeds
host-local queues into TPU infeed, and data-parallel / FSDP training is
expressed as ``jit`` + ``NamedSharding`` over an ICI device mesh instead of
parameter servers or MultiWorkerMirroredStrategy.

Public surface mirrors the reference so users can switch:

- :class:`TFCluster` / :func:`TFCluster.run` — cluster orchestration
  (reference: ``tensorflowonspark/TFCluster.py``)
- :class:`InputMode` — SPARK (push feed) vs TENSORFLOW (node-side readers)
- :mod:`~tensorflowonspark_tpu.cluster.node` — node runtime
  (reference: ``tensorflowonspark/TFSparkNode.py``)
- :mod:`~tensorflowonspark_tpu.feed` — ``DataFeed`` in-graph API
  (reference: ``tensorflowonspark/TFNode.py``)
- :mod:`~tensorflowonspark_tpu.api.pipeline` — ``TFEstimator`` / ``TFModel``
  (reference: ``tensorflowonspark/pipeline.py``)
- :mod:`~tensorflowonspark_tpu.data.dfutil` — TFRecord interop
  (reference: ``tensorflowonspark/dfutil.py``)
"""

__version__ = "0.1.0"

# utils first: its __init__ hosts the TFOS_TFSAN=1 lock-witness import
# hook, which must patch threading BEFORE any package module's
# module-level/ctor lock creation runs (utils/lockwitness.py).
import tensorflowonspark_tpu.utils  # noqa: E402,F401

from tensorflowonspark_tpu.cluster.tfcluster import InputMode, TFCluster  # noqa: E402
from tensorflowonspark_tpu.feed.datafeed import DataFeed  # noqa: E402
from tensorflowonspark_tpu.cluster.context import TFNodeContext  # noqa: E402

__all__ = [
    "InputMode",
    "TFCluster",
    "DataFeed",
    "TFNodeContext",
    "__version__",
]
