// Shared-memory SPSC ring buffer — C ABI for ctypes.
//
// The feed data plane's same-host fast path. The reference's hot loop
// paid a pickle+socket proxy call per queue op (SURVEY.md §3.2 calls this
// "the dominant overhead of the whole design"); here a co-located
// producer (feeder task) streams length-prefixed byte records through
// POSIX shared memory to the node process, with no syscalls on the data
// path (mmap'd memory + atomics; short sleeps only when full/empty).
//
// Layout: a 128-byte header followed by a power-of-two-free byte region
// of `capacity` bytes. `head`/`tail` are monotonic byte offsets
// (position = offset % capacity); records are a 4-byte little-endian
// length + payload byte stream that wraps modularly, so no space is lost
// at the end of the region and no wrap markers are needed.
//
// Contract: exactly one producer thread and one consumer thread at a
// time (the Python wrapper serializes concurrent users per handle).
// The consumer creates+owns the segment (shmring_create + shmring_unlink);
// the producer attaches by name (shmring_open).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <new>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x54464f535f52494eULL;  // "TFOS_RIN"

struct alignas(64) Header {
  uint64_t magic;
  uint64_t capacity;
  alignas(64) std::atomic<uint64_t> head;    // producer-advanced
  alignas(64) std::atomic<uint64_t> tail;    // consumer-advanced
  alignas(64) std::atomic<uint32_t> closed;  // producer done writing
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  size_t map_len;
  int fd;
  bool owner;
};

constexpr int kOk = 0;
constexpr int kTimeout = -1;
constexpr int kClosed = -2;
constexpr int kTooBig = -3;
constexpr int kError = -4;

int64_t now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000LL + ts.tv_nsec / 1000000LL;
}

void backoff(int iter) {
  if (iter < 64) return;  // pure spin first
  timespec ts{0, iter < 1024 ? 50'000 : 500'000};  // 50us then 500us
  nanosleep(&ts, nullptr);
}

// Copy n bytes into the ring at byte-offset `off` (modular).
void ring_write(Ring* r, uint64_t off, const uint8_t* src, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t pos = off % cap;
  uint64_t first = n < cap - pos ? n : cap - pos;
  std::memcpy(r->data + pos, src, first);
  if (n > first) std::memcpy(r->data, src + first, n - first);
}

void ring_read(Ring* r, uint64_t off, uint8_t* dst, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t pos = off % cap;
  uint64_t first = n < cap - pos ? n : cap - pos;
  std::memcpy(dst, r->data + pos, first);
  if (n > first) std::memcpy(dst + first, r->data, n - first);
}

}  // namespace

extern "C" {

void* shmring_create(const char* name, uint64_t capacity) {
  size_t map_len = sizeof(Header) + capacity;
  shm_unlink(name);  // stale segment from a crashed prior run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Header* hdr = new (mem) Header();
  hdr->capacity = capacity;
  hdr->head.store(0);
  hdr->tail.store(0);
  hdr->closed.store(0);
  hdr->magic = kMagic;  // last: flags segment as initialized
  return new Ring{hdr, static_cast<uint8_t*>(mem) + sizeof(Header), map_len, fd, true};
}

void* shmring_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(sizeof(Header))) {
    close(fd);
    return nullptr;
  }
  size_t map_len = static_cast<size_t>(st.st_size);
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* hdr = static_cast<Header*>(mem);
  if (hdr->magic != kMagic || sizeof(Header) + hdr->capacity != map_len) {
    munmap(mem, map_len);
    close(fd);
    return nullptr;
  }
  return new Ring{hdr, static_cast<uint8_t*>(mem) + sizeof(Header), map_len, fd, false};
}

// Append one record. Blocks while the ring lacks space, up to timeout_ms
// (-1 = wait forever). 0 on success.
int shmring_push(void* handle, const uint8_t* data, uint64_t len,
                 int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t need = 4 + len;
  uint64_t cap = r->hdr->capacity;
  // The on-wire length prefix is 4 bytes: guard the uint32 cast too.
  if (need > cap || len > UINT32_MAX - 4) return kTooBig;
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  int iter = 0;
  while (cap - (head - r->hdr->tail.load(std::memory_order_acquire)) < need) {
    if (r->hdr->closed.load(std::memory_order_relaxed)) return kClosed;
    if (deadline >= 0 && now_ms() > deadline) return kTimeout;
    backoff(iter++);
  }
  uint32_t len32 = static_cast<uint32_t>(len);
  uint8_t lenbuf[4];
  std::memcpy(lenbuf, &len32, 4);
  ring_write(r, head, lenbuf, 4);
  ring_write(r, head + 4, data, len);
  r->hdr->head.store(head + need, std::memory_order_release);
  return kOk;
}

// Wait for a record; returns its payload length without consuming it.
// kTimeout / kClosed (closed AND drained) otherwise.
int64_t shmring_peek_len(void* handle, int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(handle);
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  int iter = 0;
  while (r->hdr->head.load(std::memory_order_acquire) - tail < 4) {
    if (r->hdr->closed.load(std::memory_order_acquire) &&
        r->hdr->head.load(std::memory_order_acquire) == tail)
      return kClosed;
    if (deadline >= 0 && now_ms() > deadline) return kTimeout;
    backoff(iter++);
  }
  uint8_t lenbuf[4];
  ring_read(r, tail, lenbuf, 4);
  uint32_t len32;
  std::memcpy(&len32, lenbuf, 4);
  return static_cast<int64_t>(len32);
}

// Consume the record previously sized by shmring_peek_len into `out`
// (cap must be >= its length). Returns the length, or kError on misuse.
int64_t shmring_pop(void* handle, uint8_t* out, uint64_t out_cap) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (r->hdr->head.load(std::memory_order_acquire) - tail < 4) return kError;
  uint8_t lenbuf[4];
  ring_read(r, tail, lenbuf, 4);
  uint32_t len32;
  std::memcpy(&len32, lenbuf, 4);
  if (len32 > out_cap) return kTooBig;
  ring_read(r, tail + 4, out, len32);
  r->hdr->tail.store(tail + 4 + len32, std::memory_order_release);
  return static_cast<int64_t>(len32);
}

// ---- columnar zero-copy extensions ----------------------------------------
//
// The columnar feed path consumes records as VIEWS over the ring memory
// instead of copying them out: the Python side keeps a consumer-local
// virtual cursor (monotonic byte offset, >= tail) and releases slots by
// advancing the shared tail only once all views over them have died
// (refcounted frames). These entry points are offset-addressed so the
// cursor can run ahead of the tail; the SPSC contract is unchanged.

// Payload length of the record at byte-offset `off` (a consumer-side
// cursor), waiting up to timeout_ms for one to arrive. kTimeout, or
// kClosed once the producer closed AND everything up to `off` is
// consumed.
int64_t shmring_avail(void* handle, uint64_t off, int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(handle);
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  int iter = 0;
  while (r->hdr->head.load(std::memory_order_acquire) - off < 4) {
    if (r->hdr->closed.load(std::memory_order_acquire) &&
        r->hdr->head.load(std::memory_order_acquire) == off)
      return kClosed;
    if (deadline >= 0 && now_ms() > deadline) return kTimeout;
    backoff(iter++);
  }
  uint8_t lenbuf[4];
  ring_read(r, off, lenbuf, 4);
  uint32_t len32;
  std::memcpy(&len32, lenbuf, 4);
  return static_cast<int64_t>(len32);
}

// Pointer to the payload of the record at `off` when it lies contiguous
// in the mapping; NULL when it wraps the ring end (the caller copies it
// out via shmring_read_at instead). The pointer stays valid until the
// tail is advanced past the record.
const uint8_t* shmring_payload_ptr(void* handle, uint64_t off, uint64_t len) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t cap = r->hdr->capacity;
  uint64_t pos = (off + 4) % cap;
  if (pos + len > cap) return nullptr;
  return r->data + pos;
}

// Modular copy of n bytes starting at byte-offset `off` (absolute, not
// payload-relative: callers pass off+4 to skip the length prefix).
void shmring_read_at(void* handle, uint64_t off, uint8_t* dst, uint64_t n) {
  ring_read(static_cast<Ring*>(handle), off, dst, n);
}

uint64_t shmring_tail(void* handle) {
  return static_cast<Ring*>(handle)->hdr->tail.load(std::memory_order_acquire);
}

// Release consumed bytes: advance the shared tail to `new_tail`
// (monotonic; the Python frame bookkeeping guarantees FIFO release).
void shmring_set_tail(void* handle, uint64_t new_tail) {
  static_cast<Ring*>(handle)->hdr->tail.store(new_tail,
                                              std::memory_order_release);
}

// Scatter push: ONE record whose payload is the concatenation of
// `nparts` buffers — the columnar frame path appends header + column
// buffers straight from numpy memory, no assembly copy on the producer.
int shmring_pushv(void* handle, const uint8_t* const* parts,
                  const uint64_t* lens, uint64_t nparts, int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t total = 0;
  for (uint64_t i = 0; i < nparts; i++) total += lens[i];
  uint64_t need = 4 + total;
  uint64_t cap = r->hdr->capacity;
  if (need > cap || total > UINT32_MAX - 4) return kTooBig;
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  int iter = 0;
  while (cap - (head - r->hdr->tail.load(std::memory_order_acquire)) < need) {
    if (r->hdr->closed.load(std::memory_order_relaxed)) return kClosed;
    if (deadline >= 0 && now_ms() > deadline) return kTimeout;
    backoff(iter++);
  }
  uint32_t len32 = static_cast<uint32_t>(total);
  uint8_t lenbuf[4];
  std::memcpy(lenbuf, &len32, 4);
  ring_write(r, head, lenbuf, 4);
  uint64_t off = head + 4;
  for (uint64_t i = 0; i < nparts; i++) {
    ring_write(r, off, parts[i], lens[i]);
    off += lens[i];
  }
  r->hdr->head.store(head + need, std::memory_order_release);
  return kOk;
}

void shmring_close_write(void* handle) {
  static_cast<Ring*>(handle)->hdr->closed.store(1, std::memory_order_release);
}

int shmring_is_closed(void* handle) {
  return static_cast<Ring*>(handle)->hdr->closed.load(std::memory_order_acquire)
             ? 1
             : 0;
}

uint64_t shmring_capacity(void* handle) {
  return static_cast<Ring*>(handle)->hdr->capacity;
}

// Bytes currently buffered (diagnostics / tests).
uint64_t shmring_size(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  return r->hdr->head.load(std::memory_order_acquire) -
         r->hdr->tail.load(std::memory_order_acquire);
}

void shmring_detach(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  munmap(r->hdr, r->map_len);
  close(r->fd);
  delete r;
}

int shmring_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
