"""ctypes bindings for the shared-memory ring buffer (``shmring.cc``).

The feed plane's same-host fast path: a co-located producer streams
pickled record chunks through POSIX shm instead of the TCP manager proxy
(the reference's per-item proxied ``queue.put`` — SURVEY.md §3.2).

Ownership: the CONSUMER side (node process) creates the segment and
advertises its name in the reservation roster; producers attach by name.
One producer and one consumer at a time (per-handle locks serialize
threads within a process; the cluster feed plane already guarantees one
feeder per node).
"""

from __future__ import annotations

import ctypes
import threading

from tensorflowonspark_tpu.native import load_library

DEFAULT_CAPACITY = 64 * 1024 * 1024
_TIMEOUT = -1
_CLOSED = -2
_TOO_BIG = -3


def available() -> bool:
    return load_library() is not None


class ShmRing:
    """One endpoint of a shared-memory ring (see module docstring)."""

    def __init__(self, name: str, *, handle, owner: bool):
        self._lib = load_library()
        self.name = name
        self._h = handle
        self._owner = owner
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int = DEFAULT_CAPACITY) -> "ShmRing":
        lib = load_library()
        if lib is None:
            raise OSError("native library unavailable")
        h = lib.shmring_create(name.encode(), capacity)
        if not h:
            raise OSError(f"shmring_create({name!r}) failed")
        return cls(name, handle=h, owner=True)

    @classmethod
    def open(cls, name: str) -> "ShmRing":
        lib = load_library()
        if lib is None:
            raise OSError("native library unavailable")
        h = lib.shmring_open(name.encode())
        if not h:
            raise OSError(f"shmring_open({name!r}) failed")
        return cls(name, handle=h, owner=False)

    def close(self) -> None:
        with self._lock:
            if self._h is None:
                return
            self._lib.shmring_detach(self._h)
            self._h = None
            if self._owner:
                self._lib.shmring_unlink(self.name.encode())

    def __del__(self):  # best-effort cleanup of the shm segment
        try:
            self.close()
        except Exception:
            pass

    # -- producer ------------------------------------------------------------

    def push(self, record: bytes, timeout: float | None = None) -> None:
        """Append one record; raises TimeoutError / BrokenPipeError /
        ValueError (record larger than the whole ring)."""
        ms = -1 if timeout is None else int(timeout * 1000)
        with self._lock:
            if self._h is None:
                raise BrokenPipeError("shmring detached")
            rc = self._lib.shmring_push(self._h, record, len(record), ms)
        if rc == 0:
            return
        if rc == _TIMEOUT:
            raise TimeoutError(f"shmring push timed out after {timeout}s")
        if rc == _CLOSED:
            raise BrokenPipeError("shmring closed")
        if rc == _TOO_BIG:
            raise ValueError(f"record of {len(record)}B exceeds ring capacity")
        raise OSError(f"shmring_push failed: {rc}")

    def close_write(self) -> None:
        """Producer EOF: consumers drain the ring then see StopIteration."""
        with self._lock:
            if self._h is not None:
                self._lib.shmring_close_write(self._h)

    # -- consumer ------------------------------------------------------------

    def pop(self, timeout: float | None = None) -> bytes | None:
        """Next record; None when the producer closed and the ring drained;
        TimeoutError on timeout."""
        ms = -1 if timeout is None else int(timeout * 1000)
        with self._lock:
            if self._h is None:
                return None
            n = self._lib.shmring_peek_len(self._h, ms)
            if n == _CLOSED:
                return None
            if n == _TIMEOUT:
                raise TimeoutError(f"shmring pop timed out after {timeout}s")
            if n < 0:
                raise OSError(f"shmring_peek_len failed: {n}")
            buf = (ctypes.c_uint8 * n)()
            got = self._lib.shmring_pop(self._h, buf, n)
            if got != n:
                raise OSError(f"shmring_pop failed: {got}")
            return bytes(buf)

    def size(self) -> int:
        with self._lock:
            if self._h is None:
                return 0
            return int(self._lib.shmring_size(self._h))

    @property
    def capacity(self) -> int:
        with self._lock:
            if self._h is None:
                return 0
            return int(self._lib.shmring_capacity(self._h))
