"""ctypes bindings for the shared-memory ring buffer (``shmring.cc``).

The feed plane's same-host fast path: a co-located producer streams
record chunks through POSIX shm instead of the TCP manager proxy (the
reference's per-item proxied ``queue.put`` — SURVEY.md §3.2).

Ownership: the CONSUMER side (node process) creates the segment and
advertises its name in the reservation roster; producers attach by name.
One producer and one consumer at a time (per-handle locks serialize
threads within a process; the cluster feed plane already guarantees one
feeder per node).

Zero-copy consumption (the columnar feed path): :meth:`ShmRing.pop_frame`
returns the next record as a ``np.uint8`` VIEW over the ring memory when
the record lies contiguous in the mapping (it wraps the ring end only
once per ~capacity bytes, where a copy fallback kicks in). Each view is
backed by a refcounted ring frame: the consumer keeps a virtual cursor
ahead of the shared ``tail``, and the slot is released — tail advanced,
producer space reclaimed — only when the LAST view over it is garbage
collected (``weakref.finalize`` on the buffer owner at the base of every
view chain), in FIFO order. A consumer that holds decoded column views
therefore backpressures the producer through the ring itself, and a view
can never be overwritten while alive. :meth:`ShmRing.push_parts`
complements it on the producer side: one record scatter-gathered from
header + column buffers straight out of numpy memory, no assembly copy.
"""

from __future__ import annotations

import ctypes
import threading
import weakref
from collections import deque

import numpy as np

from tensorflowonspark_tpu.native import load_library

DEFAULT_CAPACITY = 64 * 1024 * 1024
_TIMEOUT = -1
_CLOSED = -2
_TOO_BIG = -3


def available() -> bool:
    return load_library() is not None


class _RingFrame:
    """One outstanding zero-copy slot: ``end`` is the ring offset just
    past the record. ``release`` is idempotent and safe from any thread
    (GC runs it via ``weakref.finalize`` when the last view dies)."""

    __slots__ = ("_ring", "end", "released")

    def __init__(self, ring: "ShmRing", end: int):
        self._ring = ring
        self.end = end
        self.released = False

    def release(self) -> None:
        ring = self._ring
        if ring is None:
            return
        self._ring = None
        ring._release_frame(self)


class ShmRing:
    """One endpoint of a shared-memory ring (see module docstring)."""

    def __init__(self, name: str, *, handle, owner: bool):
        self._lib = load_library()
        self.name = name
        self._h = handle
        self._owner = owner
        self._lock = threading.Lock()
        # Consumer-side virtual cursor: next unread ring offset. Starts
        # at the shared tail (0 for a fresh segment); runs ahead of the
        # tail while zero-copy frames are outstanding.
        self._cursor = int(self._lib.shmring_tail(handle)) if handle else 0
        # Outstanding zero-copy frames, FIFO by end offset. RLock, not
        # Lock: frame release runs from weakref.finalize, which GC can
        # invoke DURING an allocation made while this lock is held (e.g.
        # _RingFrame() in _retire) — on the same thread, so a plain lock
        # would self-deadlock the drain.
        self._frames: deque[_RingFrame] = deque()  # guarded-by: self._frame_lock
        self._frame_lock = threading.RLock()
        self._close_pending = False  # guarded-by: self._frame_lock

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int = DEFAULT_CAPACITY) -> "ShmRing":
        lib = load_library()
        if lib is None:
            raise OSError("native library unavailable")
        h = lib.shmring_create(name.encode(), capacity)
        if not h:
            raise OSError(f"shmring_create({name!r}) failed")
        return cls(name, handle=h, owner=True)

    @classmethod
    def open(cls, name: str) -> "ShmRing":
        lib = load_library()
        if lib is None:
            raise OSError("native library unavailable")
        h = lib.shmring_open(name.encode())
        if not h:
            raise OSError(f"shmring_open({name!r}) failed")
        return cls(name, handle=h, owner=False)

    def close(self) -> None:
        """Detach (and unlink, as owner). With zero-copy views still
        alive the detach is DEFERRED until the last frame releases —
        unmapping under a live view would turn it into a dangling
        pointer; the views' GC completes the close."""
        with self._lock:
            if self._h is None:
                return
            with self._frame_lock:
                if self._frames:
                    self._close_pending = True
                    return
                self._detach_locked()

    def _detach_locked(self) -> None:
        """Actual detach; caller holds ``_frame_lock`` (and there are no
        outstanding frames)."""
        if self._h is None:
            return
        self._lib.shmring_detach(self._h)
        self._h = None
        if self._owner:
            self._lib.shmring_unlink(self.name.encode())

    def __del__(self):  # best-effort cleanup of the shm segment
        try:
            self.close()
        except Exception:
            pass

    # -- producer ------------------------------------------------------------

    def push(self, record: bytes, timeout: float | None = None) -> None:
        """Append one record; raises TimeoutError / BrokenPipeError /
        ValueError (record larger than the whole ring)."""
        ms = -1 if timeout is None else int(timeout * 1000)
        with self._lock:
            if self._h is None:
                raise BrokenPipeError("shmring detached")
            rc = self._lib.shmring_push(self._h, record, len(record), ms)
        self._check_push_rc(rc, len(record), timeout)

    def push_parts(self, parts: list, timeout: float | None = None) -> None:
        """Scatter-push ONE record whose payload is the concatenation of
        ``parts`` (bytes or C-contiguous ndarrays) — the columnar frame
        path appends header + column buffers straight from numpy memory,
        skipping the single-buffer assembly copy."""
        ms = -1 if timeout is None else int(timeout * 1000)
        n = len(parts)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        keep = []  # pin every part's buffer for the duration of the call
        total = 0
        for i, p in enumerate(parts):
            if isinstance(p, np.ndarray):
                p = np.ascontiguousarray(p)
                ptrs[i] = p.ctypes.data
                lens[i] = p.nbytes
                total += p.nbytes
            else:
                ptrs[i] = ctypes.cast(ctypes.c_char_p(p), ctypes.c_void_p)
                lens[i] = len(p)
                total += len(p)
            keep.append(p)
        with self._lock:
            if self._h is None:
                raise BrokenPipeError("shmring detached")
            rc = self._lib.shmring_pushv(self._h, ptrs, lens, n, ms)
        del keep
        self._check_push_rc(rc, total, timeout)

    def _check_push_rc(self, rc: int, nbytes: int, timeout) -> None:
        if rc == 0:
            return
        if rc == _TIMEOUT:
            raise TimeoutError(f"shmring push timed out after {timeout}s")
        if rc == _CLOSED:
            raise BrokenPipeError("shmring closed")
        if rc == _TOO_BIG:
            raise ValueError(f"record of {nbytes}B exceeds ring capacity")
        raise OSError(f"shmring_push failed: {rc}")

    def close_write(self) -> None:
        """Producer EOF: consumers drain the ring then see StopIteration."""
        with self._lock:
            if self._h is not None:
                self._lib.shmring_close_write(self._h)

    # -- consumer ------------------------------------------------------------

    def _avail(self, timeout: float | None) -> int | None:
        """Length of the record at the cursor; None when closed+drained.
        Caller holds ``_lock``."""
        ms = -1 if timeout is None else int(timeout * 1000)
        n = self._lib.shmring_avail(self._h, self._cursor, ms)
        if n == _CLOSED:
            return None
        if n == _TIMEOUT:
            raise TimeoutError(f"shmring pop timed out after {timeout}s")
        if n < 0:
            raise OSError(f"shmring_avail failed: {n}")
        return int(n)

    def pop(self, timeout: float | None = None) -> bytes | None:
        """Next record, copied out; None when the producer closed and the
        ring drained; TimeoutError on timeout."""
        with self._lock:
            with self._frame_lock:
                if self._h is None or self._close_pending:
                    return None
            n = self._avail(timeout)
            if n is None:
                return None
            buf = (ctypes.c_uint8 * n)()
            self._lib.shmring_read_at(self._h, self._cursor + 4, buf, n)
            end = self._cursor + 4 + n
            self._cursor = end
            self._retire(end)
            return bytes(buf)

    def pop_frame(self, timeout: float | None = None):
        """Next record as a ``np.uint8`` VIEW over the ring memory when
        it lies contiguous (zero-copy; the slot is released when the
        last derived view is garbage collected), else a copied ``bytes``
        (the record wraps the ring end). None when closed and drained."""
        with self._lock:
            with self._frame_lock:
                if self._h is None or self._close_pending:
                    return None
            n = self._avail(timeout)
            if n is None:
                return None
            end = self._cursor + 4 + n
            ptr = self._lib.shmring_payload_ptr(self._h, self._cursor, n)
            if not ptr or n == 0:
                # wrapped (or empty) payload: copy fallback
                buf = (ctypes.c_uint8 * n)()
                self._lib.shmring_read_at(self._h, self._cursor + 4, buf, n)
                self._cursor = end
                self._retire(end)
                return bytes(buf)
            carr = (ctypes.c_ubyte * n).from_address(ptr)
            frame = _RingFrame(self, end)
            with self._frame_lock:
                self._frames.append(frame)
            # the ctypes array sits at the base of every numpy view chain
            # over this slot: its collection == no views left == release
            weakref.finalize(carr, frame.release)
            self._cursor = end
            return np.frombuffer(carr, dtype=np.uint8)

    def _retire(self, end: int) -> None:
        """A copied (non-view) record up to ``end`` is consumed: release
        immediately, honoring FIFO order behind outstanding frames.
        Caller holds ``_lock``."""
        with self._frame_lock:
            if not self._frames:
                if self._h is not None:
                    self._lib.shmring_set_tail(self._h, end)
                return
            f = _RingFrame(self, end)
            f.released = True
            f._ring = None
            self._frames.append(f)
            self._advance_locked()

    def _release_frame(self, frame: _RingFrame) -> None:
        """Frame refcount hit zero (last view GC'd): advance the shared
        tail through the released FIFO prefix; complete a deferred close
        when the last frame goes."""
        with self._frame_lock:
            frame.released = True
            self._advance_locked()
            if self._close_pending and not self._frames:
                self._close_pending = False
                self._detach_locked()

    def _advance_locked(self) -> None:  # lint: holds-lock
        """Caller holds ``_frame_lock``."""
        new_tail = None
        while self._frames and self._frames[0].released:
            new_tail = self._frames.popleft().end
        if new_tail is not None and self._h is not None:
            self._lib.shmring_set_tail(self._h, new_tail)

    def outstanding_frames(self) -> int:
        with self._frame_lock:
            return len(self._frames)

    def outstanding_bytes(self) -> int:
        """Ring bytes still pinned by outstanding zero-copy frames
        (newest frame end − shared tail) — the drain's backpressure
        signal for copying frames out instead of viewing them."""
        with self._frame_lock:
            if not self._frames or self._h is None:
                return 0
            return int(
                self._frames[-1].end - self._lib.shmring_tail(self._h)
            )

    def size(self) -> int:
        with self._lock:
            if self._h is None:
                return 0
            return int(self._lib.shmring_size(self._h))

    @property
    def capacity(self) -> int:
        with self._lock:
            if self._h is None:
                return 0
            return int(self._lib.shmring_capacity(self._h))
