// aot_runner — run an exported SavedModel with NO Python interpreter.
//
// The last inch of the reference's Scala/JVM inference-API parity
// (SURVEY.md §2.2 L7): the reference's Scala API loaded SavedModels on
// executors through the TF JVM runtime; this loads the SavedModel that
// `api/export.py:export_tf_saved_model` writes (jax2tf-converted JAX
// model) through the TF C API and runs batches from .npy files.
// Tensor names come from the export's `cpp_runner_manifest.txt` (plain
// lines: `input <logical> <op:idx> <dtype>`), so no proto parsing is
// needed here.
//
// Usage:
//   aot_runner <saved_model_dir> --in <file.npy> [--in <file2.npy> ...]
//              [--out-prefix <prefix>]
//
// Inputs bind to the manifest's inputs in manifest (sorted-key) order.
// Each output is written as `<prefix><logical>.npy` (default "out_"),
// and its shape/dtype is printed to stdout.
//
// Build (see native/aot_runner.py:build_runner, which does this on
// demand against the tensorflow pip package's lib + headers):
//   g++ -O2 -std=c++17 aot_runner.cc -I$TF/include \
//       -l:libtensorflow_cc.so.2 -l:libtensorflow_framework.so.2 \
//       -L$TF -Wl,-rpath,$TF -o aot_runner

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include "tensorflow/c/c_api.h"

namespace {

struct Npy {
  std::vector<int64_t> shape;
  std::string dtype;  // numpy-style: float32, int32, ...
  std::vector<char> data;
};

[[noreturn]] void die(const std::string& msg) {
  std::fprintf(stderr, "aot_runner: %s\n", msg.c_str());
  std::exit(1);
}

// bfloat16 is deliberately absent everywhere below (npy has no native
// bf16 descr): a bf16-signature model fails fast at manifest read
// instead of after a full inference. Export bf16 models with an fp32
// boundary (cast in apply_fn) for this runner.
size_t dtype_size(const std::string& d) {
  if (d == "float32" || d == "int32") return 4;
  if (d == "float64" || d == "int64") return 8;
  if (d == "uint8" || d == "bool") return 1;
  die("unsupported dtype " + d);
}

TF_DataType tf_dtype(const std::string& d) {
  if (d == "float32") return TF_FLOAT;
  if (d == "float64") return TF_DOUBLE;
  if (d == "int32") return TF_INT32;
  if (d == "int64") return TF_INT64;
  if (d == "uint8") return TF_UINT8;
  if (d == "bool") return TF_BOOL;
  die("unsupported dtype " + d);
}

std::string npy_descr(const std::string& d) {
  if (d == "float32") return "<f4";
  if (d == "float64") return "<f8";
  if (d == "int32") return "<i4";
  if (d == "int64") return "<i8";
  if (d == "uint8") return "|u1";
  if (d == "bool") return "|b1";
  die("cannot write dtype " + d);
}

std::string dtype_from_descr(const std::string& descr) {
  if (descr == "<f4" || descr == "=f4") return "float32";
  if (descr == "<f8" || descr == "=f8") return "float64";
  if (descr == "<i4" || descr == "=i4") return "int32";
  if (descr == "<i8" || descr == "=i8") return "int64";
  if (descr == "|u1") return "uint8";
  if (descr == "|b1") return "bool";
  die("unsupported npy descr " + descr);
}

// Minimal .npy v1/v2 reader: little-endian C-order arrays only.
Npy read_npy(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) die("cannot open " + path);
  char magic[6];
  f.read(magic, 6);
  if (!f || std::memcmp(magic, "\x93NUMPY", 6) != 0)
    die(path + " is not a .npy file");
  unsigned char ver[2];
  f.read(reinterpret_cast<char*>(ver), 2);
  uint32_t hlen = 0;
  if (ver[0] == 1) {
    unsigned char b[2];
    f.read(reinterpret_cast<char*>(b), 2);
    hlen = b[0] | (b[1] << 8);
  } else {
    unsigned char b[4];
    f.read(reinterpret_cast<char*>(b), 4);
    hlen = b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24);
  }
  std::string header(hlen, '\0');
  f.read(&header[0], hlen);

  auto field = [&](const std::string& key) -> std::string {
    size_t k = header.find("'" + key + "'");
    if (k == std::string::npos) die(path + ": npy header missing " + key);
    size_t c = header.find(':', k);
    size_t start = header.find_first_not_of(" ", c + 1);
    if (header[start] == '\'') {
      size_t end = header.find('\'', start + 1);
      return header.substr(start + 1, end - start - 1);
    }
    if (header[start] == '(') {
      size_t end = header.find(')', start);
      return header.substr(start + 1, end - start - 1);
    }
    size_t end = header.find_first_of(",}", start);
    return header.substr(start, end - start);
  };

  if (field("fortran_order") != "False")
    die(path + ": fortran-order npy not supported");
  Npy out;
  out.dtype = dtype_from_descr(field("descr"));
  std::stringstream shape(field("shape"));
  std::string tok;
  while (std::getline(shape, tok, ',')) {
    tok.erase(0, tok.find_first_not_of(" "));
    if (!tok.empty()) out.shape.push_back(std::stoll(tok));
  }
  size_t count = 1;
  for (int64_t d : out.shape) count *= static_cast<size_t>(d);
  out.data.resize(count * dtype_size(out.dtype));
  f.read(out.data.data(), static_cast<std::streamsize>(out.data.size()));
  if (!f) die(path + ": truncated npy data");
  return out;
}

void write_npy(const std::string& path, const std::string& dtype,
               const std::vector<int64_t>& shape, const void* data,
               size_t nbytes) {
  std::ostringstream dict;
  dict << "{'descr': '" << npy_descr(dtype)
       << "', 'fortran_order': False, 'shape': (";
  // every dim emits "N, " — the 1-D case thus gets the trailing comma
  // python's tuple syntax wants
  for (size_t i = 0; i < shape.size(); ++i) dict << shape[i] << ", ";
  dict << "), }";
  std::string h = dict.str();
  size_t total = 10 + h.size() + 1;           // magic+ver+len + header + \n
  size_t pad = (64 - total % 64) % 64;
  h += std::string(pad, ' ');
  h += '\n';
  std::ofstream f(path, std::ios::binary);
  if (!f) die("cannot write " + path);
  f.write("\x93NUMPY\x01\x00", 8);
  uint16_t hlen = static_cast<uint16_t>(h.size());
  char lenb[2] = {static_cast<char>(hlen & 0xff),
                  static_cast<char>(hlen >> 8)};
  f.write(lenb, 2);
  f.write(h.data(), static_cast<std::streamsize>(h.size()));
  f.write(static_cast<const char*>(data),
          static_cast<std::streamsize>(nbytes));
}

struct Binding {
  std::string logical;
  std::string tensor;  // "op:idx"
  std::string dtype;
};

struct Manifest {
  std::vector<Binding> inputs, outputs;
};

Manifest read_manifest(const std::string& dir) {
  std::string path = dir + "/cpp_runner_manifest.txt";
  std::ifstream f(path);
  if (!f)
    die("missing " + path +
        " (re-export with api.export.export_tf_saved_model)");
  Manifest m;
  std::string line;
  while (std::getline(f, line)) {
    std::stringstream ss(line);
    std::string kind, logical, tensor, dtype;
    ss >> kind >> logical >> tensor >> dtype;
    if (kind == "input") m.inputs.push_back({logical, tensor, dtype});
    if (kind == "output") m.outputs.push_back({logical, tensor, dtype});
  }
  if (m.inputs.empty() || m.outputs.empty())
    die(path + " has no inputs/outputs");
  // Fail fast on unsupported dtypes (e.g. a bf16 signature) before any
  // model load or inference work is spent.
  for (const Binding& b : m.inputs) tf_dtype(b.dtype);
  for (const Binding& b : m.outputs) npy_descr(b.dtype);
  return m;
}

TF_Output resolve(TF_Graph* graph, const std::string& tensor) {
  size_t colon = tensor.rfind(':');
  std::string op = tensor.substr(0, colon);
  int index = colon == std::string::npos
                  ? 0
                  : std::stoi(tensor.substr(colon + 1));
  TF_Operation* oper = TF_GraphOperationByName(graph, op.c_str());
  if (!oper) die("graph has no operation " + op);
  return TF_Output{oper, index};
}

void check(TF_Status* status) {
  if (TF_GetCode(status) != TF_OK) die(TF_Message(status));
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr,
                 "usage: aot_runner <saved_model_dir> --in f.npy [--in ...] "
                 "[--out-prefix p]\n");
    return 2;
  }
  std::string dir = argv[1];
  std::vector<std::string> in_paths;
  std::string out_prefix = "out_";
  for (int i = 2; i < argc; ++i) {
    std::string a = argv[i];
    if (a == "--in" && i + 1 < argc) {
      in_paths.push_back(argv[++i]);
    } else if (a == "--out-prefix" && i + 1 < argc) {
      out_prefix = argv[++i];
    } else {
      die("unknown argument " + a);
    }
  }

  Manifest m = read_manifest(dir);
  if (in_paths.size() != m.inputs.size())
    die("model takes " + std::to_string(m.inputs.size()) +
        " input(s), got " + std::to_string(in_paths.size()));

  TF_Status* status = TF_NewStatus();
  TF_SessionOptions* opts = TF_NewSessionOptions();
  TF_Graph* graph = TF_NewGraph();
  const char* tags[] = {"serve"};
  TF_Session* session = TF_LoadSessionFromSavedModel(
      opts, nullptr, dir.c_str(), tags, 1, graph, nullptr, status);
  check(status);

  std::vector<TF_Output> in_ops, out_ops;
  std::vector<TF_Tensor*> in_tensors;
  for (size_t i = 0; i < m.inputs.size(); ++i) {
    Npy npy = read_npy(in_paths[i]);
    if (npy.dtype != m.inputs[i].dtype)
      die("input " + m.inputs[i].logical + " expects " + m.inputs[i].dtype +
          ", file has " + npy.dtype);
    in_ops.push_back(resolve(graph, m.inputs[i].tensor));
    TF_Tensor* t = TF_AllocateTensor(
        tf_dtype(npy.dtype), npy.shape.data(),
        static_cast<int>(npy.shape.size()), npy.data.size());
    std::memcpy(TF_TensorData(t), npy.data.data(), npy.data.size());
    in_tensors.push_back(t);
  }
  for (const Binding& b : m.outputs) out_ops.push_back(resolve(graph, b.tensor));
  std::vector<TF_Tensor*> out_tensors(m.outputs.size(), nullptr);

  TF_SessionRun(session, nullptr, in_ops.data(), in_tensors.data(),
                static_cast<int>(in_tensors.size()), out_ops.data(),
                out_tensors.data(), static_cast<int>(out_tensors.size()),
                nullptr, 0, nullptr, status);
  check(status);

  for (size_t i = 0; i < out_tensors.size(); ++i) {
    TF_Tensor* t = out_tensors[i];
    std::vector<int64_t> shape(TF_NumDims(t));
    std::ostringstream shape_str;
    for (int d = 0; d < TF_NumDims(t); ++d) {
      shape[d] = TF_Dim(t, d);
      shape_str << (d ? "," : "") << shape[d];
    }
    const std::string& dtype = m.outputs[i].dtype;
    std::string path = out_prefix + m.outputs[i].logical + ".npy";
    write_npy(path, dtype, shape, TF_TensorData(t), TF_TensorByteSize(t));
    std::printf("%s shape=(%s) dtype=%s -> %s\n",
                m.outputs[i].logical.c_str(), shape_str.str().c_str(),
                dtype.c_str(), path.c_str());
    TF_DeleteTensor(t);
  }
  for (TF_Tensor* t : in_tensors) TF_DeleteTensor(t);
  TF_CloseSession(session, status);
  TF_DeleteSession(session, status);
  TF_DeleteGraph(graph);
  TF_DeleteSessionOptions(opts);
  TF_DeleteStatus(status);
  return 0;
}
