// Native TFRecord codec — C ABI for ctypes.
//
// Replaces the reference's Java record-I/O path (the tensorflow-hadoop /
// spark-tensorflow-connector jar consumed by tensorflowonspark/dfutil.py —
// SURVEY.md §2.2) with an in-repo C++ reader/writer, so record framing
// does not round-trip through tf.io on the hot path.
//
// Format (per record): uint64le length | uint32le masked_crc(length bytes)
//                      | payload | uint32le masked_crc(payload).
//
// API contract (see native/tfrecord.py):
//  - writer: open -> append* -> flush/close. append is buffered (fwrite).
//  - reader: open -> next* -> close. next returns a pointer into an
//    internal buffer valid until the following next/close. Returns the
//    payload length, 0 on clean EOF, negative on framing/crc errors.
// Thread safety: one handle per thread (same as FILE*).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "crc32c.h"

using tfos_native::masked_crc32c;

namespace {

struct Writer {
  FILE* f;
};

struct Reader {
  FILE* f;
  std::vector<uint8_t> buf;
};

constexpr int kErrIo = -1;
constexpr int kErrCorruptHeader = -2;
constexpr int kErrCorruptData = -3;
constexpr int kErrTruncated = -4;

}  // namespace

extern "C" {

void* tfr_writer_open(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  return new Writer{f};
}

// Returns 0 on success, kErrIo on write failure.
int tfr_writer_append(void* handle, const uint8_t* data, uint64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  uint8_t header[12];
  std::memcpy(header, &len, 8);  // x86_64: already little-endian
  uint32_t len_crc = masked_crc32c(header, 8);
  std::memcpy(header + 8, &len_crc, 4);
  uint32_t data_crc = masked_crc32c(data, len);
  if (std::fwrite(header, 1, 12, w->f) != 12) return kErrIo;
  if (len && std::fwrite(data, 1, len, w->f) != len) return kErrIo;
  if (std::fwrite(&data_crc, 1, 4, w->f) != 4) return kErrIo;
  return 0;
}

int tfr_writer_flush(void* handle) {
  return std::fflush(static_cast<Writer*>(handle)->f) == 0 ? 0 : kErrIo;
}

int tfr_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  int rc = std::fclose(w->f) == 0 ? 0 : kErrIo;
  delete w;
  return rc;
}

void* tfr_reader_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  return new Reader{f, {}};
}

// Reads the next record. *out points into an internal buffer valid until
// the next call. Returns payload length (>= 0... 0-length payloads are
// reported via *ok=1 with return 0), clean EOF via *ok=0 with return 0,
// negative on error.
int64_t tfr_reader_next(void* handle, const uint8_t** out, int* ok) {
  Reader* r = static_cast<Reader*>(handle);
  *ok = 0;
  *out = nullptr;
  uint8_t header[12];
  size_t got = std::fread(header, 1, 12, r->f);
  if (got == 0 && std::feof(r->f)) return 0;  // clean EOF
  if (got != 12) return kErrTruncated;
  uint64_t len;
  uint32_t len_crc;
  std::memcpy(&len, header, 8);
  std::memcpy(&len_crc, header + 8, 4);
  if (masked_crc32c(header, 8) != len_crc) return kErrCorruptHeader;
  r->buf.resize(len);
  if (len && std::fread(r->buf.data(), 1, len, r->f) != len) return kErrTruncated;
  uint32_t data_crc;
  if (std::fread(&data_crc, 1, 4, r->f) != 4) return kErrTruncated;
  if (masked_crc32c(r->buf.data(), len) != data_crc) return kErrCorruptData;
  *out = r->buf.data();
  *ok = 1;
  return static_cast<int64_t>(len);
}

void tfr_reader_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  std::fclose(r->f);
  delete r;
}

uint32_t tfr_masked_crc32c(const uint8_t* data, uint64_t len) {
  return masked_crc32c(data, len);
}

// Builds a byte-offset index for random access (the grain data-source
// path): scans the file reading only the 12-byte headers, verifying each
// length-crc, and skipping payloads with fseeko. On success returns the
// record count and sets *out to a malloc'd array of 2*count uint64
// values interleaved as (payload_offset, payload_len); the caller frees
// it with tfr_index_free. Negative error codes as in the reader.
int64_t tfr_index_file(const char* path, uint64_t** out) {
  *out = nullptr;
  FILE* f = std::fopen(path, "rb");
  if (!f) return kErrIo;
  std::vector<uint64_t> entries;
  uint64_t pos = 0;
  int64_t rc = 0;
  for (;;) {
    uint8_t header[12];
    size_t got = std::fread(header, 1, 12, f);
    if (got == 0 && std::feof(f)) break;  // clean EOF at a boundary
    if (got != 12) {
      rc = kErrTruncated;
      break;
    }
    uint64_t len;
    uint32_t len_crc;
    std::memcpy(&len, header, 8);
    std::memcpy(&len_crc, header + 8, 4);
    if (masked_crc32c(header, 8) != len_crc) {
      rc = kErrCorruptHeader;
      break;
    }
    entries.push_back(pos + 12);
    entries.push_back(len);
    if (fseeko(f, static_cast<off_t>(len) + 4, SEEK_CUR) != 0) {
      rc = kErrIo;
      break;
    }
    pos += 12 + len + 4;
  }
  if (rc == 0) {
    // fseeko past EOF succeeds silently, so a truncated final record is
    // caught here: the walk must end exactly at the file size.
    if (fseeko(f, 0, SEEK_END) != 0 ||
        static_cast<uint64_t>(ftello(f)) != pos) {
      rc = kErrTruncated;
    }
  }
  std::fclose(f);
  if (rc != 0) return rc;
  if (entries.empty()) return 0;  // *out stays nullptr: nothing to free
  uint64_t* arr = static_cast<uint64_t*>(
      std::malloc(entries.size() * sizeof(uint64_t)));
  if (!arr) return kErrIo;
  std::memcpy(arr, entries.data(), entries.size() * sizeof(uint64_t));
  *out = arr;
  return static_cast<int64_t>(entries.size() / 2);
}

void tfr_index_free(uint64_t* p) { std::free(p); }

}  // extern "C"
