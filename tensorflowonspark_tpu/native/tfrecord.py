"""ctypes bindings for the native TFRecord codec (``tfrecord.cc``), with a
pure-Python fallback so record I/O never requires the C++ toolchain.

Reference parity: record framing done by the tensorflow-hadoop connector
jar (SURVEY.md §2.2); surfaced through :mod:`..data.dfutil`.
"""

from __future__ import annotations

import ctypes
import struct
from collections.abc import Iterator

from tensorflowonspark_tpu.native import load_library

_ERRORS = {
    -1: "I/O error",
    -2: "corrupt length header (crc mismatch)",
    -3: "corrupt payload (crc mismatch)",
    -4: "truncated record",
}


class TFRecordWriter:
    """Write length+crc framed records. ``native`` property says which path."""

    def __init__(self, path: str):
        self._lib = load_library()
        self._path = path
        if self._lib is not None:
            self._h = self._lib.tfr_writer_open(path.encode())
            if not self._h:
                raise OSError(f"cannot open {path!r} for writing")
            self._f = None
        else:
            self._h = None
            self._f = open(path, "wb")

    @property
    def native(self) -> bool:
        return self._h is not None

    def write(self, record: bytes) -> None:
        if self._h is not None:
            rc = self._lib.tfr_writer_append(self._h, record, len(record))
            if rc != 0:
                raise OSError(f"write failed: {_ERRORS.get(rc, rc)}")
        else:
            header = struct.pack("<Q", len(record))
            self._f.write(header)
            self._f.write(struct.pack("<I", _py_masked_crc(header)))
            self._f.write(record)
            self._f.write(struct.pack("<I", _py_masked_crc(record)))

    def flush(self) -> None:
        if self._h is not None:
            self._lib.tfr_writer_flush(self._h)
        else:
            self._f.flush()

    def close(self) -> None:
        if self._h is not None:
            self._lib.tfr_writer_close(self._h)
            self._h = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: str) -> Iterator[bytes]:
    """Yield record payloads from one TFRecord file (native or fallback)."""
    lib = load_library()
    if lib is None:
        yield from _py_read_records(path)
        return
    h = lib.tfr_reader_open(path.encode())
    if not h:
        raise OSError(f"cannot open {path!r}")
    try:
        out = ctypes.POINTER(ctypes.c_uint8)()
        ok = ctypes.c_int()
        while True:
            n = lib.tfr_reader_next(h, ctypes.byref(out), ctypes.byref(ok))
            if n < 0:
                raise OSError(f"{path}: {_ERRORS.get(n, n)}")
            if not ok.value:
                return
            yield ctypes.string_at(out, n)
    finally:
        lib.tfr_reader_close(h)


# --- pure-Python fallback ---------------------------------------------------

_CRC_TABLE: list[int] | None = None


def _crc32c_py(data: bytes) -> int:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else (c >> 1)
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _py_masked_crc(data: bytes) -> int:
    crc = _crc32c_py(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _py_read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) != 12:
                raise OSError(f"{path}: truncated record")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if _py_masked_crc(header[:8]) != len_crc:
                raise OSError(f"{path}: corrupt length header (crc mismatch)")
            payload = f.read(length)
            tail = f.read(4)
            if len(payload) != length or len(tail) != 4:
                raise OSError(f"{path}: truncated record")
            if _py_masked_crc(payload) != struct.unpack("<I", tail)[0]:
                raise OSError(f"{path}: corrupt payload (crc mismatch)")
            yield payload
