// CRC32C (Castagnoli) — needed for TFRecord framing (each record carries
// masked crc32c checksums of its length header and payload).
//
// Hardware path: SSE4.2 crc32 instruction when compiled with -msse4.2;
// portable slicing table fallback otherwise. From-scratch implementation
// (the reference delegated record checksums to the Java
// tensorflow-hadoop connector — SURVEY.md §2.2).
#pragma once

#include <cstddef>
#include <cstdint>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

namespace tfos_native {

namespace detail {

// Generate the CRC32C lookup table at first use (reflected poly 0x82F63B78).
inline const uint32_t* crc32c_table() {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
      table[i] = c;
    }
    init = true;
  }
  return table;
}

}  // namespace detail

inline uint32_t crc32c(const void* data, size_t n, uint32_t seed = 0) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  uint32_t crc = ~seed;
#if defined(__SSE4_2__)
  while (n >= 8) {
    uint64_t v;
    __builtin_memcpy(&v, p, 8);
    crc = static_cast<uint32_t>(_mm_crc32_u64(crc, v));
    p += 8;
    n -= 8;
  }
  while (n--) crc = _mm_crc32_u8(crc, *p++);
#else
  const uint32_t* table = detail::crc32c_table();
  while (n--) crc = table[(crc ^ *p++) & 0xFF] ^ (crc >> 8);
#endif
  return ~crc;
}

// TFRecord "masked" crc: rotate right 15 and add a constant, so checksums
// of checksums don't collide with data checksums.
inline uint32_t masked_crc32c(const void* data, size_t n) {
  uint32_t crc = crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

}  // namespace tfos_native
