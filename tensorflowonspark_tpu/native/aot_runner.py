"""Build/run helper for the no-Python SavedModel runner (aot_runner.cc).

The runner binary itself never touches Python — this module only
discovers the TensorFlow pip package's headers/libraries, compiles the
binary on demand (cached in ``native/build/``), and offers a subprocess
convenience wrapper for tests and tooling.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCE = os.path.join(_DIR, "aot_runner.cc")
_BIN_NAME = "aot_runner"

_lock = threading.Lock()
_bin: str | None = None
_build_failed = False


def _tf_base() -> str | None:
    spec = importlib.util.find_spec("tensorflow")
    if spec is None or not spec.origin:
        return None
    return os.path.dirname(spec.origin)


def build_runner() -> str | None:
    """Compile (if stale) and return the runner binary path; None when
    TensorFlow or the C++ toolchain is unavailable."""
    global _bin, _build_failed
    if _bin is not None or _build_failed:
        return _bin
    with _lock:
        if _bin is not None or _build_failed:
            return _bin
        base = _tf_base()
        if base is None:
            logger.warning("tensorflow not installed; aot_runner unavailable")
            _build_failed = True
            return None
        build_dir = os.environ.get("TFOS_NATIVE_BUILD_DIR") or os.path.join(
            _DIR, "build"
        )
        os.makedirs(build_dir, exist_ok=True)
        bin_path = os.path.join(build_dir, _BIN_NAME)
        if not os.path.exists(bin_path) or os.path.getmtime(
            bin_path
        ) < os.path.getmtime(_SOURCE):
            tmp = bin_path + f".tmp.{os.getpid()}"  # atomic vs concurrent builders
            cmd = [
                os.environ.get("CXX", "g++"),
                "-O2",
                "-std=c++17",
                "-Wall",
                _SOURCE,
                f"-I{os.path.join(base, 'include')}",
                f"-L{base}",
                "-l:libtensorflow_cc.so.2",
                "-l:libtensorflow_framework.so.2",
                f"-Wl,-rpath,{base}",
                "-o",
                tmp,
            ]
            logger.info("building aot_runner: %s", " ".join(cmd))
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                os.replace(tmp, bin_path)
            except (OSError, subprocess.CalledProcessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                logger.warning(
                    "aot_runner build failed: %s", detail.strip()[:800]
                )
                _build_failed = True
                return None
        _bin = bin_path
    return _bin


def run_saved_model(saved_model_dir: str, inputs, out_dir: str) -> dict:
    """Run the C++ binary over ``inputs`` (list of np arrays, manifest
    order) and return {logical_name: np.ndarray} outputs.

    Every inference step happens in the subprocess — this wrapper only
    stages .npy files, so it doubles as the CI proof that the artifact
    is consumable without a Python interpreter."""
    import numpy as np

    binary = build_runner()
    if binary is None:
        raise RuntimeError("aot_runner binary unavailable (no TF or no g++)")
    os.makedirs(out_dir, exist_ok=True)
    args = [binary, saved_model_dir]
    for i, arr in enumerate(inputs):
        path = os.path.join(out_dir, f"in{i}.npy")
        np.save(path, np.ascontiguousarray(arr))
        args += ["--in", path]
    prefix = os.path.join(out_dir, "out_")
    args += ["--out-prefix", prefix]
    proc = subprocess.run(args, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"aot_runner failed (rc={proc.returncode}): {proc.stderr[-800:]}"
        )
    out = {}
    for line in proc.stdout.splitlines():
        logical = line.split(" ", 1)[0]
        path = f"{prefix}{logical}.npy"
        if os.path.exists(path):
            out[logical] = np.load(path)
    return out
