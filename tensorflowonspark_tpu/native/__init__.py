"""Native (C++) runtime components and their ctypes bindings.

Two components, both from-scratch C++ replacing engine capabilities the
reference delegated to external native code (SURVEY.md §2.2):

- ``tfrecord.cc`` — TFRecord framing codec with masked crc32c (replaces
  the Java tensorflow-hadoop connector consumed by ``dfutil.py``).
- ``shmring.cc`` — shared-memory SPSC ring buffer, the same-host feed
  fast path (replaces the reference's pickle+socket proxy hot loop,
  SURVEY.md §3.2).

The library is compiled on demand with the toolchain's ``g++`` (cached
next to the sources, rebuilt when they change). Callers must tolerate
``load_library()`` returning None — every user has a pure-Python
fallback, so the framework works without a C++ toolchain.
"""

from __future__ import annotations

import ctypes
import logging
import os
import platform
import subprocess
import threading

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ("tfrecord.cc", "shmring.cc")
_HEADERS = ("crc32c.h",)  # staleness check only; not on the compile line
_LIB_NAME = "libtfos_native.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _build_dir() -> str:
    d = os.environ.get("TFOS_NATIVE_BUILD_DIR") or os.path.join(_DIR, "build")
    os.makedirs(d, exist_ok=True)
    return d


def _needs_build(lib_path: str) -> bool:
    if not os.path.exists(lib_path):
        return True
    lib_mtime = os.path.getmtime(lib_path)
    return any(
        os.path.getmtime(os.path.join(_DIR, s)) > lib_mtime
        for s in _SOURCES + _HEADERS
    )


def _compile(lib_path: str) -> None:
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-Wall",
    ]
    if platform.machine() in ("x86_64", "AMD64"):
        cmd.append("-msse4.2")  # hardware crc32c
    cmd += [os.path.join(_DIR, s) for s in _SOURCES]
    cmd += ["-o", lib_path, "-lrt", "-pthread"]
    logger.info("building native library: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def load_library() -> ctypes.CDLL | None:
    """Build (if stale) and dlopen the native library; None on failure."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        lib_path = os.path.join(_build_dir(), _LIB_NAME)
        try:
            if _needs_build(lib_path):
                tmp = lib_path + f".tmp.{os.getpid()}"
                _compile(tmp)
                os.replace(tmp, lib_path)  # atomic vs concurrent builders
            lib = ctypes.CDLL(lib_path)
            _bind(lib)
            _lib = lib
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.warning(
                "native library unavailable, using pure-Python fallbacks: %s",
                detail.strip()[:500],
            )
            _load_failed = True
    return _lib


def _bind(lib: ctypes.CDLL) -> None:
    c = ctypes
    u8p, u64, i64, u32 = (
        c.POINTER(c.c_uint8),
        c.c_uint64,
        c.c_int64,
        c.c_uint32,
    )
    # tfrecord
    lib.tfr_writer_open.restype = c.c_void_p
    lib.tfr_writer_open.argtypes = [c.c_char_p]
    lib.tfr_writer_append.restype = c.c_int
    lib.tfr_writer_append.argtypes = [c.c_void_p, c.c_char_p, u64]
    lib.tfr_writer_flush.restype = c.c_int
    lib.tfr_writer_flush.argtypes = [c.c_void_p]
    lib.tfr_writer_close.restype = c.c_int
    lib.tfr_writer_close.argtypes = [c.c_void_p]
    lib.tfr_reader_open.restype = c.c_void_p
    lib.tfr_reader_open.argtypes = [c.c_char_p]
    lib.tfr_reader_next.restype = i64
    lib.tfr_reader_next.argtypes = [
        c.c_void_p,
        c.POINTER(c.POINTER(c.c_uint8)),
        c.POINTER(c.c_int),
    ]
    lib.tfr_reader_close.restype = None
    lib.tfr_reader_close.argtypes = [c.c_void_p]
    lib.tfr_masked_crc32c.restype = u32
    lib.tfr_masked_crc32c.argtypes = [c.c_char_p, u64]
    lib.tfr_index_file.restype = i64
    lib.tfr_index_file.argtypes = [
        c.c_char_p,
        c.POINTER(c.POINTER(c.c_uint64)),
    ]
    lib.tfr_index_free.restype = None
    lib.tfr_index_free.argtypes = [c.POINTER(c.c_uint64)]
    # shmring
    lib.shmring_create.restype = c.c_void_p
    lib.shmring_create.argtypes = [c.c_char_p, u64]
    lib.shmring_open.restype = c.c_void_p
    lib.shmring_open.argtypes = [c.c_char_p]
    lib.shmring_push.restype = c.c_int
    lib.shmring_push.argtypes = [c.c_void_p, c.c_char_p, u64, i64]
    lib.shmring_peek_len.restype = i64
    lib.shmring_peek_len.argtypes = [c.c_void_p, i64]
    lib.shmring_pop.restype = i64
    lib.shmring_pop.argtypes = [c.c_void_p, u8p, u64]
    lib.shmring_close_write.restype = None
    lib.shmring_close_write.argtypes = [c.c_void_p]
    lib.shmring_is_closed.restype = c.c_int
    lib.shmring_is_closed.argtypes = [c.c_void_p]
    lib.shmring_size.restype = u64
    lib.shmring_size.argtypes = [c.c_void_p]
    lib.shmring_capacity.restype = u64
    lib.shmring_capacity.argtypes = [c.c_void_p]
    lib.shmring_detach.restype = None
    lib.shmring_detach.argtypes = [c.c_void_p]
    lib.shmring_unlink.restype = c.c_int
    lib.shmring_unlink.argtypes = [c.c_char_p]
    # shmring columnar zero-copy extensions
    lib.shmring_avail.restype = i64
    lib.shmring_avail.argtypes = [c.c_void_p, u64, i64]
    lib.shmring_payload_ptr.restype = c.c_void_p
    lib.shmring_payload_ptr.argtypes = [c.c_void_p, u64, u64]
    lib.shmring_read_at.restype = None
    lib.shmring_read_at.argtypes = [c.c_void_p, u64, u8p, u64]
    lib.shmring_tail.restype = u64
    lib.shmring_tail.argtypes = [c.c_void_p]
    lib.shmring_set_tail.restype = None
    lib.shmring_set_tail.argtypes = [c.c_void_p, u64]
    lib.shmring_pushv.restype = c.c_int
    lib.shmring_pushv.argtypes = [
        c.c_void_p,
        c.POINTER(c.c_void_p),
        c.POINTER(u64),
        u64,
        i64,
    ]


def available() -> bool:
    return load_library() is not None
