"""User-facing in-graph API module.

Reference parity: ``tensorflowonspark/TFNode.py`` module-level functions
(``hdfs_path``, ``start_cluster_server``, ``export_saved_model``) plus the
``DataFeed`` class (re-exported from :mod:`tensorflowonspark_tpu.feed`).
User ``map_fun`` code written against the reference's ``from
tensorflowonspark import TFNode`` maps 1:1 onto ``from
tensorflowonspark_tpu import tfnode as TFNode``.
"""

from __future__ import annotations

from tensorflowonspark_tpu.feed.datafeed import DataFeed  # noqa: F401

__all__ = ["DataFeed", "hdfs_path", "start_cluster_server", "export_saved_model"]


def hdfs_path(ctx, path: str) -> str:
    """Resolve a path against the cluster's default FS / working dir.

    Reference: ``TFNode.py:hdfs_path``.
    """
    return ctx.absolute_path(path)


def start_cluster_server(ctx, num_gpus: int = 0, rdma: bool = False):
    """Join the distributed runtime (reference: ``TFNode.start_cluster_server``).

    ``num_gpus``/``rdma`` are accepted for signature compatibility and
    ignored: on TPU, device ownership is per-process by construction and
    transport selection (ICI vs DCN) is a property of mesh-axis placement,
    not a protocol flag.
    """
    ctx.initialize_distributed()
    return None


def export_saved_model(ctx, state, export_dir: str, **kwargs) -> str:
    """Chief-only export (reference: ``TFNode.export_saved_model``)."""
    return ctx.export_saved_model(state, export_dir, **kwargs)
