"""Cluster-wide observability: trace correlation + driver-side metrics.

PR 1 gave every process excellent LOCAL observability (span tracer,
Prometheus registry, per-node ``/metrics``), but the cluster stayed a
set of islands: a feed stall shows up as ``feed.data_wait`` on a node
and ``feed.columnize`` on the driver with no way to see they are the
same incident, and ``TFCluster.metrics_urls()`` returns URLs nobody
scrapes (SURVEY: TFoS debugging meant grepping per-executor logs).
This module is the cross-process half:

- **Trace context** (:func:`set_trace_context`): a run-scoped
  ``trace_id`` (the cluster id) plus this process's node name, stamped
  into every :meth:`SpanTracer.export` as a ``trace_context`` metadata
  event. Per-stream/per-frame span links ride the existing wires — the
  columnar frame header already carries ``{stream, seq}``, and the
  driver's ``feed.send`` / the node's ``feed.queue_get`` spans carry
  the same pair as args — so ``tools/trace_merge.py`` can stitch
  driver → transit → node → train into one causal timeline.

- **Clock sync** (:func:`note_clock_sync`): the node heartbeater
  timestamps each HEARTBEAT round-trip and the reply carries the
  driver's wall clock, so ``offset = server_time - rtt_midpoint`` is a
  classic NTP-style estimate whose error is bounded by the RTT. The
  minimum-RTT sample wins (lowest error bound). Exported with every
  trace so merged timelines align across hosts; see
  docs/OBSERVABILITY.md for the caveat.

- **MetricsAggregator**: the driver-side scraper. On the heartbeat
  cadence it GETs every node's ``/metrics``, parses the Prometheus
  text back into typed samples (:func:`parse_prometheus_text`), and
  exposes the merge three ways: ``TFCluster.cluster_stats()`` (typed
  per-node + sum/max series), a driver ``/metrics`` endpoint (every
  sample re-labelled ``node="<eid>"``, one TYPE line per family), and
  — through the process registry it shares — ``Registry.window()``
  for the future feedback autotuner (ROADMAP item 5).
"""

from __future__ import annotations

import logging
import re
import threading
import time
import urllib.request
from typing import Any, Callable, Iterable

from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.obs.registry import (
    CONTENT_TYPE,
    Registry,
    default_registry,
)

logger = logging.getLogger(__name__)

__all__ = [
    "MetricsAggregator",
    "clock_sync",
    "export_meta",
    "merge_families",
    "note_clock_sync",
    "parse_prometheus_text",
    "serve_text",
    "set_trace_context",
    "trace_context",
]


# -- trace context -----------------------------------------------------------

_ctx_lock = threading.Lock()
_trace_id: str | None = None  # guarded-by: _ctx_lock
_node: str | None = None  # guarded-by: _ctx_lock
# Best (minimum-RTT) clock sample: offset_s is what to ADD to this
# process's wall clock to get the driver's wall clock; rtt_s bounds the
# estimate's error.
_clock: dict[str, float] | None = None  # guarded-by: _ctx_lock


def set_trace_context(trace_id: str, node: str | None = None) -> None:
    """Install the run-scoped trace id (and this process's node name)
    — called once by the node runtime / driver at cluster start. Every
    subsequent ``SpanTracer.export`` carries it, so traces from N
    processes of one run are stitchable by id alone."""
    global _trace_id, _node
    with _ctx_lock:
        _trace_id = str(trace_id)
        if node is not None:
            _node = str(node)


def trace_context() -> dict[str, str | None]:
    with _ctx_lock:
        return {"trace_id": _trace_id, "node": _node}


def note_clock_sync(offset_s: float, rtt_s: float) -> None:
    """Record one clock-offset sample (driver_wall - local rtt
    midpoint). The MINIMUM-RTT sample is kept: its midpoint estimate
    has the tightest error bound (|true offset - estimate| <= rtt/2),
    so one quiet round-trip beats any amount of congested ones. Also
    mirrored as the ``node_clock_offset_seconds`` gauge."""
    global _clock
    rtt_s = max(0.0, float(rtt_s))
    with _ctx_lock:
        if _clock is None or rtt_s < _clock["rtt_s"]:
            _clock = {"offset_s": float(offset_s), "rtt_s": rtt_s}
    try:
        default_registry().gauge(
            "node_clock_offset_seconds",
            "estimated offset to the driver wall clock (heartbeat "
            "RTT-midpoint, min-RTT sample)",
        ).set(offset_s)
    except Exception:  # the clock sample must survive a registry error
        pass


def clock_sync() -> dict[str, float] | None:
    """The current best ``{"offset_s", "rtt_s"}`` estimate, or None
    before any heartbeat completed (e.g. the driver itself, whose
    offset is 0 by definition)."""
    with _ctx_lock:
        return dict(_clock) if _clock is not None else None


def export_meta() -> dict[str, Any]:
    """Trace-context fields :meth:`SpanTracer.export` embeds in the
    ``trace_context`` metadata event."""
    out: dict[str, Any] = {}
    with _ctx_lock:
        if _trace_id is not None:
            out["trace_id"] = _trace_id
        if _node is not None:
            out["node"] = _node
        if _clock is not None:
            out["clock_offset_s"] = _clock["offset_s"]
            out["clock_rtt_s"] = _clock["rtt_s"]
    return out


def _reset_for_tests() -> None:
    global _trace_id, _node, _clock
    with _ctx_lock:
        _trace_id = _node = _clock = None


# -- Prometheus text parsing -------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)(?:\s+\d+)?\s*$"
)
_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"(?P<v>(?:[^"\\]|\\.)*)"\s*,?'
)
_UNESCAPE = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}


def _unescape_label(v: str) -> str:
    return re.sub(
        r'\\(\\|"|n)', lambda m: _UNESCAPE["\\" + m.group(1)], v
    )


def parse_prometheus_text(text: str) -> dict[str, dict[str, Any]]:
    """Parse exposition format 0.0.4 back into
    ``{family: {"type": kind, "samples": {(sample_name, label_items):
    value}}}`` where ``label_items`` is a sorted tuple of ``(k, v)``
    pairs. Histogram ``_bucket``/``_sum``/``_count`` samples are
    grouped under their base family when a ``# TYPE <base> histogram``
    line declared it. Malformed lines raise ValueError — a scraper
    that silently skips lines hides exactly the exposition bugs the
    tier-1 validator exists to catch."""
    families: dict[str, dict[str, Any]] = {}
    types: dict[str, str] = {}

    def family_of(sample_name: str) -> str:
        for suf in ("_bucket", "_sum", "_count"):
            base = sample_name[: -len(suf)] if sample_name.endswith(suf) else None
            if base and types.get(base) == "histogram":
                return base
        return sample_name

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                raise ValueError(f"line {lineno}: malformed TYPE line: {line!r}")
            types[parts[2]] = parts[3]
            families.setdefault(
                parts[2], {"type": parts[3], "samples": {}}
            )["type"] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        labels: list[tuple[str, str]] = []
        raw = m.group("labels")
        if raw is not None:
            pos = 0
            while pos < len(raw):
                lm = _LABEL_PAIR_RE.match(raw, pos)
                if not lm:
                    raise ValueError(
                        f"line {lineno}: malformed labels: {raw!r}"
                    )
                labels.append((lm.group("k"), _unescape_label(lm.group("v"))))
                pos = lm.end()
        val_s = m.group("value")
        try:
            value = float(
                val_s.replace("+Inf", "inf").replace("-Inf", "-inf")
            )
        except ValueError:
            raise ValueError(
                f"line {lineno}: non-numeric value {val_s!r}"
            ) from None
        name = m.group("name")
        fam = families.setdefault(
            family_of(name), {"type": types.get(family_of(name)), "samples": {}}
        )
        key = (name, tuple(sorted(labels)))
        if key in fam["samples"]:
            raise ValueError(
                f"line {lineno}: duplicate sample {name}{dict(labels)}"
            )
        fam["samples"][key] = value
    return families


def _label_items_str(items: Iterable[tuple[str, str]]) -> str:
    """Canonical ``k="v",k2="v2"`` key (no braces) for cluster_stats
    dicts; ``""`` for the unlabeled series."""
    return ",".join(
        f'{k}="' + v.replace("\\", r"\\").replace('"', r"\"")
        .replace("\n", r"\n") + '"'
        for k, v in items
    )


def _render_label_items(items: Iterable[tuple[str, str]]) -> str:
    inner = _label_items_str(items)
    return "{" + inner + "}" if inner else ""


def merge_families(
    by_key: dict[Any, dict[str, Any]], label: str = "node"
) -> str:
    """Merge several parsed expositions (``{key: families}``, each
    families dict shaped like :func:`parse_prometheus_text`'s output)
    into ONE valid exposition: every sample re-labelled
    ``<label>="<key>"``, one TYPE line per family. A sample that
    already carries the label (honor_labels=false convention) yields
    it to the merge key, surviving as ``exported_<label>``.

    Shared by the driver's aggregated ``/metrics``
    (:meth:`MetricsAggregator.render`, ``label="node"``) and the
    serving fleet router's ``/metrics`` (``label="replica"``) so the
    two merge planes cannot drift."""
    by_family: dict[str, dict[str, Any]] = {}
    for key, families in sorted(by_key.items(), key=lambda kv: str(kv[0])):
        for fam, data in families.items():
            out = by_family.setdefault(
                fam, {"type": data.get("type"), "samples": []}
            )
            if out["type"] is None:
                out["type"] = data.get("type")
            for (sname, labels), value in sorted(data["samples"].items()):
                d = dict(labels)
                if label in d:
                    d[f"exported_{label}"] = d.pop(label)
                d[label] = str(key)
                merged = tuple(sorted(d.items()))
                out["samples"].append((sname, merged, value))
    lines: list[str] = []
    for fam in sorted(by_family):
        data = by_family[fam]
        lines.append(f"# TYPE {fam} {data['type'] or 'untyped'}")
        for sname, labels, value in data["samples"]:
            v = (
                str(int(value))
                if float(value).is_integer() and abs(value) < 1e15
                else repr(float(value))
            )
            lines.append(f"{sname}{_render_label_items(labels)} {v}")
    return "\n".join(lines) + ("\n" if lines else "")


# -- driver-side aggregation -------------------------------------------------


class MetricsAggregator:
    """Scrapes every node's ``/metrics`` on the liveness cadence and
    merges the samples into cluster-level series.

    ``targets`` is a callable returning ``{node_key: url}`` — re-resolved
    each scrape, so a roster that changes (elastic clusters, ROADMAP
    item 4) needs no aggregator restart. ``registry`` (default: the
    process-global one) is scraped locally under node key ``"driver"``
    and also receives the aggregator's own scrape counters
    (``cluster_scrape_total`` / ``cluster_scrape_errors_total`` /
    ``cluster_scrape_seconds``), so scrape overhead is itself
    observable — the mnist feed bench asserts it stays under 1% of
    ``train.step`` time.
    """

    def __init__(
        self,
        targets: Callable[[], dict[Any, str]],
        interval: float = 2.0,
        timeout: float = 5.0,
        registry: Registry | None = None,
        driver_key: str = "driver",
        history: Any = None,
    ):
        self.targets = targets
        self.interval = max(0.2, float(interval))
        self.timeout = float(timeout)
        self.registry = registry if registry is not None else default_registry()
        self.driver_key = driver_key
        # optional obs.history.History: every scrape round's parsed
        # families land in its bounded rings (labelled node=<key>), so
        # the driver holds WINDOWS of cluster telemetry — rates and
        # percentiles over the last N rounds — not just the last scrape
        self.history = history
        self._lock = threading.Lock()
        # {node_key: {"ok", "samples", "types", "error", "scraped_at"}}
        self._last: dict[Any, dict[str, Any]] = {}  # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.total_scrape_s = 0.0  # guarded-by: self._lock
        # CPU seconds the scrape thread actually consumed — wall time
        # is dominated by GIL/IO waits on a loaded host, so this is
        # the honest "stolen from training" number the bench reports.
        self.total_scrape_cpu_s = 0.0  # guarded-by: self._lock
        self._m_scrapes = self.registry.counter(
            "cluster_scrape_total", "aggregator scrape rounds"
        )
        self._m_errors = self.registry.counter(
            "cluster_scrape_errors_total",
            "per-node scrape failures, by node",
        )
        self._m_seconds = self.registry.histogram(
            "cluster_scrape_seconds", "wall time of one scrape round"
        )
        # Pull-plane rate derivation: feed_ingest_bytes_total is a
        # per-node counter; differencing it between scrape rounds gives
        # the per-node ingest rate as a driver-side gauge — the scaling
        # acceptance ("per-node throughput flat") readable straight off
        # the driver registry / aggregated /metrics endpoint.
        self._prev_ingest: dict[Any, tuple[float, float]] = {}  # guarded-by: self._lock
        self._g_ingest = self.registry.gauge(
            "cluster_node_ingest_bytes_per_s",
            "per-node executor-local ingest rate "
            "(feed_ingest_bytes_total differenced between scrapes)",
        )

    # -- scraping ------------------------------------------------------

    def _fetch(self, url: str) -> str:
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return resp.read().decode("utf-8", "replace")

    def scrape_once(self) -> dict[Any, dict[str, Any]]:
        """One scrape round over every target (plus the local driver
        registry); per-node failures are recorded, never raised — one
        dead node must not blind the aggregator to the rest."""
        t0 = time.perf_counter()
        c0 = time.thread_time()
        # counted at round START so the driver-registry snapshot taken
        # within this very round already reflects it
        self._m_scrapes.inc()
        with obs_spans.span("cluster.scrape"):
            results: dict[Any, dict[str, Any]] = {}
            now = time.time()
            targets = dict(self.targets() or {})
            for key, url in targets.items():
                entry: dict[str, Any] = {"url": url, "scraped_at": now}
                try:
                    parsed = parse_prometheus_text(self._fetch(url))
                    entry.update(ok=True, families=parsed)
                except Exception as e:  # noqa: BLE001 - recorded per node
                    entry.update(ok=False, error=f"{type(e).__name__}: {e}")
                    self._m_errors.inc(node=str(key))
                results[key] = entry
            # the driver's own registry, no HTTP hop
            try:
                results[self.driver_key] = {
                    "ok": True,
                    "scraped_at": now,
                    "families": parse_prometheus_text(self.registry.render()),
                }
            except Exception as e:  # noqa: BLE001 - recorded like a node
                results[self.driver_key] = {
                    "ok": False,
                    "scraped_at": now,
                    "error": f"{type(e).__name__}: {e}",
                }
        dt = time.perf_counter() - t0
        dt_cpu = time.thread_time() - c0
        self._m_seconds.observe(dt)
        self._note_ingest_rates(results)
        if self.history is not None:
            for key, entry in results.items():
                if not entry.get("ok"):
                    continue
                try:
                    self.history.record_families(
                        entry["families"],
                        extra_labels={"node": str(key)},
                        t=entry.get("scraped_at"),
                    )
                except Exception as e:  # noqa: BLE001 - the windowed
                    # store is an observer; it must not fail the scrape
                    logger.warning("history record failed: %s", e)
        with self._lock:
            self._last = results
            self.total_scrape_s += dt
            self.total_scrape_cpu_s += dt_cpu
        return results

    def _note_ingest_rates(self, results: dict[Any, dict[str, Any]]) -> None:
        """Difference each node's ``feed_ingest_bytes_total`` against
        the previous round into ``cluster_node_ingest_bytes_per_s``.
        Keys absent from this round (departed/elastically-removed
        nodes) are dropped from both the bookkeeping and the gauge —
        a ghost node must not report its last rate forever.

        Runs under ``self._lock`` like every other shared-state write:
        the background loop and a manual ``scrape_once()`` may race,
        and an unguarded read-modify-write of ``_prev_ingest`` would
        difference two rounds over a near-zero interval (an inflated
        rate sample)."""
        with self._lock:
            for key in list(self._prev_ingest):
                if key not in results:
                    del self._prev_ingest[key]
                    self._g_ingest.remove(node=str(key))
            for key, entry in results.items():
                if not entry.get("ok"):
                    continue
                fam = entry["families"].get("feed_ingest_bytes_total")
                if fam is None:
                    continue
                total = sum(fam["samples"].values())
                t = float(entry.get("scraped_at") or 0.0)
                prev = self._prev_ingest.get(key)
                self._prev_ingest[key] = (t, total)
                if prev is not None and t > prev[0]:
                    # max(0, ·): a node restart resets its counter
                    self._g_ingest.set(
                        max(0.0, (total - prev[1]) / (t - prev[0])),
                        node=str(key),
                    )

    def start(self) -> None:
        """Background scraping on the heartbeat cadence (daemon)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.scrape_once()
                except Exception:  # pragma: no cover - scrape_once guards
                    logger.exception("metrics scrape round failed")

        self._thread = threading.Thread(
            target=loop, daemon=True, name="metrics-aggregator"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.timeout + 1.0)

    # -- merged views --------------------------------------------------

    def last_scrape(self) -> dict[Any, dict[str, Any]]:
        with self._lock:
            return dict(self._last)

    def cluster_stats(self, fresh: bool = True) -> dict[str, Any]:
        """The merged typed view: ``{"nodes": {key: {"ok", "age_s",
        "error"?}}, "series": {sample_name: {"type", "per_node":
        {key: {label_str: value}}, "sum": {label_str: v}, "max":
        {label_str: v}}}}``. ``fresh=True`` (default) scrapes now;
        ``False`` reuses the background loop's last round."""
        snap = self.scrape_once() if fresh else self.last_scrape()
        if not snap:
            snap = self.scrape_once()
        now = time.time()
        nodes: dict[Any, dict[str, Any]] = {}
        series: dict[str, dict[str, Any]] = {}
        for key, entry in snap.items():
            nodes[key] = {
                "ok": bool(entry.get("ok")),
                "age_s": round(now - entry.get("scraped_at", now), 3),
            }
            if not entry.get("ok"):
                nodes[key]["error"] = entry.get("error")
                continue
            for fam, data in entry["families"].items():
                for (sname, labels), value in data["samples"].items():
                    s = series.setdefault(
                        sname,
                        {
                            "type": data.get("type"),
                            "per_node": {},
                            "sum": {},
                            "max": {},
                        },
                    )
                    if s["type"] is None:
                        s["type"] = data.get("type")
                    lstr = _label_items_str(labels)
                    s["per_node"].setdefault(key, {})[lstr] = value
                    s["sum"][lstr] = s["sum"].get(lstr, 0.0) + value
                    s["max"][lstr] = max(
                        s["max"].get(lstr, float("-inf")), value
                    )
        return {"nodes": nodes, "series": series}

    def render(self) -> str:
        """The merge as ONE valid exposition: every sample re-labelled
        ``node="<key>"`` (honor_labels=false: a sample's own node label
        survives as ``exported_node``), one TYPE line per family (the
        driver ``/metrics`` endpoint body) — :func:`merge_families`.
        Prometheus-side aggregation (``sum by (...)``) then works
        unmodified."""
        snap = self.last_scrape() or self.scrape_once()
        return merge_families(
            {
                key: entry["families"]
                for key, entry in snap.items()
                if entry.get("ok")
            },
            label="node",
        )


# -- HTTP --------------------------------------------------------------------


def serve_text(
    body_fn: Callable[[], str], host: str = "127.0.0.1", port: int = 0
):
    """Serve ``body_fn()`` at ``GET /metrics`` (Prometheus content
    type) on a daemon ThreadingHTTPServer; returns ``(server, port)``
    or ``(None, None)`` when the bind fails. Shared by the per-node
    registry endpoint and the driver's aggregated endpoint."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *fargs):  # scrapes are not news
            logger.debug("%s " + fmt, self.client_address[0], *fargs)

        def do_GET(self):  # noqa: N802 - http.server API
            if self.path not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            try:
                body = body_fn().encode()
            except Exception as e:  # noqa: BLE001 - a scrape must not 500 silently
                self.send_response(500)
                self.end_headers()
                self.wfile.write(str(e).encode())
                return
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    try:
        server = ThreadingHTTPServer((host, port), _Handler)
    except OSError as e:
        logger.warning("metrics endpoint unavailable (%s)", e)
        return None, None
    threading.Thread(
        target=server.serve_forever, daemon=True, name="metrics-http"
    ).start()
    return server, server.server_address[1]
