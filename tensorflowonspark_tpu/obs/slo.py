"""Declarative SLOs + multi-window burn-rate evaluation over History.

ROADMAP items 1/4/5 each restated "p99 within budget under X" as a
hand-rolled bench assert; this module makes the objective declarative
and the evaluation uniform, so serve_model ``/statusz``, the router's
shed annotations, and ``bench.py --serve-fleet/--rollout/--serve-slo``
all gate on the SAME evaluator.

An :class:`SLO` names an objective over metrics that ``obs.history``
already retains:

- ``kind="latency"``: a histogram metric; the *bad fraction* of a
  window is the share of observations slower than ``objective``
  seconds (interpolated from cumulative bucket deltas).
- ``kind="error_rate"`` / ``kind="availability"``: a bad-event counter
  over a total counter; the bad fraction is ``bad / total`` deltas.

**Burn rate** is the classic multi-window form: ``bad_fraction /
budget`` computed over a fast and a slow trailing window; a *breach*
requires BOTH to exceed their thresholds (fast catches the spike, slow
filters the blip). Verdicts are emitted three ways on every
:meth:`SLOEvaluator.evaluate`:

- ``slo_burn_rate{slo,window}`` gauge (both windows, every cycle);
- ``slo_breaches_total{slo}`` counter (rising edge only);
- a ``slo_breach`` flight-recorder event, plus an async
  ``dump_now("slo_breach:<name>")`` on the rising edge — a breach is
  an incident, and the black box should hold the moment it began.

No data (an empty window) evaluates to burn 0.0 — an idle service is
in budget, and the evaluator must not false-fire at startup.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Mapping

from tensorflowonspark_tpu.obs import flightrec
from tensorflowonspark_tpu.obs.history import History
from tensorflowonspark_tpu.obs.registry import Registry, default_registry

__all__ = ["SLO", "SLOEvaluator", "default_serving_slos", "router_slos"]

_KINDS = ("latency", "error_rate", "availability")


@dataclass(frozen=True)
class SLO:
    """One objective. ``budget`` is the allowed bad fraction (0.01 =
    99% of requests must be good); burn 1.0 = consuming budget exactly
    at the sustainable rate."""

    name: str
    kind: str
    metric: str  # histogram (latency) / bad-event counter (rates)
    objective: float = 0.0  # latency bound, seconds (latency kind only)
    budget: float = 0.01
    total_metric: str | None = None  # denominator counter (rate kinds)
    labels: Mapping[str, str] | None = None
    total_labels: Mapping[str, str] | None = None
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    fast_burn: float = 14.0
    slow_burn: float = 6.0
    description: str = ""

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"SLO {self.name!r}: kind must be one of {_KINDS}, "
                f"got {self.kind!r}"
            )
        if self.kind == "latency" and self.objective <= 0:
            raise ValueError(
                f"latency SLO {self.name!r} needs objective > 0 seconds"
            )
        if self.kind != "latency" and not self.total_metric:
            raise ValueError(
                f"{self.kind} SLO {self.name!r} needs total_metric"
            )
        if self.budget <= 0 or self.budget >= 1:
            raise ValueError(
                f"SLO {self.name!r}: budget must be in (0, 1), "
                f"got {self.budget}"
            )


def default_serving_slos(
    ttft_objective_s: float = 2.5,
    ttft_budget: float = 0.05,
    error_budget: float = 0.02,
    fast_window_s: float = 60.0,
    slow_window_s: float = 300.0,
) -> tuple[SLO, ...]:
    """serve_model's per-replica objectives, over the engine's own
    registry metrics (one replica, no router in the loop)."""
    return (
        SLO(
            name="ttft",
            kind="latency",
            metric="engine_ttft_seconds",
            objective=ttft_objective_s,
            budget=ttft_budget,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
            description="time-to-first-token within objective",
        ),
        SLO(
            name="engine_errors",
            kind="error_rate",
            metric="engine_requests_failed_total",
            total_metric="engine_requests_total",
            budget=error_budget,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
            description="engine-failed requests within error budget",
        ),
    )


def router_slos(
    latency_objective_s: float,
    latency_budget: float = 0.05,
    shed_budget: float = 0.02,
    fast_window_s: float = 60.0,
    slow_window_s: float = 300.0,
    fast_burn: float = 14.0,
    slow_burn: float = 6.0,
) -> tuple[SLO, ...]:
    """Fleet-level objectives over the router's registry — the single
    budget gate bench.py's fleet/rollout legs adopt."""
    return (
        SLO(
            name="fleet_latency",
            kind="latency",
            metric="router_request_seconds",
            objective=latency_objective_s,
            budget=latency_budget,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
            fast_burn=fast_burn,
            slow_burn=slow_burn,
            description="routed request latency within objective",
        ),
        SLO(
            name="fleet_availability",
            kind="availability",
            metric="router_shed_total",
            total_metric="router_requests_total",
            budget=shed_budget,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
            fast_burn=fast_burn,
            slow_burn=slow_burn,
            description="admission sheds within availability budget",
        ),
    )


@dataclass
class Verdict:
    """One SLO's evaluation at one instant (JSON-safe via vars())."""

    slo: str
    kind: str
    breached: bool
    burn_fast: float
    burn_slow: float
    bad_fraction_fast: float | None
    budget: float
    objective: float
    detail: dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> dict[str, Any]:
        return {
            "slo": self.slo,
            "kind": self.kind,
            "breached": self.breached,
            "burn_fast": self.burn_fast,
            "burn_slow": self.burn_slow,
            "bad_fraction_fast": self.bad_fraction_fast,
            "budget": self.budget,
            "objective": self.objective,
            **self.detail,
        }


class SLOEvaluator:
    """Evaluates a set of SLOs against one History on demand."""

    def __init__(
        self,
        slos: tuple[SLO, ...] | list[SLO],
        history: History,
        registry: Registry | None = None,
    ):
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.slos = tuple(slos)
        self.history = history
        reg = registry if registry is not None else default_registry()
        self._g_burn = reg.gauge(
            "slo_burn_rate",
            "error-budget burn rate per SLO and window (1.0 = "
            "sustainable consumption)",
        )
        self._m_breach = reg.counter(
            "slo_breaches_total",
            "multi-window SLO breach onsets (rising edges)",
        )
        self._lock = threading.Lock()
        self._breached: dict[str, bool] = {}  # guarded-by: self._lock
        self._last: list[Verdict] = []  # guarded-by: self._lock
        self._evals = 0  # guarded-by: self._lock

    # -- math ---------------------------------------------------------

    def _bad_fraction(self, slo: SLO, window_s: float, now) -> float | None:
        h = self.history
        if slo.kind == "latency":
            frac = h.fraction_le(
                slo.metric, slo.objective, dict(slo.labels or {}) or None,
                window_s=window_s, now=now,
            )
            return None if frac is None else max(0.0, 1.0 - frac)
        bad = h.delta(
            slo.metric, dict(slo.labels or {}) or None,
            window_s=window_s, now=now,
        )
        total = h.delta(
            slo.total_metric,
            dict(slo.total_labels or slo.labels or {}) or None,
            window_s=window_s, now=now,
        )
        if slo.kind == "availability":
            # sheds never reach the request counter: the offered load
            # is admitted + shed
            total += bad
        if total <= 0:
            return None
        return max(0.0, min(1.0, bad / total))

    # -- evaluation ---------------------------------------------------

    def evaluate(self, now: float | None = None) -> list[Verdict]:
        now = time.time() if now is None else float(now)
        verdicts: list[Verdict] = []
        onsets: list[Verdict] = []
        for slo in self.slos:
            bf = self._bad_fraction(slo, slo.fast_window_s, now)
            bs = self._bad_fraction(slo, slo.slow_window_s, now)
            burn_fast = 0.0 if bf is None else bf / slo.budget
            burn_slow = 0.0 if bs is None else bs / slo.budget
            breached = burn_fast >= slo.fast_burn and burn_slow >= slo.slow_burn
            self._g_burn.set(burn_fast, slo=slo.name, window="fast")
            self._g_burn.set(burn_slow, slo=slo.name, window="slow")
            v = Verdict(
                slo=slo.name,
                kind=slo.kind,
                breached=breached,
                burn_fast=round(burn_fast, 4),
                burn_slow=round(burn_slow, 4),
                bad_fraction_fast=None if bf is None else round(bf, 6),
                budget=slo.budget,
                objective=slo.objective,
            )
            verdicts.append(v)
            with self._lock:
                was = self._breached.get(slo.name, False)
                self._breached[slo.name] = breached
            if breached and not was:
                self._m_breach.inc(slo=slo.name)
                flightrec.note(
                    "slo_breach",
                    slo=slo.name,
                    slo_kind=slo.kind,
                    burn_fast=v.burn_fast,
                    burn_slow=v.burn_slow,
                    budget=slo.budget,
                )
                onsets.append(v)
        with self._lock:
            self._last = list(verdicts)
            self._evals += 1
        for v in onsets:
            # a breach onset is an incident: persist the black box —
            # on a daemon thread, the dump's IO must not sit on the
            # evaluation (often a request-path pump) thread
            threading.Thread(
                target=flightrec.dump_now,
                args=(f"slo_breach:{v.slo}",),
                daemon=True,
            ).start()
        return verdicts

    # -- read surface -------------------------------------------------

    def last_verdicts(self) -> list[Verdict]:
        with self._lock:
            return list(self._last)

    def breaching(self) -> list[str]:
        """Names of SLOs currently in breach (last evaluation)."""
        with self._lock:
            return sorted(k for k, v in self._breached.items() if v)

    def statusz(self) -> dict[str, Any]:
        """The JSON block serve_model ``/statusz`` exposes."""
        with self._lock:
            last = list(self._last)
            evals = self._evals
        return {
            "evaluations": evals,
            "breaching": sorted(
                v.slo for v in last if v.breached
            ),
            "slos": [v.as_dict() for v in last],
        }
