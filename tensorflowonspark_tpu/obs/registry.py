"""Metrics registry: counters/gauges/histograms + Prometheus text export.

The reference had no metrics pipeline of its own (SURVEY.md §5.5) and
the rebuild's ``utils.metrics.MetricsWriter`` only pushed step scalars
to TensorBoard/JSONL. This module is the missing pull side: components
register named metrics once, mutate them cheaply from any thread, and
any HTTP surface (the serving engine's ``/metrics``, each node
runtime's metrics port) renders the registry in Prometheus text
exposition format 0.0.4 on demand — no scrape, no dependency, ~200
lines of stdlib.

Design points:

- **Label support** is per-observation keyword args
  (``c.inc(phase="fetch")``); each distinct label set is one time
  series, rendered sorted so output is deterministic (golden-testable).
- **Collectors**: a component whose values live elsewhere (engine slot
  occupancy, queue depth) registers a callback that refreshes its
  gauges at render time instead of on every mutation.
- **One system, not two**: ``utils.metrics.MetricsWriter`` is a *sink*
  of this registry — :meth:`Registry.publish` snapshots every series
  into ``writer.scalar`` calls (TensorBoard/JSONL), and legacy
  ``writer.scalar`` calls mirror into the registry as gauges, so the
  push (TB) and pull (Prometheus) views can never diverge.
- A process-global :func:`default_registry` serves the common case;
  components needing isolation (several engines in one test process)
  construct their own :class:`Registry` and render both.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Any, Callable, Iterable

__all__ = [
    "CONTENT_TYPE",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "default_registry",
    "sanitize_name",
]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Default histogram buckets, in seconds — spans the ~ms device steps to
# the multi-second tail a wedged host path produces.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    """Coerce an arbitrary scalar name (``loss/train``, ``lr.decay``)
    into a valid Prometheus metric name."""
    name = _BAD_CHARS.sub("_", name)
    if not name or not _NAME_RE.match(name):
        name = "_" + name
    return name


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="' + v.replace("\\", r"\\").replace('"', r"\"")
        .replace("\n", r"\n") + '"'
        for k, v in labels
    )
    return "{" + inner + "}"


def _label_key(labels: dict[str, Any]) -> tuple[tuple[str, str], ...]:
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: dict[tuple, Any] = {}  # guarded-by: self._lock

    def _render_series(self) -> "Iterable[str]":  # pragma: no cover
        raise NotImplementedError

    def render(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        lines.extend(self._render_series())
        return lines


class Counter(_Metric):
    """Monotonically increasing count (requests, tokens, errors)."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels: Any) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up, got {amount}")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: Any) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)

    def _render_series(self):
        with self._lock:
            items = sorted(self._series.items())
        return [
            f"{self.name}{_label_str(k)} {_fmt(v)}" for k, v in items
        ] or [f"{self.name} 0"]


class Gauge(_Metric):
    """A value that goes up and down (queue depth, slots busy, loss)."""

    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1, **labels: Any) -> None:
        self.inc(-amount, **labels)

    def remove(self, **labels: Any) -> None:
        """Drop one labelled series — a departed cluster member's gauge
        must not keep reporting its last value forever."""
        with self._lock:
            self._series.pop(_label_key(labels), None)

    def value(self, **labels: Any) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)

    def _render_series(self):
        with self._lock:
            items = sorted(self._series.items())
        return [
            f"{self.name}{_label_str(k)} {_fmt(v)}" for k, v in items
        ] or [f"{self.name} 0"]


class Histogram(_Metric):
    """Cumulative-bucket histogram (the Prometheus convention: each
    ``_bucket{le=...}`` counts observations <= its bound, ``+Inf``
    equals ``_count``). Percentiles are the scraper's job; in-process
    percentile views come from ``obs.spans`` instead."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs or any(
            not math.isfinite(b) for b in bs
        ):
            raise ValueError(f"invalid histogram buckets {buckets!r}")
        self.buckets = bs

    def observe(self, value: float, **labels: Any) -> None:
        if "le" in labels:
            raise ValueError(
                "histogram label 'le' is reserved for bucket bounds"
            )
        key = _label_key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = {
                    "counts": [0] * len(self.buckets),
                    "sum": 0.0,
                    "count": 0,
                }
            for i, b in enumerate(self.buckets):
                if value <= b:
                    series["counts"][i] += 1
            series["sum"] += value
            series["count"] += 1

    def value(self, **labels: Any) -> dict | None:
        """The raw ``{counts, sum, count}`` for one label set."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            return None if s is None else {
                "counts": list(s["counts"]),
                "sum": s["sum"],
                "count": s["count"],
            }

    def _render_series(self):
        with self._lock:
            items = sorted(
                (k, dict(v, counts=list(v["counts"])))
                for k, v in self._series.items()
            )
        lines = []
        for key, s in items:
            for b, c in zip(self.buckets, s["counts"]):
                lines.append(
                    f"{self.name}_bucket"
                    f"{_label_str(key + (('le', _fmt(b)),))} {c}"
                )
            lines.append(
                f"{self.name}_bucket"
                f"{_label_str(key + (('le', '+Inf'),))} {s['count']}"
            )
            lines.append(
                f"{self.name}_sum{_label_str(key)} {_fmt(s['sum'])}"
            )
            lines.append(
                f"{self.name}_count{_label_str(key)} {s['count']}"
            )
        return lines


class Registry:
    """Named metrics + render-time collectors; get-or-create semantics
    so call sites don't coordinate registration order."""

    #: Series suffixes a histogram family owns in the exposition. A
    #: plain metric named ``foo_bucket`` beside a histogram ``foo``
    #: would render two samples of the same name — promtool rejects
    #: that, and a scraper silently keeps whichever it parsed last.
    _HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}  # guarded-by: self._lock
        self._collectors: list[Callable[[], None]] = []  # guarded-by: self._lock
        # previous window() snapshot, keyed (name, label key)
        self._window_prev: dict[tuple, Any] = {}  # guarded-by: self._lock

    def _check_collision(self, name: str, cls) -> None:  # lint: holds-lock
        # callers (_get_or_create) hold self._lock
        if cls is Histogram:
            for suf in self._HISTOGRAM_SUFFIXES:
                if name + suf in self._metrics:
                    raise ValueError(
                        f"histogram {name!r} would collide with existing "
                        f"metric {name + suf!r} (histograms own the "
                        f"_bucket/_sum/_count series names)"
                    )
        for suf in self._HISTOGRAM_SUFFIXES:
            if name.endswith(suf):
                base = name[: -len(suf)]
                if isinstance(self._metrics.get(base), Histogram):
                    raise ValueError(
                        f"metric {name!r} collides with histogram "
                        f"{base!r}'s {suf} series"
                    )

    def _get_or_create(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                self._check_collision(name, cls)
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            elif cls is Histogram and "buckets" in kw:
                want = tuple(sorted(float(b) for b in kw["buckets"]))
                if want != m.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}, not {want} — two call "
                        "sites disagreeing would silently share one "
                        "bucket layout"
                    )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def add_collector(self, fn: Callable[[], None]) -> None:
        """Register a callback run at the top of every :meth:`render`
        (refresh gauges whose truth lives elsewhere). Exceptions are
        swallowed — a broken collector must not take down the scrape."""
        with self._lock:
            self._collectors.append(fn)

    def remove_collector(self, fn: Callable[[], None]) -> None:
        """Unregister a collector added with :meth:`add_collector` (a
        no-op when absent) — components with a bounded lifetime (a
        cluster handle on the process-global registry) must detach on
        shutdown or every render keeps refreshing stale gauges."""
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return [m for _, m in sorted(self._metrics.items())]

    def render(self) -> str:
        """Prometheus text exposition (0.0.4); deterministic ordering
        (metrics by name, series by sorted label sets)."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass
        lines: list[str] = []
        for m in self.metrics():
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def publish(self, writer, step: int) -> None:
        """Snapshot every series into ``writer.scalar(name, value,
        step)`` — the bridge that makes ``MetricsWriter`` (TensorBoard /
        JSONL) a *sink* of this registry. Counters and gauges publish
        their value per label set (labels suffixed ``name{k=v}``);
        histograms publish ``_count`` and ``_sum``. ``mirror=False``
        stops the writer echoing the scalars back into a registry."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass
        for m in self.metrics():
            if isinstance(m, (Counter, Gauge)):
                with m._lock:
                    items = sorted(m._series.items())
                for key, v in items:
                    writer.scalar(
                        m.name + _label_str(key), v, step, mirror=False
                    )
            elif isinstance(m, Histogram):
                with m._lock:
                    items = sorted(m._series.items())
                for key, s in items:
                    base = m.name + _label_str(key)
                    writer.scalar(
                        base + "_count", s["count"], step, mirror=False
                    )
                    writer.scalar(
                        base + "_sum", s["sum"], step, mirror=False
                    )

    def window(self) -> dict[str, dict[str, Any]]:
        """Windowed read API: every series' current value plus its
        change since the PREVIOUS ``window()`` call — the shape a
        feedback controller wants ("how much feed.data_wait accrued
        this window"), without the controller keeping its own
        per-series bookkeeping.

        Returns ``{name: {"kind": ..., "series": {label_str: entry}}}``
        where ``label_str`` is the rendered ``{k="v",...}`` label set
        (``""`` for the unlabeled series). Counter/gauge entries are
        ``{"value", "delta"}``; histogram entries are ``{"count",
        "sum", "delta_count", "delta_sum"}`` (windowed mean latency =
        ``delta_sum / delta_count``) plus the cumulative bucket view —
        ``"le"`` (finite bucket bounds), ``"buckets"`` (cumulative
        counts per bound; ``count`` is the implicit ``+Inf``), and
        ``"delta_buckets"`` — so a consumer (``obs.history``) can
        derive windowed percentiles. The first call's deltas equal the
        values (window start = registry birth). Collectors run first,
        like :meth:`render`.
        """
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass
        out: dict[str, dict[str, Any]] = {}
        with self._lock:
            for name, m in sorted(self._metrics.items()):
                series: dict[str, Any] = {}
                with m._lock:
                    items = [
                        (k, (v["count"], v["sum"], list(v["counts"])))
                        if isinstance(m, Histogram)
                        else (k, v)
                        for k, v in sorted(m._series.items())
                    ]
                for key, v in items:
                    wkey = (name, key)
                    if isinstance(m, Histogram):
                        cnt, tot, buckets = v
                        prev = self._window_prev.get(
                            wkey, (0, 0.0, [0] * len(buckets))
                        )
                        # pre-extension windows stored (count, sum) only
                        prev_b = (
                            prev[2]
                            if len(prev) > 2
                            else [0] * len(buckets)
                        )
                        entry = {
                            "count": cnt,
                            "sum": tot,
                            "delta_count": cnt - prev[0],
                            "delta_sum": tot - prev[1],
                            "le": list(m.buckets),
                            "buckets": buckets,
                            "delta_buckets": [
                                b - p for b, p in zip(buckets, prev_b)
                            ],
                        }
                        self._window_prev[wkey] = (cnt, tot, buckets)
                    else:
                        prev_v = self._window_prev.get(wkey, 0.0)
                        entry = {"value": v, "delta": v - prev_v}
                        self._window_prev[wkey] = v
                    series[_label_str(key)] = entry
                out[name] = {"kind": m.kind, "series": series}
        return out


_default = Registry()


def default_registry() -> Registry:
    return _default
