"""Unified observability: spans, metrics, trace attribution.

Three pillars, one package (round-5 verdict: the stack could build fast
paths but not *see* them):

- :mod:`~tensorflowonspark_tpu.obs.spans` — host-side span tracer
  (ring buffer, Chrome-trace export, percentile summaries) that bridges
  into ``jax.profiler`` annotations so host phases and XLA ops share a
  timeline. Wired into the serving engine's request phases and the
  train/feed hot paths.
- :mod:`~tensorflowonspark_tpu.obs.registry` — counters/gauges/
  histograms with a Prometheus text exporter, served at ``/metrics``
  by the HTTP server and each node runtime;
  ``utils.metrics.MetricsWriter`` is a sink of the registry
  (``Registry.publish``), not a parallel system.
- :mod:`~tensorflowonspark_tpu.obs.trace_report` — nesting-aware
  self-time over captured profiler traces plus an op classifier
  (MXU / vector / copy / infeed / collective / host), emitted as a
  JSON artifact by ``bench.py --trace`` and readable via
  ``python -m tensorflowonspark_tpu.tools.trace_report``.

Plus the cluster-wide plane (docs/OBSERVABILITY.md):

- :mod:`~tensorflowonspark_tpu.obs.cluster` — run-scoped trace
  context, heartbeat clock sync, Prometheus text parsing, and the
  driver-side :class:`MetricsAggregator` behind
  ``TFCluster.cluster_stats()`` and the driver ``/metrics`` endpoint.
- :mod:`~tensorflowonspark_tpu.obs.flightrec` — per-process failure
  flight recorder (rolling snapshots + event-triggered dumps).
- :mod:`~tensorflowonspark_tpu.obs.trace_merge` — clock-aligned merge
  of driver + node traces into one timeline (``tools/trace_merge.py``).

And the request-level plane (ISSUE 16, docs/OBSERVABILITY.md):

- :mod:`~tensorflowonspark_tpu.obs.reqtrace` — per-request distributed
  tracing with tail-sampled retention (``X-TFOS-Trace`` propagation,
  ``GET /debugz/trace/<id>``).
- :mod:`~tensorflowonspark_tpu.obs.history` — bounded windowed
  time-series rings over metric scrapes (rates, percentiles, JSONL
  spill) — the autotune controller's read substrate.
- :mod:`~tensorflowonspark_tpu.obs.slo` — declarative SLOs with
  multi-window burn-rate evaluation over History.
- :mod:`~tensorflowonspark_tpu.obs.snapshot` — one-command incident
  bundle (``tools/obs_snapshot.py``).
"""

from tensorflowonspark_tpu.obs.history import History
from tensorflowonspark_tpu.obs.registry import (
    CONTENT_TYPE,
    Registry,
    default_registry,
    sanitize_name,
)
from tensorflowonspark_tpu.obs.slo import SLO, SLOEvaluator
from tensorflowonspark_tpu.obs.spans import (
    SpanTracer,
    get_tracer,
    record,
    span,
    step_span,
    traced,
)

__all__ = [
    "CONTENT_TYPE",
    "History",
    "Registry",
    "SLO",
    "SLOEvaluator",
    "SpanTracer",
    "default_registry",
    "get_tracer",
    "record",
    "sanitize_name",
    "span",
    "step_span",
    "traced",
]
