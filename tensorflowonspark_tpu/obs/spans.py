"""Host-side span tracing: where does the host's time go, per phase.

The device side already has a first-class story (``jax.profiler.trace``
→ ``obs.trace_report``); what the stack lacked was the HOST side — queue
waits, batch formation, dispatch, fetches — the glue the round-5 verdict
could only hand-wave about ("57× latency tax ≈ host RPCs"). A
:class:`SpanTracer` records named intervals into a thread-safe ring
buffer with microsecond timestamps, cheap enough to leave on in
production hot paths (one ``perf_counter`` pair + a deque append per
span; no allocation beyond the span tuple).

Three consumption surfaces, one recording API:

- **Percentiles in-process**: :meth:`SpanTracer.summary` aggregates the
  ring buffer per span name (count/p50/p90/p99/total) — what the
  serving engine's ``/stats`` serves per request phase.
- **Chrome trace export**: :meth:`SpanTracer.export` /
  :meth:`write_chrome_trace` emit standard ``traceEvents`` JSON
  (``ph: "X"`` complete events, per-thread lanes) that
  ``obs.trace_report`` — and chrome://tracing / Perfetto — read
  directly.
- **XLA timeline bridge**: every span body also runs under
  ``jax.profiler.TraceAnnotation`` (and :meth:`step_span` under
  ``StepTraceAnnotation``), so when a device trace is active the host
  spans land on the SAME timeline as the XLA ops. When jax is absent or
  no trace is active these are no-ops costing one TraceMe call.

Usage::

    from tensorflowonspark_tpu.obs import spans

    with spans.span("engine.dispatch", rows=8):
        out = step_fn(...)

    @spans.traced("feed.columnize")
    def columnize(...): ...

    spans.get_tracer().summary(prefix="engine.")
"""

from __future__ import annotations

import functools
import gzip
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable

__all__ = [
    "Span",
    "SpanTracer",
    "get_tracer",
    "span",
    "step_span",
    "record",
    "traced",
    "summary",
]

_CLOCK = time.perf_counter

# jax.profiler resolved lazily and at most once: obs must import (and
# record) fine in processes that never touch jax, and the bridge must
# not pay an import-attempt per span.
_UNSET = object()
_PROF: Any = _UNSET


def _profiler():
    global _PROF
    if _PROF is _UNSET:
        try:
            from jax import profiler as _p  # noqa: PLC0415

            _PROF = _p
        except Exception:  # pragma: no cover - jax is present in CI
            _PROF = None
    return _PROF


class Span(tuple):
    """One recorded interval: ``(name, ts, dur, tid, thread_name, args)``
    with ``ts``/``dur`` in seconds on the tracer's monotonic clock.
    ``tid`` is the recording thread's ident for call-stack spans, or a
    synthetic ``"interval:<name>"`` lane id for :meth:`SpanTracer.record`
    intervals (which don't nest with any thread's call stack)."""

    __slots__ = ()
    name = property(lambda s: s[0])
    ts = property(lambda s: s[1])
    dur = property(lambda s: s[2])
    tid = property(lambda s: s[3])
    thread_name = property(lambda s: s[4])
    args = property(lambda s: s[5])


class _SpanCtx:
    """Context manager for one open span; also usable as a decorator via
    :func:`traced`. Enters a ``jax.profiler`` annotation so the span
    shows on the device timeline when a trace is active."""

    __slots__ = ("_tracer", "_name", "_args", "_t0", "_ann", "_step_num")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict,
                 step_num: int | None = None):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._step_num = step_num
        self._ann = None

    def set(self, **args: Any) -> None:
        """Attach args discovered DURING the span body (the consumer
        pull learns the frame's ``stream``/``seq`` only after the
        blocking get returns) — they land on the recorded span like
        ctor args, so cross-process trace stitching can key on them."""
        self._args.update(args)

    def __enter__(self) -> "_SpanCtx":
        prof = _profiler()
        if prof is not None:
            try:
                if self._step_num is not None:
                    ann = prof.StepTraceAnnotation(
                        self._name, step_num=self._step_num
                    )
                else:
                    ann = prof.TraceAnnotation(self._name)
                ann.__enter__()
                self._ann = ann
            except Exception:  # annotation is best-effort observability
                self._ann = None
        self._t0 = _CLOCK()
        return self

    def __exit__(self, *exc) -> None:
        dur = _CLOCK() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        self._tracer._append(self._name, self._t0, dur, self._args)


class SpanTracer:
    """Thread-safe ring buffer of completed spans.

    ``capacity`` bounds memory: the buffer holds the most recent spans
    (older ones are silently dropped — ``recorded`` keeps the lifetime
    count, so ``recorded - len(spans())`` is the drop count). All
    methods are safe to call from any thread; recording takes one lock
    around a deque append.
    """

    def __init__(self, capacity: int = 8192):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._buf: "deque[Span]" = deque(maxlen=int(capacity))  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._epoch = _CLOCK()
        # lifetime spans, including dropped ones
        self.recorded = 0  # guarded-by: self._lock

    # -- recording -----------------------------------------------------

    def span(self, name: str, **args: Any) -> _SpanCtx:
        """Context manager measuring its body as one span."""
        return _SpanCtx(self, name, args)

    def step_span(self, name: str, step_num: int, **args: Any) -> _SpanCtx:
        """Like :meth:`span`, but bridges to
        ``jax.profiler.StepTraceAnnotation`` so an active device trace
        groups the device ops under this step number (the per-step
        attribution the profiler UI keys on)."""
        return _SpanCtx(self, name, dict(args, step=step_num), step_num)

    def record(self, name: str, dur: float, ts: float | None = None,
               **args: Any) -> None:
        """Record an already-measured interval of ``dur`` seconds ending
        now (or starting at monotonic ``ts``) — for durations measured
        elsewhere, e.g. a request's queue wait stamped at enqueue.

        The interval lands on a synthetic per-name lane
        (``tid="interval:<name>"``), NOT the calling thread's lane: a
        backdated interval (a ~1s queue wait recorded at admission time)
        would otherwise span real call-stack spans the same thread
        recorded in the meantime without properly nesting them, and
        nesting-aware consumers (``obs.trace_report.self_times``) would
        subtract those spans from it — producing negative self time.
        ``summary()`` percentiles key on name only and are identical
        either way.
        """
        t_start = (_CLOCK() - dur) if ts is None else ts
        self._append(
            name, t_start, dur, args,
            tid=f"interval:{name}",
            thread_name=f"intervals: {name}",
        )

    def traced(self, name: str | None = None) -> Callable:
        """Decorator: run the function body under a span (default name:
        the function's qualified name)."""

        def deco(fn):
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def inner(*a, **kw):
                with self.span(span_name):
                    return fn(*a, **kw)

            return inner

        return deco

    def _append(self, name: str, ts: float, dur: float, args: dict,
                tid: Any = None, thread_name: str | None = None) -> None:
        if tid is None:
            t = threading.current_thread()
            tid, thread_name = t.ident, t.name
        s = Span((name, ts, dur, tid, thread_name, args or None))
        with self._lock:
            self._buf.append(s)
            self.recorded += 1

    # -- consumption ---------------------------------------------------

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def summary(self, prefix: str = "") -> dict[str, dict[str, float]]:
        """Aggregate the buffered spans per name (optionally filtered by
        ``prefix``): ``{name: {count, total_ms, p50_ms, p90_ms,
        p99_ms, max_ms}}``. Percentiles are nearest-rank over whatever
        the ring currently holds — a sliding window by construction."""
        by_name: dict[str, list[float]] = {}
        for s in self.spans():
            if s.name.startswith(prefix):
                by_name.setdefault(s.name, []).append(s.dur)
        out: dict[str, dict[str, float]] = {}
        for name, durs in sorted(by_name.items()):
            durs.sort()
            n = len(durs)

            def pct(p: float) -> float:
                return durs[min(n - 1, int(p * n))]

            out[name] = {
                "count": n,
                "total_ms": round(sum(durs) * 1e3, 3),
                "p50_ms": round(pct(0.50) * 1e3, 3),
                "p90_ms": round(pct(0.90) * 1e3, 3),
                "p99_ms": round(pct(0.99) * 1e3, 3),
                "max_ms": round(durs[-1] * 1e3, 3),
            }
        return out

    def export(self, process_name: str | None = None) -> dict:
        """The buffer as a Chrome-trace dict (``{"traceEvents": [...]}``,
        ``ts``/``dur`` in microseconds relative to the tracer epoch) —
        the format ``obs.trace_report`` and chrome://tracing read."""
        pid = os.getpid()
        # Cross-process alignment metadata: the wall-clock time of this
        # tracer's epoch (event ts are relative to it), plus the run's
        # trace id / node name / clock-offset estimate when the cluster
        # trace context is set (obs.cluster) — what tools/trace_merge.py
        # keys on to put N processes' spans on ONE timeline.
        ctx_args: dict[str, Any] = {
            "epoch_unix": time.time() - (_CLOCK() - self._epoch),
        }
        try:
            from tensorflowonspark_tpu.obs import cluster as _obs_cluster

            ctx_args.update(_obs_cluster.export_meta())
        except Exception:  # trace context is best-effort metadata
            pass
        events: list[dict] = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "args": {
                    "name": process_name or f"host: pid {pid}"
                },
            },
            {
                "ph": "M",
                "name": "trace_context",
                "pid": pid,
                "args": ctx_args,
            },
        ]
        seen_tids: set = set()
        for s in self.spans():
            if s.tid not in seen_tids:
                seen_tids.add(s.tid)
                events.append(
                    {
                        "ph": "M",
                        "name": "thread_name",
                        "pid": pid,
                        "tid": s.tid,
                        "args": {"name": s.thread_name},
                    }
                )
            ev = {
                "ph": "X",
                "pid": pid,
                "tid": s.tid,
                "name": s.name,
                "ts": round((s.ts - self._epoch) * 1e6, 3),
                "dur": round(s.dur * 1e6, 3),
            }
            if s.args:
                ev["args"] = dict(s.args)
            events.append(ev)
        return {"traceEvents": events}

    def write_chrome_trace(
        self, path: str, process_name: str | None = None
    ) -> str:
        """Write :meth:`export` as JSON (gzipped when the path ends in
        ``.gz``); returns the path."""
        data = self.export(process_name)
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "wt") as f:
            json.dump(data, f)
        return path


# Process-global default tracer: hot paths (engine, feed, train step)
# record here so one export/summary sees the whole process. Components
# that need isolated percentile windows (one engine instance among
# several) construct their own SpanTracer.
_default = SpanTracer()


def get_tracer() -> SpanTracer:
    return _default


def span(name: str, **args: Any) -> _SpanCtx:
    return _default.span(name, **args)


def step_span(name: str, step_num: int, **args: Any) -> _SpanCtx:
    return _default.step_span(name, step_num, **args)


def record(name: str, dur: float, ts: float | None = None, **args) -> None:
    _default.record(name, dur, ts, **args)


def traced(name: str | None = None) -> Callable:
    return _default.traced(name)


def summary(prefix: str = "") -> dict[str, dict[str, float]]:
    return _default.summary(prefix)
