"""Trace attribution: where a captured profiler trace's time goes.

Promoted from ``benchmarks/trace_summary.py`` (which remains as an
import shim): the profiler (``benchmarks/real_chip.py --profile DIR``,
``bench.py --trace``, or any ``jax.profiler.trace``) writes a
TensorBoard-readable run under ``DIR/plugins/profile/<run>/`` containing
a Chrome-trace export ``*.trace.json.gz``. TensorBoard isn't part of
this environment's loop, so this module answers the questions the trace
was captured for directly:

1. **Self time** (:func:`self_times`): per-(lane, op) nesting-aware
   durations — events that overlap hierarchically within one thread
   (XLA module > fusion > op) would double-count if summed naively, so
   each event's self time subtracts its nested children.
2. **Attribution** (:func:`attribution`): every op classified into
   MXU/matmul, vector/fusion, copy/layout, infeed/outfeed, collective,
   or host — the breakdown that turns "58.1% MFU with a 42% non-MXU
   residual" from a mystery into a table (which round 5 could not
   produce; VERDICT.md).
3. **Report artifact** (:func:`build_report` / :func:`write_report`):
   one JSON dict with lane totals, top ops, and the attribution table —
   what ``bench.py`` commits under ``benchmarks/results/`` on every
   traced run so build-but-don't-measure is structurally impossible.

CLI (also exposed as ``python -m tensorflowonspark_tpu.tools.trace_report``)::

    python -m tensorflowonspark_tpu.tools.trace_report /tmp/profile \
        [--top 30] [--lane TPU] [--json report.json]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import warnings

__all__ = [
    "find_trace_files",
    "load_events",
    "self_times",
    "classify_op",
    "is_device_lane",
    "attribution",
    "build_report",
    "write_report",
    "main",
]

# Classifier categories, in report order. Patterns target XLA/HLO op
# names as they appear in trace event names (``fusion.123``,
# ``%dot.45``, ``copy-start``, ``all-reduce.7``, ``infeed`` ...); the
# first matching category wins, so transfer/copy names are tested
# before the broad vector fallback. ``weight_update`` is tested first
# of all: ops lowered under the train step's
# ``jax.named_scope("train.weight_update")`` (the optimizer update —
# Adam moments, masters, and the ZeRO reduce-scatter/all-gather pair)
# carry the scope in their metadata-derived names, and the optimizer
# fraction of step time is exactly what the ``bench.py --zero`` A/B
# reads out of a committed ``*_trace_report.json``.
CATEGORIES = (
    "weight_update", "mxu", "vector", "copy", "infeed", "collective",
    "host",
)

_PATTERNS = (
    # the train step's optimizer scope (see compute/train.make_step_fn)
    ("weight_update", re.compile(r"train\.weight_update", re.I)),
    # device-to-device / host-device data movement and layout changes
    ("infeed", re.compile(
        r"infeed|outfeed|host-to-device|device-to-host|"
        r"\btransfer|send|recv", re.I)),
    ("collective", re.compile(
        r"all-reduce|all-gather|reduce-scatter|all-to-all|"
        r"collective|ppermute|permute", re.I)),
    ("mxu", re.compile(
        r"\bdot\b|dot[._-]|conv(?:olution)?[._-]|\bconv\b|einsum|"
        r"matmul|\bgemm\b|cublas|mxu", re.I)),
    ("copy", re.compile(
        r"copy|transpose|bitcast|reshape|broadcast|concatenate|"
        r"\bslice\b|slice[._-]|dynamic-slice|dynamic-update-slice|"
        r"\bpad\b|pad[._-]|gather[._-]|\bgather\b|scatter", re.I)),
)


def classify_op(name: str, device: bool = True) -> str:
    """Category for one op name. Host-lane events are all ``host`` —
    attribution contrasts device-side MXU vs residual against host
    glue, not host function names against each other."""
    if not device:
        return "host"
    for cat, pat in _PATTERNS:
        if pat.search(name):
            return cat
    return "vector"


def is_device_lane(lane_name: str) -> bool:
    """Heuristic over trace process-lane names: TPU/GPU/XLA device
    lanes hold op activity; everything else (python, TSL, plugins) is
    host."""
    n = lane_name.lower()
    return any(
        key in n for key in ("/device:", "tpu", "gpu", "xla:", "stream")
    ) and "host" not in n


def find_trace_files(root: str) -> list[str]:
    pats = [
        os.path.join(root, "**", "*.trace.json.gz"),
        os.path.join(root, "**", "*.trace.json"),
        # flight-recorder dumps (obs.flightrec) embed a trace export
        os.path.join(root, "**", "flightrec-*.json"),
    ]
    out: list[str] = []
    for p in pats:
        out.extend(glob.glob(p, recursive=True))
    return sorted(out)


def resolve_inputs(paths) -> list[str]:
    """Expand a path — or a list of paths — into trace files: a
    directory contributes every trace/flightrec file under it, a file
    is taken as-is. Order is deterministic (input order, dirs sorted
    within)."""
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(find_trace_files(p))
        else:
            out.append(p)
    return out


def load_events(path: str) -> dict:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        data = json.load(f)
    if "traceEvents" not in data and isinstance(data.get("spans"), dict):
        # flight-recorder dump (obs.flightrec): the span export is the
        # trace — per-node postmortems read like any captured profile
        data = data["spans"]
    return data


def self_times(events: list[dict]) -> "collections.Counter[tuple]":
    """Per-(pid, tid) nesting-aware self time, keyed by (pid, name).

    Chrome-trace complete events within one thread nest like a call
    stack. Sort by (start, -dur); maintain a stack of open intervals; an
    event's self time is its duration minus the portions of its direct
    children that fall INSIDE it.

    Real call stacks nest strictly. Events that only PARTIALLY overlap
    violate that model; naively subtracting each child's full duration
    then yields negative self time, which a summed report silently
    launders into plausible-looking wrong totals. So: a child only
    charges its parent for the overlapping portion, per-event self time
    is clamped at zero, and detection of non-nested overlap raises a
    ``RuntimeWarning`` — the trace is malformed and its attribution is
    approximate. Lanes holding externally-measured intervals
    (``tid="interval:<name>"``, from ``obs.spans`` ``record()``) are
    not call stacks at all: they skip nesting attribution and each
    event simply owns its full duration.
    """
    per_thread: dict = collections.defaultdict(list)
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        per_thread[(e.get("pid"), e.get("tid"))].append(e)

    self_us: "collections.Counter[tuple]" = collections.Counter()
    non_nested = 0
    for (pid, tid), evs in per_thread.items():
        if isinstance(tid, str) and tid.startswith("interval:"):
            # externally-measured intervals (``SpanTracer.record``):
            # independent durations, not a call stack — concurrent
            # requests' queue waits overlap freely and each owns its
            # full duration; nesting attribution does not apply
            for e in evs:
                self_us[(pid, e["name"])] += e["dur"]
            continue
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list[dict] = []  # open events, each with _child_us accumulator
        for e in evs:
            ts, dur = e["ts"], e["dur"]
            while stack and ts >= stack[-1]["_end"]:
                done = stack.pop()
                self_us[(pid, done["name"])] += max(
                    0, done["dur"] - done["_child_us"]
                )
            if stack:
                inside = min(ts + dur, stack[-1]["_end"]) - ts
                if inside < dur:
                    non_nested += 1
                stack[-1]["_child_us"] += max(0, inside)
            e = dict(e, _child_us=0, _end=ts + dur)
            stack.append(e)
        while stack:
            done = stack.pop()
            self_us[(pid, done["name"])] += max(
                0, done["dur"] - done["_child_us"]
            )
    if non_nested:
        warnings.warn(
            f"{non_nested} trace event(s) overlap a same-lane event "
            "without nesting inside it (call-stack events must nest "
            "strictly); self-time attribution clamped the overlap — "
            "treat per-op self times on the affected lanes as "
            "approximate",
            RuntimeWarning,
            stacklevel=2,
        )
    return self_us


def lane_names(events: list[dict]) -> dict:
    """pid -> process lane name, from the trace's metadata events."""
    names: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[e.get("pid")] = e.get("args", {}).get("name", "")
    return names


def attribution(
    self_us: "collections.Counter[tuple]", pid_names: dict
) -> dict:
    """Classify per-op self time into the category table.

    Returns ``{"categories": {cat: {"us": int, "pct": float}},
    "device_total_us": int, "host_total_us": int,
    "mxu_fraction": float}`` where ``pct`` and ``mxu_fraction`` are
    relative to DEVICE self time (the MFU question); host time is
    reported beside it, not mixed in.
    """
    cat_us: "collections.Counter[str]" = collections.Counter()
    device_total = 0
    host_total = 0
    for (pid, name), us in self_us.items():
        device = is_device_lane(pid_names.get(pid, str(pid)))
        cat = classify_op(name, device=device)
        cat_us[cat] += us
        if device:
            device_total += us
        else:
            host_total += us
    cats = {
        c: {
            "us": int(cat_us.get(c, 0)),
            "pct": round(
                100.0 * cat_us.get(c, 0) / device_total, 2
            )
            if device_total and c != "host"
            else (0.0 if c != "host" else None),
        }
        for c in CATEGORIES
    }
    # host pct is relative to (device + host): "of all measured self
    # time, how much never touched the chip"
    total = device_total + host_total
    cats["host"]["pct"] = (
        round(100.0 * host_total / total, 2) if total else 0.0
    )
    return {
        "categories": cats,
        "device_total_us": int(device_total),
        "host_total_us": int(host_total),
        "mxu_fraction": (
            round(cat_us.get("mxu", 0) / device_total, 4)
            if device_total
            else 0.0
        ),
        # the optimizer fraction of device time — the number the ZeRO
        # cross-replica weight update (bench.py --zero) exists to shrink
        "weight_update_fraction": (
            round(cat_us.get("weight_update", 0) / device_total, 4)
            if device_total
            else 0.0
        ),
    }


def build_report(trace_dir, top: int = 30) -> dict:
    """Aggregate trace inputs into one report dict: per-file lanes +
    top ops by self time, and a combined attribution table.

    ``trace_dir`` is a directory (every trace/flightrec file under it),
    a single file, or a LIST of directories/files — one merged report
    over a driver trace plus N per-node flight-recorder dumps is
    ``build_report(["driver.trace.json", *glob("logs/flightrec-*")])``.
    Raises FileNotFoundError when no input resolves to a trace file
    (callers decide whether that's fatal)."""
    inputs = trace_dir if isinstance(trace_dir, (list, tuple)) else [trace_dir]
    files = resolve_inputs(inputs)
    if not files:
        raise FileNotFoundError(
            f"no *.trace.json[.gz] / flightrec-*.json under {inputs}"
        )
    first = str(inputs[0])
    rel_root = first if os.path.isdir(first) else os.path.dirname(first)
    combined: "collections.Counter[tuple]" = collections.Counter()
    combined_names: dict = {}
    file_reports = []
    for path in files:
        events = load_events(path).get("traceEvents", [])
        pid_names = lane_names(events)
        self_us = self_times(events)
        # pids can collide across files; prefix with the file index
        idx = len(file_reports)
        for (pid, name), us in self_us.items():
            combined[((idx, pid), name)] += us
        for pid, nm in pid_names.items():
            combined_names[(idx, pid)] = nm
        lane_total: "collections.Counter" = collections.Counter()
        for (pid, _name), us in self_us.items():
            lane_total[pid] += us
        lanes = []
        for pid, total in lane_total.most_common():
            ops = sorted(
                (
                    (n, us)
                    for (p, n), us in self_us.items()
                    if p == pid
                ),
                key=lambda kv: -kv[1],
            )
            lanes.append(
                {
                    "pid": pid,
                    "name": pid_names.get(pid, str(pid)),
                    "device": is_device_lane(
                        pid_names.get(pid, str(pid))
                    ),
                    "total_us": int(total),
                    "top_ops": [
                        {
                            "name": n,
                            "us": int(us),
                            "category": classify_op(
                                n,
                                device=is_device_lane(
                                    pid_names.get(pid, str(pid))
                                ),
                            ),
                        }
                        for n, us in ops[:top]
                    ],
                }
            )
        under_root = os.path.abspath(path).startswith(
            os.path.abspath(rel_root) + os.sep
        )
        file_reports.append(
            {
                "file": (
                    os.path.relpath(path, rel_root) if under_root else path
                ),
                "lanes": lanes,
            }
        )
    return {
        "trace_dir": os.path.abspath(first),
        "inputs": [str(p) for p in inputs],
        "files": file_reports,
        "attribution": attribution(combined, combined_names),
    }


def write_report(
    trace_dir: str, out_path: str, top: int = 30, report: dict | None = None
) -> dict:
    """Write the JSON report (building it from ``trace_dir`` unless a
    prebuilt ``report`` is passed — callers that already hold one must
    not re-parse the trace files); returns the report dict."""
    if report is None:
        report = build_report(trace_dir, top=top)
    parent = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(parent, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    return report


def _print_attribution(att: dict, out) -> None:
    print("\n== attribution (device self time)", file=out)
    for cat in CATEGORIES:
        row = att["categories"][cat]
        pct = row["pct"]
        pct_s = f"{pct:5.1f}%" if pct is not None else "     -"
        print(f"  {cat:<10} {row['us']/1e3:10.3f} ms  {pct_s}", file=out)
    print(
        f"  device total {att['device_total_us']/1e3:.3f} ms, host "
        f"total {att['host_total_us']/1e3:.3f} ms, MXU fraction "
        f"{att['mxu_fraction']:.3f}",
        file=out,
    )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="trace_report")
    ap.add_argument(
        "trace_dir",
        nargs="+",
        help="profile directory, trace file(s), and/or flight-recorder "
        "dump(s) — multiple inputs merge into one report",
    )
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument(
        "--lane",
        default=None,
        help="only lanes whose name contains this substring (e.g. 'TPU')",
    )
    ap.add_argument(
        "--json",
        default=None,
        help="also write the full report dict to this path",
    )
    args = ap.parse_args(argv)

    # Parse the (potentially tens-of-MB gzipped) trace files ONCE; the
    # lane tables, attribution, and --json artifact all print from the
    # same report dict.
    try:
        report = build_report(args.trace_dir, top=args.top)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 1

    for fr in report["files"]:
        print(f"== {fr['file']}")
        for lane in fr["lanes"]:
            if args.lane and args.lane.lower() not in lane["name"].lower():
                continue
            total = lane["total_us"]
            print(
                f"\n-- lane pid={lane['pid']} {lane['name']!r}: "
                f"total self-time {total/1e3:.2f} ms"
            )
            for op in lane["top_ops"]:
                pct = 100.0 * op["us"] / total if total else 0.0
                print(
                    f"  {op['us']/1e3:10.3f} ms  {pct:5.1f}%  "
                    f"{op['name'][:120]}"
                )

    _print_attribution(report["attribution"], sys.stdout)
    if args.json:
        write_report(args.trace_dir, args.json, report=report)
        print(f"report written to {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
