"""Failure flight recorder: the last N seconds, on disk, at death.

PR 4 made node death *detectable* in seconds (liveness plane), but the
postmortem still had nothing to read: a SIGKILLed node's span ring,
counters, and recent events died with the process, and the driver-side
diagnostic was one line ("node(s) [1] missed heartbeats"). This module
keeps a bounded in-memory record per process — recent spans (the
tracer's ring IS the bound), a metrics snapshot, and a small event log
— and persists it to ``logs/flightrec-<node>.json``:

- **Periodically** (node processes, on the heartbeat cadence): an
  atomic rolling snapshot, so even a SIGKILL — where the process gets
  no chance to say goodbye — leaves its last interval on disk.
- **On events**: the driver dumps when the liveness plane declares a
  node dead or ``supervise()`` triggers a relaunch; the serving
  engine dumps when its wedge watchdog fires; a node dumps when its
  ``map_fun`` ferries an exception.

Dumps embed the tracer's Chrome-trace export (with its
``trace_context`` metadata), so ``tools/trace_report.py`` and
``tools/trace_merge.py`` read them directly — a postmortem is one
``trace_merge logs/flightrec-*.json`` away from a cluster timeline.

Module-level :func:`install` / :func:`note` / :func:`dump_now` keep
call sites one line: hot paths ``note()`` unconditionally (a no-op
before install), and crash handlers ``dump_now(reason)``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any

from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.obs.registry import Registry, default_registry

logger = logging.getLogger(__name__)

__all__ = [
    "EVENTS",
    "FlightRecorder",
    "dump_now",
    "get",
    "install",
    "note",
]

FORMAT_VERSION = 1

#: The registered event-name catalog. Postmortem tooling greps dumps by
#: these exact strings, so ``note()`` call sites must use literals from
#: this set — lint rule OB002 (``analysis/flightrecnames.py``) parses
#: this assignment from disk (the FP001 pattern) and flags dynamic or
#: unregistered names at build time. Adding an event = add the literal
#: here, ``note()`` it at the site, document it in
#: docs/OBSERVABILITY.md. (``dump_now`` *reasons* are free-form — they
#: name why a dump was cut, not a queryable event stream.)
EVENTS = frozenset(
    {
        # cluster liveness / supervision (cluster/*)
        "node_start",
        "dead_node",
        "supervise_restart",
        "map_fun_error",
        "membership_epoch",
        # elastic reconfiguration (compute/elastic.py, cluster/tfcluster.py)
        "elastic_epoch_bump",
        "elastic_reconfigure",
        "elastic_reconfigure_failed",
        "elastic_hydrate",
        # ingest plane (feed/ingest.py, cluster/tfcluster.py)
        "ingest_plan",
        "ingest_plan_republish",
        "ingest_handover",
        # serving fleet (serving/*)
        "engine_watchdog",
        "fleet_shed",
        "fleet_drain",
        "replica_drain",
        "replica_respawn",
        "replica_dead",
        "replica_swap",
        "rollout_begin",
        "rollout_complete",
        "rollout_rollback",
        # disaggregated cache tier (cachetier/ + serving/fleet.py —
        # docs/SERVING.md "Cache tier"): daemon lifecycle and rollout
        # reclamation are the post-mortem surface for "why did the
        # fleet hit-rate fall off a cliff at 14:03"
        "cachetier_spawn",
        "cachetier_respawn",
        "cachetier_invalidate",
        # observability plane (obs/slo.py, utils/lockwitness.py)
        "slo_breach",
        "tfsan",
        # online knob tuning (autotune/ — docs/AUTOTUNE.md): every
        # controller move, every regression revert, and every freeze
        # (operator or SLO-breach back-off) is auditable after the fact
        "autotune_decision",
        "autotune_revert",
        "autotune_frozen",
        # online continual loop (feed/livelog.py + online.py — see
        # docs/ROBUSTNESS.md "Online continual loop"): every loop cycle
        # (manifests discovered, data age, lag), every sealed-segment
        # manifest publication, and every stall onset is auditable
        "online_cycle",
        "online_stall",
        "online_manifest_publish",
    }
)


class FlightRecorder:
    """Bounded per-process black box; :meth:`dump` writes it atomically.

    ``tracer``/``registry`` default to the process-global ones — the
    recorder does not re-instrument anything, it snapshots what the
    existing obs surfaces already hold. ``interval > 0`` enables the
    rolling-snapshot daemon (:meth:`start`).
    """

    def __init__(
        self,
        path: str,
        process: str = "proc",
        tracer: obs_spans.SpanTracer | None = None,
        registry: Registry | None = None,
        events_capacity: int = 512,
        interval: float = 0.0,
    ):
        self.path = path
        self.process = process
        self.tracer = tracer if tracer is not None else obs_spans.get_tracer()
        self.registry = registry if registry is not None else default_registry()
        self.interval = float(interval)
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, int(events_capacity)))  # guarded-by: self._lock
        self.dumps = 0  # lifetime dump count  # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def note(self, kind: str, **details: Any) -> None:
        """Append one event (wall-clock stamped) to the bounded log —
        cheap enough for supervision/degradation paths (one deque
        append; no IO)."""
        with self._lock:
            self._events.append(
                {"t_unix": time.time(), "kind": kind, **details}
            )

    def snapshot(self, reason: str) -> dict[str, Any]:
        from tensorflowonspark_tpu.obs import cluster as obs_cluster

        with self._lock:
            events = list(self._events)
        try:
            metrics_text = self.registry.render()
        except Exception as e:  # noqa: BLE001 - a snapshot must not raise
            metrics_text = f"# render failed: {type(e).__name__}: {e}\n"
        return {
            "flightrec_version": FORMAT_VERSION,
            "process": self.process,
            "reason": reason,
            "written_unix": time.time(),
            "trace_context": obs_cluster.trace_context(),
            "clock_sync": obs_cluster.clock_sync(),
            "events": events,
            "metrics": metrics_text,
            # full Chrome-trace export (with trace_context metadata):
            # trace_report/trace_merge read dumps as trace files
            "spans": self.tracer.export(process_name=self.process),
        }

    def dump(self, reason: str) -> str:
        """Write the snapshot atomically (tmp + rename, so a reader —
        or a SIGKILL mid-write — never sees a torn file); returns the
        path."""
        snap = self.snapshot(reason)
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            # default=str: span args are user-extensible (numpy scalars
            # and the like must degrade to text, not kill the dump)
            json.dump(snap, f, default=str)
            f.write("\n")
        os.replace(tmp, self.path)
        with self._lock:
            self.dumps += 1
        return self.path

    # -- rolling snapshots --------------------------------------------

    def start(self) -> None:
        """Daemon thread re-dumping every ``interval`` seconds — the
        SIGKILL story: the process never gets to dump at death, so the
        last rolling snapshot IS the postmortem."""
        if self.interval <= 0 or self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.dump("periodic")
                except Exception as e:  # noqa: BLE001 - keep rolling
                    logger.warning("flight recorder snapshot failed: %s", e)

        self._thread = threading.Thread(
            target=loop, daemon=True, name="flightrec"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


# -- process-global recorder -------------------------------------------------

_install_lock = threading.Lock()
_recorder: FlightRecorder | None = None  # guarded-by: _install_lock


def install(path: str, **kwargs: Any) -> FlightRecorder:
    """Install (or replace) the process-global recorder; a replaced
    recorder's snapshot thread is stopped. Returns the new recorder —
    call :meth:`FlightRecorder.start` for rolling snapshots."""
    global _recorder
    rec = FlightRecorder(path, **kwargs)
    with _install_lock:
        old, _recorder = _recorder, rec
    if old is not None:
        old.stop()
    return rec


def get() -> FlightRecorder | None:
    with _install_lock:
        return _recorder


def note(kind: str, **details: Any) -> None:
    """Event-log append on the installed recorder; no-op without one
    — callers (engine watchdog, supervision) never need to know
    whether this process opted into flight recording."""
    rec = get()
    if rec is not None:
        try:
            rec.note(kind, **details)
        except Exception:  # pragma: no cover - note must never raise
            pass


def dump_now(reason: str) -> str | None:
    """Dump the installed recorder (None without one / on IO failure)
    — the one-liner for crash paths, which must never crash harder
    because the black box had a bad day."""
    rec = get()
    if rec is None:
        return None
    try:
        return rec.dump(reason)
    except Exception as e:  # noqa: BLE001 - crash paths call this
        logger.warning("flight recorder dump failed: %s", e)
        return None
