"""One-command incident bundle: scrape, dump, merge — then page someone.

During an incident the evidence is scattered across processes that may
be about to die: the driver registry, each node runtime's ``/metrics``,
every serving replica's ``/metrics`` + ``/debugz`` trace ring, and the
flight-recorder dumps already on disk. This module gathers all of it
into ONE postmortem directory in a single pass (``tools/obs_snapshot.py``
is the CLI)::

    out/
      MANIFEST.json           what was collected, from where, and what
                              failed (a dead source is a recorded error,
                              never an aborted bundle)
      metrics/<source>.prom   raw Prometheus expositions, one per URL
      traces/<source>-<id>.trace.json
                              per-request timelines pulled from each
                              ``/debugz`` ring (Chrome-trace JSON)
      flightrec/<name>.json   flight-recorder dumps copied from disk
      autotune/<name>.json    autotune decision logs copied from disk
                              (Controller.dump artifacts — every knob
                              move/revert around the incident)
      merged_trace.json       every trace above — debugz timelines and
                              flightrec span exports — clock-aligned
                              into one timeline via
                              :mod:`~tensorflowonspark_tpu.obs.trace_merge`

Everything here is stdlib-only (urllib + json + shutil), so the CLI
runs through the stub-package fast path without importing jax.
"""

from __future__ import annotations

import argparse
import glob as globlib
import json
import logging
import os
import re
import shutil
import time
import urllib.request
from typing import Any, Iterable, Mapping, Sequence

logger = logging.getLogger(__name__)

__all__ = ["collect_bundle", "main"]


def _slug(text: str) -> str:
    """Filesystem-safe name for a URL/source ("http://h:8500/metrics"
    -> "h_8500_metrics")."""
    text = re.sub(r"^[a-z]+://", "", str(text))
    return re.sub(r"[^A-Za-z0-9._-]+", "_", text).strip("_") or "src"


def _fetch(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


def _normalize_sources(sources: Iterable[Any]) -> list[tuple[str, str]]:
    """``(name, url)`` pairs from urls, ``name=url`` strings, pairs, or
    ``{name: url}`` mappings."""
    out: list[tuple[str, str]] = []
    for src in sources or ():
        if isinstance(src, Mapping):
            out.extend((str(k), str(v)) for k, v in src.items())
        elif isinstance(src, (tuple, list)) and len(src) == 2:
            out.append((str(src[0]), str(src[1])))
        elif isinstance(src, str) and "=" in src.split("://", 1)[0]:
            name, url = src.split("=", 1)
            out.append((name, url))
        else:
            out.append((_slug(src), str(src)))
    return out


def collect_bundle(
    out_dir: str,
    metrics_urls: Iterable[Any] = (),
    debugz_urls: Iterable[Any] = (),
    flightrec_globs: Sequence[str] = (),
    trace_files: Sequence[str] = (),
    autotune_globs: Sequence[str] = (),
    timeout: float = 5.0,
) -> dict[str, Any]:
    """Collect one incident bundle under ``out_dir``; returns the
    manifest (also written as ``MANIFEST.json``). Per-source failures
    are recorded in the manifest — an incident bundle's job is to
    save whatever is still reachable, not to be atomic."""
    os.makedirs(out_dir, exist_ok=True)
    manifest: dict[str, Any] = {
        "snapshot_version": 1,
        "written_unix": time.time(),
        "metrics": [],
        "traces": [],
        "flightrec": [],
        "autotune": [],
        "errors": [],
    }

    def _err(source: str, e: BaseException) -> None:
        manifest["errors"].append(
            {"source": source, "error": f"{type(e).__name__}: {e}"}
        )

    # -- raw Prometheus expositions -----------------------------------
    metrics_dir = os.path.join(out_dir, "metrics")
    for name, url in _normalize_sources(metrics_urls):
        try:
            text = _fetch(url, timeout)
            os.makedirs(metrics_dir, exist_ok=True)
            path = os.path.join(metrics_dir, f"{_slug(name)}.prom")
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
            manifest["metrics"].append({"name": name, "url": url})
        except Exception as e:  # noqa: BLE001 - recorded per source
            _err(url, e)

    # -- tail-sampled request timelines from each /debugz ring --------
    traces_dir = os.path.join(out_dir, "traces")
    mergeable: list[str] = []
    for name, base in _normalize_sources(debugz_urls):
        base = base.rstrip("/")
        try:
            listing = json.loads(
                _fetch(f"{base}/debugz/traces", timeout)
            )
        except Exception as e:  # noqa: BLE001 - recorded per source
            _err(base, e)
            continue
        for tid in listing.get("trace_ids") or []:
            try:
                data = _fetch(f"{base}/debugz/trace/{tid}", timeout)
                os.makedirs(traces_dir, exist_ok=True)
                path = os.path.join(
                    traces_dir, f"{_slug(name)}-{_slug(tid)}.trace.json"
                )
                with open(path, "w", encoding="utf-8") as f:
                    f.write(data)
                mergeable.append(path)
                manifest["traces"].append(
                    {"source": name, "trace_id": tid}
                )
            except Exception as e:  # noqa: BLE001 - one evicted trace
                # must not lose the rest of the ring
                _err(f"{base}/debugz/trace/{tid}", e)

    # -- flight-recorder dumps already on disk ------------------------
    rec_dir = os.path.join(out_dir, "flightrec")
    for pattern in flightrec_globs or ():
        for path in sorted(globlib.glob(pattern)):
            try:
                os.makedirs(rec_dir, exist_ok=True)
                dst = os.path.join(rec_dir, os.path.basename(path))
                shutil.copyfile(path, dst)
                mergeable.append(dst)
                manifest["flightrec"].append(os.path.basename(path))
            except Exception as e:  # noqa: BLE001 - recorded per file
                _err(path, e)
    mergeable.extend(p for p in (trace_files or ()) if os.path.exists(p))

    # -- autotune decision logs already on disk -----------------------
    # (Controller.dump artifacts: was the controller moving a knob
    # right before the incident? The audit trail answers it.)
    at_dir = os.path.join(out_dir, "autotune")
    for pattern in autotune_globs or ():
        for path in sorted(globlib.glob(pattern)):
            try:
                os.makedirs(at_dir, exist_ok=True)
                dst = os.path.join(at_dir, os.path.basename(path))
                shutil.copyfile(path, dst)
                manifest["autotune"].append(os.path.basename(path))
            except Exception as e:  # noqa: BLE001 - recorded per file
                _err(path, e)

    # -- one clock-aligned timeline over everything -------------------
    if mergeable:
        from tensorflowonspark_tpu.obs import trace_merge

        try:
            merged = trace_merge.merge_traces(mergeable)
            merged_path = os.path.join(out_dir, "merged_trace.json")
            with open(merged_path, "w", encoding="utf-8") as f:
                json.dump(merged, f)
            manifest["merged_trace"] = {
                "path": "merged_trace.json",
                "events": len(merged.get("traceEvents") or []),
                "sources": len(mergeable),
            }
        except Exception as e:  # noqa: BLE001 - a torn trace must not
            # lose the raw files already saved
            _err("merge", e)

    with open(
        os.path.join(out_dir, "MANIFEST.json"), "w", encoding="utf-8"
    ) as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    return manifest


def main(argv: Sequence[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="obs_snapshot",
        description="collect one incident bundle: /metrics scrapes, "
        "/debugz trace rings, flight-recorder dumps, and a merged "
        "cluster timeline",
    )
    p.add_argument("-o", "--out", required=True, help="bundle directory")
    p.add_argument(
        "--metrics",
        action="append",
        default=[],
        metavar="[NAME=]URL",
        help="a /metrics endpoint to scrape (repeatable): the driver, "
        "a node runtime's metrics_urls() entry, a replica",
    )
    p.add_argument(
        "--debugz",
        action="append",
        default=[],
        metavar="[NAME=]URL",
        help="a serve_model base URL whose /debugz trace ring to dump "
        "(repeatable)",
    )
    p.add_argument(
        "--flightrec",
        action="append",
        default=[],
        metavar="GLOB",
        help="flight-recorder dump glob (repeatable; default "
        "logs/flightrec-*.json when none given)",
    )
    p.add_argument(
        "--trace",
        action="append",
        default=[],
        metavar="FILE",
        help="extra Chrome-trace file to fold into the merge "
        "(repeatable)",
    )
    p.add_argument(
        "--autotune",
        action="append",
        default=[],
        metavar="GLOB",
        help="autotune decision-log glob (repeatable; default "
        "logs/autotune-*.json when none given)",
    )
    p.add_argument("--timeout", type=float, default=5.0)
    args = p.parse_args(argv)
    recs = args.flightrec or ["logs/flightrec-*.json"]
    at_globs = args.autotune or ["logs/autotune-*.json"]
    manifest = collect_bundle(
        args.out,
        metrics_urls=args.metrics,
        debugz_urls=args.debugz,
        flightrec_globs=recs,
        trace_files=args.trace,
        autotune_globs=at_globs,
        timeout=args.timeout,
    )
    print(
        json.dumps(
            {
                "out": args.out,
                "metrics": len(manifest["metrics"]),
                "traces": len(manifest["traces"]),
                "flightrec": len(manifest["flightrec"]),
                "autotune": len(manifest["autotune"]),
                "errors": len(manifest["errors"]),
                "merged": "merged_trace" in manifest,
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
