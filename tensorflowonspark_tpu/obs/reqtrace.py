"""Per-request distributed tracing with tail-sampled retention.

Spans (``obs.spans``) answer "where does the *process* spend time";
this module answers "where did *this request* spend time" — across the
router, a failover hop, the subprocess boundary, and the engine's
scheduler phases, as ONE timeline.

A trace id is minted at the first surface a request touches (serve_model
HTTP ingress or ``FleetRouter.submit/stream``) and propagated in-process
as a ``trace=`` keyword and across the subprocess boundary as the
:data:`HEADER` (``X-TFOS-Trace``) request header, so the child
serve_model's engine stamps its segments onto the SAME trace id the
parent minted. Each participant appends:

- **events** — points in time (placement, failover hop, shed, swap);
- **segments** — durations (queue wait, prefill, per-decode-block
  share, emit), the substrate for wall-time attribution;
- **flags** — retention hints (``failover``, ``propagated``, ``error``).

**Tail sampling**: the keep/drop decision happens at :meth:`finish`,
when the outcome is known — full timelines are retained for error,
failover, slow (>= ``slow_s``), propagated (a parent holds the other
half), and 1-in-``sample_every`` requests; the rest are dropped. Both
the live map and the retained ring are bounded, so the ring never
exceeds ``capacity`` regardless of load.

Retained traces are served by ``GET /debugz/trace/<id>`` (serve_model
and the node metrics endpoint) as Chrome-trace JSON whose
``trace_context`` metadata makes them mergeable by
``tools/trace_merge.py`` into a clock-aligned cluster timeline.

Module-level helpers (the ``flightrec`` pattern) keep call sites one
line and make the untraced path nearly free: every helper returns
immediately when the trace id is ``None``, and the engine guards its
per-token stamps on ``p.trace is not None`` (cost asserted
failpoint-bar style in tests/test_reqtrace.py). ``TFOS_REQTRACE=0``
disables minting entirely.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any

from tensorflowonspark_tpu.obs.registry import default_registry

__all__ = [
    "HEADER",
    "TraceRing",
    "begin",
    "enabled",
    "ensure",
    "event",
    "finish",
    "flag",
    "get_record",
    "get_ring",
    "install",
    "mark",
    "mint",
    "segment",
    "to_chrome",
]

#: The cross-process propagation header: a parent (router host) sends
#: it on /generate and /generate_stream; the child serve_model adopts
#: the id instead of minting, so both halves share one trace.
HEADER = "X-TFOS-Trace"

_ENV_ENABLE = "TFOS_REQTRACE"
_ENV_CAP = "TFOS_REQTRACE_CAP"
_ENV_SAMPLE = "TFOS_REQTRACE_SAMPLE"
_ENV_SLOW_MS = "TFOS_REQTRACE_SLOW_MS"


def enabled() -> bool:
    """Minting enabled? (``TFOS_REQTRACE=0`` to disable; default on.)"""
    return os.environ.get(_ENV_ENABLE, "1") != "0"


class TraceRing:
    """Bounded live + tail-sampled retained per-request timelines.

    ``capacity`` bounds the retained ring; the live map is bounded at
    ``4 * capacity`` (an abandoned begin — a caller that died before
    ``finish`` — is evicted oldest-first, not leaked). ``max_events``
    bounds each record's event and segment lists, so one pathological
    request cannot grow without bound either.
    """

    def __init__(
        self,
        capacity: int | None = None,
        sample_every: int | None = None,
        slow_s: float | None = None,
        max_events: int = 512,
    ):
        if capacity is None:
            capacity = int(os.environ.get(_ENV_CAP, "256"))
        if sample_every is None:
            sample_every = int(os.environ.get(_ENV_SAMPLE, "64"))
        if slow_s is None:
            slow_s = float(os.environ.get(_ENV_SLOW_MS, "1000")) / 1e3
        self.capacity = max(1, int(capacity))
        self.sample_every = max(0, int(sample_every))
        self.slow_s = float(slow_s)
        self.max_events = max(16, int(max_events))
        self._lock = threading.Lock()
        self._live: OrderedDict[str, dict] = OrderedDict()  # guarded-by: self._lock
        self._retained: OrderedDict[str, dict] = OrderedDict()  # guarded-by: self._lock
        self._seq = 0  # finish() count, for 1-in-N sampling  # guarded-by: self._lock
        self._evicted = 0  # abandoned live records  # guarded-by: self._lock
        reg = default_registry()
        self._m_retained = reg.counter(
            "reqtrace_retained_total",
            "finished request traces kept by tail sampling, by reason",
        )
        self._m_dropped = reg.counter(
            "reqtrace_dropped_total",
            "finished request traces dropped by tail sampling",
        )

    # -- write surface ------------------------------------------------

    @staticmethod
    def mint() -> str:
        return uuid.uuid4().hex[:16]

    def begin(self, trace_id: str | None = None, **meta: Any) -> str:
        """Open a live record (minting an id when none given); evicts
        the oldest abandoned live record past the live bound."""
        tid = trace_id or self.mint()
        rec = {
            "trace_id": tid,
            "started_unix": time.time(),
            "_t0": time.monotonic(),
            "meta": dict(meta),
            "events": [],
            "segments": [],
            "flags": {},
            "outcome": None,
            "duration_s": None,
        }
        with self._lock:
            self._live[tid] = rec
            while len(self._live) > 4 * self.capacity:
                self._live.popitem(last=False)
                self._evicted += 1
        return tid

    def ensure(self, trace_id: str | None, **meta: Any) -> tuple[str, bool]:
        """(trace_id, began_now): begin a record unless one is already
        open/retained for ``trace_id`` — the owner (whoever began it)
        is the one who should :meth:`finish` it."""
        if trace_id is not None:
            with self._lock:
                if trace_id in self._live or trace_id in self._retained:
                    return trace_id, False
        return self.begin(trace_id, **meta), True

    def _rec(self, trace_id: str):  # lint: holds-lock
        """Live record first, retained second (late events from a slow
        participant still land). Callers hold ``self._lock``."""
        return self._live.get(trace_id) or self._retained.get(trace_id)

    def event(self, trace_id: str, name: str, **detail: Any) -> None:
        with self._lock:
            rec = self._rec(trace_id)
            if rec is None or len(rec["events"]) >= self.max_events:
                return
            rec["events"].append(
                {
                    "name": name,
                    "t_s": round(time.monotonic() - rec["_t0"], 6),
                    **detail,
                }
            )

    def segment(
        self,
        trace_id: str,
        name: str,
        dur_s: float,
        t_s: float | None = None,
        **meta: Any,
    ) -> None:
        """A duration on the timeline; ``t_s`` (segment start, seconds
        from trace start) defaults to "ended just now"."""
        with self._lock:
            rec = self._rec(trace_id)
            if rec is None or len(rec["segments"]) >= self.max_events:
                return
            if t_s is None:
                t_s = time.monotonic() - rec["_t0"] - dur_s
            rec["segments"].append(
                {
                    "name": name,
                    "t_s": round(max(0.0, t_s), 6),
                    "dur_s": round(float(dur_s), 6),
                    **meta,
                }
            )

    def flag(self, trace_id: str, **flags: Any) -> None:
        """Retention hints (``failover=True``, ``error=...``): any
        truthy flag keeps the trace at finish."""
        with self._lock:
            rec = self._rec(trace_id)
            if rec is not None:
                rec["flags"].update(flags)

    def mark(self, name: str, **detail: Any) -> int:
        """Append one event to EVERY live trace — fleet-scoped moments
        (a rollout weight swap) that belong on the timeline of every
        request they overlapped. Returns the number marked."""
        with self._lock:
            live = list(self._live.values())
            t = time.monotonic()
            n = 0
            for rec in live:
                if len(rec["events"]) >= self.max_events:
                    continue
                rec["events"].append(
                    {"name": name, "t_s": round(t - rec["_t0"], 6), **detail}
                )
                n += 1
            return n

    def finish(self, trace_id: str, outcome: str = "ok", **detail: Any) -> bool:
        """Close the record and make the tail-sampling call; returns
        whether the timeline was retained."""
        with self._lock:
            rec = self._live.pop(trace_id, None)
            if rec is None:
                # double-finish / unknown id: annotate if retained
                kept = self._retained.get(trace_id)
                if kept is not None and kept["outcome"] is None:
                    kept["outcome"] = outcome
                return kept is not None
            dur = time.monotonic() - rec["_t0"]
            rec["outcome"] = outcome
            rec["duration_s"] = round(dur, 6)
            if detail:
                rec["meta"].update(detail)
            reason = None
            if outcome != "ok":
                reason = "error"
            else:
                for k, v in rec["flags"].items():
                    if v:
                        reason = str(k)
                        break
                if reason is None and dur >= self.slow_s:
                    reason = "slow"
                if (
                    reason is None
                    and self.sample_every
                    and self._seq % self.sample_every == 0
                ):
                    reason = "sampled"
            self._seq += 1
            if reason is None:
                kept_now = False
            else:
                rec["kept"] = reason
                self._retained[trace_id] = rec
                while len(self._retained) > self.capacity:
                    self._retained.popitem(last=False)
                kept_now = True
        # counters outside our lock: the metric's own lock never nests
        # under the ring's
        if kept_now:
            self._m_retained.inc(reason=reason)
        else:
            self._m_dropped.inc()
        return kept_now

    # -- read surface -------------------------------------------------

    def get(self, trace_id: str) -> dict | None:
        """A JSON-safe copy of one record (live or retained)."""
        with self._lock:
            rec = self._rec(trace_id)
            if rec is None:
                return None
            out = {k: v for k, v in rec.items() if k != "_t0"}
            out["events"] = list(rec["events"])
            out["segments"] = list(rec["segments"])
            out["flags"] = dict(rec["flags"])
            out["meta"] = dict(rec["meta"])
            return out

    def ids(self) -> list[str]:
        """Retained trace ids, oldest first."""
        with self._lock:
            return list(self._retained)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "live": len(self._live),
                "retained": len(self._retained),
                "finished": self._seq,
                "evicted_live": self._evicted,
                "capacity": self.capacity,
            }

    def to_chrome(self, trace_id: str, process_name: str = "reqtrace") -> dict | None:
        """One record as Chrome-trace JSON. The ``trace_context``
        metadata stamps ``epoch_unix`` = the trace's start on THIS
        process's wall clock (plus the node's clock-offset estimate via
        ``obs.cluster.export_meta``), so ``trace_merge`` rebases the
        parent's and the child's halves onto one driver-clock
        timeline."""
        from tensorflowonspark_tpu.obs import cluster as obs_cluster

        rec = self.get(trace_id)
        if rec is None:
            return None
        events: list[dict] = [
            {
                "ph": "M",
                "pid": 0,
                "name": "process_name",
                "args": {"name": process_name},
            },
            {
                "ph": "M",
                "pid": 0,
                "name": "trace_context",
                "args": {
                    "epoch_unix": rec["started_unix"],
                    **obs_cluster.export_meta(),
                },
            },
        ]
        for seg in rec["segments"]:
            args = {
                k: v for k, v in seg.items() if k not in ("name", "t_s", "dur_s")
            }
            args["trace"] = trace_id
            events.append(
                {
                    "ph": "X",
                    "pid": 0,
                    "tid": f"req:{trace_id[:8]}",
                    "name": seg["name"],
                    "ts": round(seg["t_s"] * 1e6, 3),
                    "dur": round(seg["dur_s"] * 1e6, 3),
                    "args": args,
                }
            )
        for ev in rec["events"]:
            args = {k: v for k, v in ev.items() if k not in ("name", "t_s")}
            args["trace"] = trace_id
            events.append(
                {
                    "ph": "i",
                    "s": "t",
                    "pid": 0,
                    "tid": f"req:{trace_id[:8]}",
                    "name": ev["name"],
                    "ts": round(ev["t_s"] * 1e6, 3),
                    "args": args,
                }
            )
        return {
            "traceEvents": events,
            "metadata": {
                "trace_id": trace_id,
                "outcome": rec["outcome"],
                "duration_s": rec["duration_s"],
                "flags": rec["flags"],
                "meta": rec["meta"],
            },
        }

    def attribution(self, trace_id: str) -> dict[str, Any] | None:
        """Wall-time attribution for one finished trace: per-segment-
        name totals and the covered fraction of ``duration_s`` — the
        number the end-to-end trace proof (ISSUE 16) gates on. Segment
        overlap is merged (union, not sum) so double-stamped intervals
        cannot claim > 100%."""
        rec = self.get(trace_id)
        if rec is None or not rec.get("duration_s"):
            return None
        by_name: dict[str, float] = {}
        ivals: list[tuple[float, float]] = []
        for seg in rec["segments"]:
            by_name[seg["name"]] = by_name.get(seg["name"], 0.0) + seg["dur_s"]
            ivals.append((seg["t_s"], seg["t_s"] + seg["dur_s"]))
        ivals.sort()
        covered = 0.0
        cur_lo = cur_hi = None
        for lo, hi in ivals:
            if cur_hi is None or lo > cur_hi:
                if cur_hi is not None:
                    covered += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            else:
                cur_hi = max(cur_hi, hi)
        if cur_hi is not None:
            covered += cur_hi - cur_lo
        dur = rec["duration_s"]
        return {
            "trace_id": trace_id,
            "duration_s": dur,
            "covered_s": round(covered, 6),
            "covered_fraction": round(min(1.0, covered / dur), 4),
            "segments_s": {k: round(v, 6) for k, v in sorted(by_name.items())},
        }


# -- process-global ring ------------------------------------------------------

_install_lock = threading.Lock()
_ring: TraceRing | None = None  # guarded-by: _install_lock


def install(**kwargs: Any) -> TraceRing:
    """Install (or replace) the process-global ring — tests and
    processes that want non-default caps."""
    global _ring
    ring = TraceRing(**kwargs)
    with _install_lock:
        _ring = ring
    return ring


def get_ring() -> TraceRing:
    """The process-global ring, created on first use."""
    global _ring
    with _install_lock:
        if _ring is None:
            _ring = TraceRing()
        return _ring


def mint(**meta: Any) -> str | None:
    """Begin a new trace on the global ring; ``None`` when tracing is
    disabled (callers pass the id straight through — every other
    helper no-ops on ``None``)."""
    if not enabled():
        return None
    return get_ring().begin(**meta)


def ensure(trace_id: str | None, **meta: Any) -> tuple[str | None, bool]:
    """Adopt ``trace_id`` (begin it here if unknown) or mint one;
    ``(None, False)`` when disabled and no id was handed in."""
    if trace_id is None and not enabled():
        return None, False
    return get_ring().ensure(trace_id, **meta)


def begin(trace_id: str | None = None, **meta: Any) -> str | None:
    if trace_id is None and not enabled():
        return None
    return get_ring().begin(trace_id, **meta)


def event(trace_id: str | None, name: str, **detail: Any) -> None:
    if trace_id is None:
        return
    get_ring().event(trace_id, name, **detail)


def segment(
    trace_id: str | None,
    name: str,
    dur_s: float,
    t_s: float | None = None,
    **meta: Any,
) -> None:
    if trace_id is None:
        return
    get_ring().segment(trace_id, name, dur_s, t_s, **meta)


def flag(trace_id: str | None, **flags: Any) -> None:
    if trace_id is None:
        return
    get_ring().flag(trace_id, **flags)


def mark(name: str, **detail: Any) -> int:
    with _install_lock:
        ring = _ring
    if ring is None:  # nothing traced yet: nothing to mark
        return 0
    return ring.mark(name, **detail)


def finish(trace_id: str | None, outcome: str = "ok", **detail: Any) -> bool:
    if trace_id is None:
        return False
    return get_ring().finish(trace_id, outcome, **detail)


def get_record(trace_id: str) -> dict | None:
    return get_ring().get(trace_id)


def to_chrome(trace_id: str, process_name: str = "reqtrace") -> dict | None:
    return get_ring().to_chrome(trace_id, process_name)


def _reset_for_tests() -> None:
    global _ring
    with _install_lock:
        _ring = None
