"""Windowed time-series history: bounded rings over metric scrapes.

``Registry.window()`` gives ONE delta since the previous call; a
feedback controller (the ROADMAP-2 autotune loop) and a burn-rate SLO
evaluator (``obs.slo``) both need a *series* — "the last N windows of
``engine_ttft_seconds``, with rates and percentiles derivable per
window". This module is that read substrate:

- :meth:`History.scrape_registry` pumps an in-process
  :class:`~tensorflowonspark_tpu.obs.registry.Registry` snapshot
  (serve_model's pump thread, bench drive loops);
- :meth:`History.record_families` pumps parsed Prometheus expositions —
  the shape the driver-side ``MetricsAggregator`` scrapes off every
  node (``obs.cluster`` wires this in);
- :meth:`History.series` / :meth:`rate` / :meth:`percentile` /
  :meth:`fraction_le` are the query surface, each over a trailing
  wall-clock window;
- every appended point optionally spills to JSONL
  (``spill_path``), so a run leaves its full telemetry history on
  disk, and :meth:`to_artifact` packages the rings for bench
  artifacts (windowed history instead of a point snapshot).

Per-series rings are ``deque(maxlen=capacity)`` — memory is bounded by
``capacity * series-cardinality`` regardless of run length.

Point shapes (one dict per scrape, stored as ``(t_unix, entry)``):
counter/gauge ``{"value", "delta"}``; histogram ``{"count", "sum",
"delta_count", "delta_sum", "le", "buckets", "delta_buckets"}`` with
cumulative bucket counts (``count`` is the implicit ``+Inf`` bound),
exactly :meth:`Registry.window`'s entry shape.
"""

from __future__ import annotations

import json
import re
import threading
import time
from collections import deque
from typing import Any, Iterable, Mapping

from tensorflowonspark_tpu.obs.registry import Registry, _label_str

__all__ = ["History"]

_LABEL_PAIR = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:\\.|[^"\\])*)",?')


def _labels_key(labels: Mapping[str, Any] | str | None) -> str:
    """Normalize a label set to the registry-rendered ``{k="v",...}``
    string (the series key)."""
    if labels is None:
        return ""
    if isinstance(labels, str):
        return labels
    return _label_str(tuple(sorted((k, str(v)) for k, v in labels.items())))


def _parse_label_str(label_str: str) -> dict[str, str]:
    if not label_str:
        return {}
    out: dict[str, str] = {}
    for m in _LABEL_PAIR.finditer(label_str.strip("{}")):
        v = m.group("v")
        out[m.group("k")] = (
            v.replace(r"\n", "\n").replace(r"\"", '"').replace(r"\\", "\\")
        )
    return out


class History:
    """Bounded per-series rings of windowed metric scrapes."""

    def __init__(
        self,
        capacity: int = 512,
        spill_path: str | None = None,
        source: str = "",
    ):
        self.capacity = max(2, int(capacity))
        self.source = source
        self._lock = threading.Lock()
        #: (name, label_str) -> deque[(t_unix, entry)]
        self._series: dict[tuple[str, str], deque] = {}  # guarded-by: self._lock
        self._kinds: dict[str, str] = {}  # guarded-by: self._lock
        self._points = 0  # lifetime appended points  # guarded-by: self._lock
        self._spill_path = spill_path
        self._spill_f = None  # lazily opened  # guarded-by: self._lock

    # -- write surface ------------------------------------------------

    def record_point(
        self,
        name: str,
        labels: Mapping[str, Any] | str | None,
        kind: str,
        entry: Mapping[str, Any],
        t: float | None = None,
    ) -> None:
        t = time.time() if t is None else float(t)
        key = (name, _labels_key(labels))
        entry = dict(entry)
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                ring = self._series[key] = deque(maxlen=self.capacity)
            ring.append((t, entry))
            self._kinds[name] = kind
            self._points += 1
            if self._spill_path is not None:
                if self._spill_f is None:
                    self._spill_f = open(self._spill_path, "a")
                json.dump(
                    {"t": round(t, 3), "name": name, "labels": key[1],
                     "kind": kind, **entry},
                    self._spill_f,
                )
                self._spill_f.write("\n")

    def scrape_registry(self, registry: Registry, t: float | None = None) -> int:
        """One ``registry.window()`` snapshot into the rings; returns
        the number of points appended. NOTE: ``window()`` deltas are
        relative to the registry's previous ``window()`` call — give
        each registry ONE pumping History or the windows interleave."""
        t = time.time() if t is None else float(t)
        n = 0
        for name, fam in registry.window().items():
            for label_str, entry in fam["series"].items():
                self.record_point(name, label_str, fam["kind"], entry, t=t)
                n += 1
        return n

    def record_families(
        self,
        families: Mapping[str, Mapping[str, Any]],
        extra_labels: Mapping[str, str] | None = None,
        t: float | None = None,
    ) -> int:
        """Parsed Prometheus expositions (``parse_prometheus_text``'s
        ``{family: {"type", "samples": {(sample, label_items): v}}}``)
        into the rings — the driver aggregator's per-node scrapes.
        Histogram families are regrouped (``_bucket``/``_sum``/
        ``_count`` samples under one entry per label set); deltas are
        computed against each series' previous point. ``extra_labels``
        (e.g. ``{"node": "3"}``) joins every sample's label set."""
        t = time.time() if t is None else float(t)
        extra = tuple(sorted((extra_labels or {}).items()))
        n = 0
        for fam_name, data in families.items():
            kind = data.get("type") or "untyped"
            samples = data.get("samples") or {}
            if kind == "histogram":
                # label-set (minus le) -> {"le": {bound: v}, "sum", "count"}
                grouped: dict[tuple, dict[str, Any]] = {}
                for (sname, label_items), value in samples.items():
                    items = tuple(
                        (k, v) for k, v in label_items if k != "le"
                    ) + extra
                    g = grouped.setdefault(
                        items, {"le": {}, "sum": 0.0, "count": 0}
                    )
                    if sname.endswith("_bucket"):
                        bound = dict(label_items).get("le", "+Inf")
                        g["le"][bound] = value
                    elif sname.endswith("_sum"):
                        g["sum"] = value
                    elif sname.endswith("_count"):
                        g["count"] = int(value)
                for items, g in grouped.items():
                    finite = sorted(
                        (float(b), v)
                        for b, v in g["le"].items()
                        if b not in ("+Inf", "inf")
                    )
                    entry = {
                        "count": g["count"],
                        "sum": g["sum"],
                        "le": [b for b, _ in finite],
                        "buckets": [int(v) for _, v in finite],
                    }
                    label_str = _label_str(tuple(sorted(items)))
                    prev = self._last_entry(fam_name, label_str)
                    pb = (prev or {}).get("buckets") or [0] * len(finite)
                    if len(pb) != len(finite):
                        pb = [0] * len(finite)
                    entry["delta_count"] = entry["count"] - (
                        (prev or {}).get("count") or 0
                    )
                    entry["delta_sum"] = entry["sum"] - (
                        (prev or {}).get("sum") or 0.0
                    )
                    entry["delta_buckets"] = [
                        b - p for b, p in zip(entry["buckets"], pb)
                    ]
                    self.record_point(fam_name, label_str, kind, entry, t=t)
                    n += 1
            else:
                for (sname, label_items), value in samples.items():
                    label_str = _label_str(tuple(sorted(label_items + extra)))
                    prev = self._last_entry(sname, label_str)
                    entry = {
                        "value": value,
                        "delta": value - ((prev or {}).get("value") or 0.0),
                    }
                    self.record_point(sname, label_str, kind, entry, t=t)
                    n += 1
        return n

    def _last_entry(self, name: str, label_str: str) -> dict | None:
        with self._lock:
            ring = self._series.get((name, label_str))
            return dict(ring[-1][1]) if ring else None

    # -- query surface ------------------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            return sorted({name for name, _ in self._series})

    def labels_of(self, name: str) -> list[str]:
        with self._lock:
            return sorted(ls for n, ls in self._series if n == name)

    def series(
        self,
        name: str,
        labels: Mapping[str, Any] | str | None = None,
        last_n: int | None = None,
    ) -> list[tuple[float, dict[str, Any]]]:
        """The ring for one series, oldest first — THE read substrate
        the autotune controller consumes. ``labels`` is a dict or the
        rendered ``{k="v"}`` string; ``last_n`` trims to the newest N
        points."""
        key = (name, _labels_key(labels))
        with self._lock:
            ring = self._series.get(key)
            pts = [(t, dict(e)) for t, e in ring] if ring else []
        return pts[-last_n:] if last_n else pts

    def _matching_keys(
        self, name: str, labels: Mapping[str, Any] | str | None
    ) -> list[str]:
        """Series keys for a selector: ``None`` matches every label
        set of ``name`` (Prometheus-style sum), a string is the exact
        rendered key, and a dict is a label-SUBSET filter (``{"route":
        "generate"}`` matches every series carrying that pair)."""
        with self._lock:
            all_ls = [ls for n, ls in self._series if n == name]
        if labels is None:
            return sorted(all_ls)
        if isinstance(labels, str):
            return [labels] if labels in all_ls else []
        want = {(k, str(v)) for k, v in labels.items()}
        return sorted(
            ls
            for ls in all_ls
            if want <= set(_parse_label_str(ls).items())
        )

    def _window_points(
        self, name, label_str, window_s, now
    ) -> list[tuple[float, dict[str, Any]]]:
        now = time.time() if now is None else now
        pts = self.series(name, label_str)
        if window_s is None:
            return pts
        lo = now - float(window_s)
        return [p for p in pts if p[0] >= lo]

    def rate(
        self,
        name: str,
        labels: Mapping[str, Any] | str | None = None,
        window_s: float | None = 60.0,
        now: float | None = None,
    ) -> float | None:
        """Per-second increase of a counter (or histogram ``count``)
        over the trailing window, summed over matching series; None
        without any series holding >= 2 in-window points."""
        total = None
        for ls in self._matching_keys(name, labels):
            pts = self._window_points(name, ls, window_s, now)
            if len(pts) < 2:
                continue
            (t0, e0), (t1, e1) = pts[0], pts[-1]
            if t1 <= t0:
                continue
            v0 = e0.get("value", e0.get("count", 0.0))
            v1 = e1.get("value", e1.get("count", 0.0))
            total = (total or 0.0) + (v1 - v0) / (t1 - t0)
        return total

    def delta(
        self,
        name: str,
        labels: Mapping[str, Any] | str | None = None,
        window_s: float | None = 60.0,
        now: float | None = None,
    ) -> float:
        """Total increase over the window (sum of point deltas across
        matching series — robust to ring eviction mid-window). 0.0
        with no points."""
        out = 0.0
        for ls in self._matching_keys(name, labels):
            pts = self._window_points(name, ls, window_s, now)
            out += sum(
                e.get("delta", e.get("delta_count", 0.0)) for _, e in pts
            )
        return float(out)

    def delta_sum(
        self,
        name: str,
        labels: Mapping[str, Any] | str | None = None,
        window_s: float | None = 60.0,
        now: float | None = None,
    ) -> float:
        """Total increase of a histogram's ``sum`` over the window
        (seconds spent, bytes moved, ...), summed across matching
        series — the time-share complement of :meth:`delta`'s count
        view; autotune overhead hints read this. 0.0 with no points."""
        out = 0.0
        for ls in self._matching_keys(name, labels):
            pts = self._window_points(name, ls, window_s, now)
            out += sum(e.get("delta_sum", 0.0) for _, e in pts)
        return float(out)

    def _bucket_deltas(
        self, name, labels, window_s, now
    ) -> tuple[list[float], list[float], float] | None:
        """Summed (le, delta_buckets, delta_count) over the window and
        matching series; None when nothing histogram-shaped matched."""
        le: list[float] | None = None
        acc: list[float] = []
        total = 0.0
        for ls in self._matching_keys(name, labels):
            for _, e in self._window_points(name, ls, window_s, now):
                if "delta_buckets" not in e:
                    continue
                if le is None:
                    le = list(e.get("le") or [])
                    acc = [0.0] * len(le)
                if list(e.get("le") or []) != le:
                    continue  # bucket layout changed mid-window: skip
                for i, d in enumerate(e["delta_buckets"]):
                    acc[i] += d
                total += e.get("delta_count", 0.0)
        if le is None:
            return None
        return le, acc, total

    def fraction_le(
        self,
        name: str,
        bound: float,
        labels: Mapping[str, Any] | str | None = None,
        window_s: float | None = 60.0,
        now: float | None = None,
    ) -> float | None:
        """Fraction of the window's observations <= ``bound`` (linear
        interpolation inside the straddling bucket) — the latency-SLO
        compliance ratio. None with no observations in the window."""
        bd = self._bucket_deltas(name, labels, window_s, now)
        if bd is None:
            return None
        le, acc, total = bd
        if total <= 0:
            return None
        prev_edge = 0.0
        prev_cum = 0.0
        for edge, cum in zip(le, acc):
            if bound <= edge:
                if edge <= prev_edge:
                    return min(1.0, cum / total)
                frac_in = (bound - prev_edge) / (edge - prev_edge)
                est = prev_cum + (cum - prev_cum) * max(0.0, min(1.0, frac_in))
                return min(1.0, est / total)
            prev_edge, prev_cum = edge, cum
        return 1.0 if bound >= (le[-1] if le else 0.0) else min(
            1.0, prev_cum / total
        )

    def percentile(
        self,
        name: str,
        q: float,
        labels: Mapping[str, Any] | str | None = None,
        window_s: float | None = 60.0,
        now: float | None = None,
    ) -> float | None:
        """The q-quantile (0..1) of the window's observations, linearly
        interpolated over cumulative bucket deltas; observations above
        the top finite bucket clamp to it (Prometheus convention)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        bd = self._bucket_deltas(name, labels, window_s, now)
        if bd is None:
            return None
        le, acc, total = bd
        if total <= 0 or not le:
            return None
        want = q * total
        prev_edge = 0.0
        prev_cum = 0.0
        for edge, cum in zip(le, acc):
            if cum >= want:
                if cum <= prev_cum:
                    return edge
                return prev_edge + (edge - prev_edge) * (
                    (want - prev_cum) / (cum - prev_cum)
                )
            prev_edge, prev_cum = edge, cum
        return le[-1]

    # -- export -------------------------------------------------------

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "series": len(self._series),
                "points": self._points,
                "capacity": self.capacity,
            }

    def to_artifact(
        self,
        last_n: int | None = None,
        names: Iterable[str] | None = None,
    ) -> dict[str, Any]:
        """The rings as a JSON-safe artifact — what bench commits
        instead of a point snapshot."""
        want = set(names) if names is not None else None
        with self._lock:
            keys = sorted(self._series)
            kinds = dict(self._kinds)
            series = []
            for name, label_str in keys:
                if want is not None and name not in want:
                    continue
                pts = list(self._series[(name, label_str)])
                if last_n:
                    pts = pts[-last_n:]
                series.append(
                    {
                        "name": name,
                        "labels": label_str,
                        "kind": kinds.get(name, "untyped"),
                        "points": [
                            {"t": round(t, 3), **e} for t, e in pts
                        ],
                    }
                )
        return {
            "history_version": 1,
            "source": self.source,
            "capacity": self.capacity,
            "series": series,
        }

    def close(self) -> None:
        with self._lock:
            f, self._spill_f = self._spill_f, None
        if f is not None:
            f.close()
