"""Merge driver + N node Chrome traces into ONE cluster timeline.

Each process's :meth:`SpanTracer.export` stamps a ``trace_context``
metadata event: the run's ``trace_id``, the process's node name, the
wall-clock time of the tracer epoch (``epoch_unix``), and — on nodes —
the clock-offset estimate from heartbeat RTT midpoints
(``obs.cluster.note_clock_sync``). That is exactly enough to rebase
every event onto the DRIVER's wall clock::

    driver_time = epoch_unix + ts/1e6 + clock_offset_s

so a feed frame's ``feed.send`` span on the driver and its
``feed.queue_get`` span on the node line up causally, within the
heartbeat RTT error bound (offset estimation caveat:
docs/OBSERVABILITY.md). Inputs may be plain Chrome-trace JSON
(optionally gzipped) or flight-recorder dumps (``obs.flightrec``),
whose embedded span export is used.

Pids are remapped per source (Chrome traces key lanes on pid, and two
single-host processes can collide), process names gain the node
prefix, and spans carrying ``{stream, seq}`` args — the columnar frame
identity that rides the wire header — get Chrome flow arrows linking
producer to consumer across processes.

CLI (also at ``tools/trace_merge.py``)::

    python -m tensorflowonspark_tpu.obs.trace_merge \
        -o merged.json driver.trace.json logs/flightrec-node*.json
"""

from __future__ import annotations

import argparse
import gzip
import json
import sys
from typing import Any, Sequence

__all__ = ["load_trace", "merge_traces", "main", "trace_context_of"]


def load_trace(path: str) -> dict:
    """A Chrome-trace dict from ``path`` — plain/gzipped trace JSON, or
    a flight-recorder dump (its ``spans`` export)."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        data = json.load(f)
    if "traceEvents" not in data and isinstance(data.get("spans"), dict):
        data = data["spans"]  # flightrec dump
    if "traceEvents" not in data:
        raise ValueError(f"{path}: neither a Chrome trace nor a flightrec dump")
    return data


def trace_context_of(events: Sequence[dict]) -> dict[str, Any]:
    """The first ``trace_context`` metadata event's args ({} if the
    trace predates trace-context export)."""
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "trace_context":
            return dict(e.get("args") or {})
    return {}


def _process_names(events: Sequence[dict]) -> dict:
    return {
        e.get("pid"): (e.get("args") or {}).get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }


def merge_traces(paths: Sequence[str]) -> dict:
    """One merged ``{"traceEvents": [...], "metadata": {...}}`` over
    ``paths``. Events are rebased to a common zero (the earliest event
    across all sources, on the driver clock); sources without
    ``epoch_unix`` cannot be aligned and are rebased to zero with
    ``aligned: false`` in their metadata entry. ``metadata.trace_ids``
    lists every distinct trace id seen — more than one means the
    inputs span different runs, which the CLI warns about."""
    if not paths:
        raise ValueError("no trace files to merge")
    sources: list[dict[str, Any]] = []
    for i, path in enumerate(paths):
        events = load_trace(path).get("traceEvents", [])
        ctx = trace_context_of(events)
        offset = float(ctx.get("clock_offset_s") or 0.0)
        epoch_unix = ctx.get("epoch_unix")
        sources.append(
            {
                "file": path,
                "index": i,
                "events": events,
                "ctx": ctx,
                "node": ctx.get("node") or f"proc{i}",
                "trace_id": ctx.get("trace_id"),
                "clock_offset_s": offset,
                "clock_rtt_s": ctx.get("clock_rtt_s"),
                "epoch_unix": (
                    float(epoch_unix) if epoch_unix is not None else None
                ),
                "aligned": epoch_unix is not None,
            }
        )

    # Common zero: the earliest aligned event start, driver clock.
    base_unix: float | None = None
    for src in sources:
        if not src["aligned"]:
            continue
        for e in src["events"]:
            if e.get("ph") != "X" or "ts" not in e:
                continue
            t = src["epoch_unix"] + e["ts"] / 1e6 + src["clock_offset_s"]
            base_unix = t if base_unix is None else min(base_unix, t)
    if base_unix is None:
        base_unix = 0.0

    merged: list[dict] = []
    # flow linking: (stream, seq) -> list of (abs_ts_us, pid, tid, name)
    frame_sites: dict[tuple, list[tuple]] = {}
    for src in sources:
        pid_map: dict[Any, int] = {}
        names = _process_names(src["events"])

        def remap_pid(pid, src=src, pid_map=pid_map):
            if pid not in pid_map:
                pid_map[pid] = src["index"] * 1000 + len(pid_map)
            return pid_map[pid]

        if src["aligned"]:
            shift_us = (
                src["epoch_unix"] + src["clock_offset_s"] - base_unix
            ) * 1e6
        else:
            shift_us = 0.0
        for e in src["events"]:
            e = dict(e)
            pid = remap_pid(e.get("pid"))
            e["pid"] = pid
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    orig = (e.get("args") or {}).get("name", "")
                    e["args"] = {"name": f"{src['node']}: {orig}"}
                elif e.get("name") == "trace_context":
                    continue  # superseded by metadata.sources below
                merged.append(e)
                continue
            if "ts" in e:
                e["ts"] = round(e["ts"] + shift_us, 3)
            merged.append(e)
            args = e.get("args") or {}
            if (
                e.get("ph") == "X"
                and src["aligned"]
                and args.get("stream") is not None
                and args.get("seq") is not None
            ):
                frame_sites.setdefault(
                    (str(args["stream"]), int(args["seq"])), []
                ).append((e["ts"], pid, e.get("tid"), e.get("name")))
        src["pids"] = {
            pid_map.get(p): f"{src['node']}: {n}" for p, n in names.items()
        }
        del src["events"]

    # Chrome flow arrows between consecutive sites of one frame
    # (driver feed.send -> node feed.queue_get -> ...): same id + cat.
    flow_id = 0
    for (stream, seq), sites in sorted(frame_sites.items()):
        if len(sites) < 2:
            continue
        sites.sort(key=lambda s: s[0])  # ts only: tids mix int/str
        flow_id += 1
        for j, (ts, pid, tid, name) in enumerate(sites):
            merged.append(
                {
                    "ph": "s" if j == 0 else ("f" if j == len(sites) - 1 else "t"),
                    "cat": "feed_frame",
                    "id": flow_id,
                    "name": f"frame {stream}/{seq}",
                    "pid": pid,
                    "tid": tid,
                    "ts": ts,
                    **({"bp": "e"} if j == len(sites) - 1 else {}),
                }
            )

    trace_ids = sorted(
        {s["trace_id"] for s in sources if s["trace_id"] is not None}
    )
    return {
        "traceEvents": sorted(
            merged, key=lambda e: (e.get("ph") != "M", e.get("ts", 0))
        ),
        "metadata": {
            "base_unix": base_unix,
            "trace_ids": trace_ids,
            "sources": [
                {
                    k: s[k]
                    for k in (
                        "file",
                        "node",
                        "trace_id",
                        "clock_offset_s",
                        "clock_rtt_s",
                        "epoch_unix",
                        "aligned",
                        "pids",
                    )
                }
                for s in sources
            ],
        },
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_merge",
        description="merge driver + node Chrome traces / flightrec "
        "dumps into one clock-aligned cluster timeline",
    )
    ap.add_argument("traces", nargs="+", help="trace files or flightrec dumps")
    ap.add_argument(
        "-o", "--out", required=True, help="merged Chrome-trace JSON path"
    )
    args = ap.parse_args(argv)
    try:
        merged = merge_traces(args.traces)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"trace_merge: {e}", file=sys.stderr)
        return 1
    meta = merged["metadata"]
    if len(meta["trace_ids"]) > 1:
        print(
            f"trace_merge: WARNING: inputs span {len(meta['trace_ids'])} "
            f"different trace ids {meta['trace_ids']} — these are "
            "different runs",
            file=sys.stderr,
        )
    unaligned = [s["file"] for s in meta["sources"] if not s["aligned"]]
    if unaligned:
        print(
            f"trace_merge: WARNING: no epoch_unix in {unaligned}; those "
            "sources are rebased to 0, not clock-aligned",
            file=sys.stderr,
        )
    with open(args.out, "w") as f:
        json.dump(merged, f)
        f.write("\n")
    n_ev = len(merged["traceEvents"])
    print(
        f"trace_merge: {len(meta['sources'])} source(s), {n_ev} events "
        f"-> {args.out}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
