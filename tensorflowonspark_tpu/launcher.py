"""``tpu-submit`` — the spark-submit-shaped entry point.

In the reference, ``spark-submit`` *is* the CLI (SURVEY.md §1): it starts
the user's driver script, which then calls ``TFCluster.run``. This launcher
keeps that UX with zero Spark: it accepts the familiar flags, exports them
as ``TFOS_TPU_*`` env vars (read by :func:`cluster_args_from_env`), and
executes the user script as ``__main__``.

Usage::

    tpu-submit --num-executors 4 [--conf K=V ...] script.py [script args...]
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-submit",
        description="Run a driver script against a TPU cluster "
        "(spark-submit-compatible surface).",
    )
    p.add_argument("--num-executors", type=int, default=1)
    p.add_argument(
        "--master",
        default="local",
        help="'local' (this host) or 'hosts:h1,h2,...' (one node per host)",
    )
    p.add_argument(
        "--conf",
        action="append",
        default=[],
        metavar="K=V",
        help="extra configuration, exported as env vars",
    )
    p.add_argument("--name", default=None, help="job name (informational)")
    p.add_argument("--queue", default=None, help="accepted for CLI parity; unused")
    p.add_argument(
        "--deploy-mode", default="client", help="accepted for CLI parity; unused"
    )
    p.add_argument("script", help="driver script to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def cluster_args_from_env() -> dict:
    """Read launcher-provided defaults inside a driver script.

    Returns kwargs directly usable as ``tfcluster.run(fn, args, **these)``:
    ``num_executors`` plus, for ``--master hosts:h1,h2,...``, a configured
    ``launcher`` (one node per host over ssh) and ``distributed=True``.
    """
    out: dict = {
        "num_executors": int(os.environ.get("TFOS_TPU_NUM_EXECUTORS", "1"))
    }
    master = os.environ.get("TFOS_TPU_MASTER", "local")
    if master.startswith("hosts:"):
        from tensorflowonspark_tpu.cluster.launchers import HostListLauncher

        hosts = master[len("hosts:") :].split(",")
        out["num_executors"] = len(hosts)
        out["launcher"] = HostListLauncher(hosts)
        out["distributed"] = True
    return out


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    os.environ["TFOS_TPU_NUM_EXECUTORS"] = str(args.num_executors)
    os.environ["TFOS_TPU_MASTER"] = args.master
    if args.name:
        os.environ["TFOS_TPU_JOB_NAME"] = args.name
    for conf in args.conf:
        if "=" not in conf:
            raise SystemExit(f"--conf expects K=V, got {conf!r}")
        k, v = conf.split("=", 1)
        os.environ[k] = v

    sys.argv = [args.script] + list(args.script_args)
    runpy.run_path(args.script, run_name="__main__")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
