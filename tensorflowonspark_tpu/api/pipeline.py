"""``TFEstimator.fit`` / ``TFModel.transform`` — the ML-pipeline layer.

Reference parity: ``tensorflowonspark/pipeline.py`` — ``Namespace``/
``ArgvParams`` argv↔params merging, the ``Has*`` param mixins, ``TFEstimator
._fit`` (run a full cluster training job, return a model), ``TFModel
._transform`` (per-worker single-process inference with a lazily-loaded
exported model, ``input_mapping``/``output_mapping`` column↔tensor maps).

TPU-native differences: the exported artifact is an orbax checkpoint plus a
registered apply-fn (instead of a SavedModel + signature defs), and
``transform`` runs the compiled apply fn batch-wise in-process — the moral
equivalent of the reference's SavedModel-session singleton per executor.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Iterable, Sequence

import numpy as np

logger = logging.getLogger(__name__)


class Namespace(dict):
    """Dict/attr hybrid holding merged params (reference: ``pipeline.Namespace``).

    Accepts a dict, another Namespace, or an argv list (``['--batch_size',
    '64', '--flag']`` → ``{'batch_size': '64', 'flag': True}``).
    """

    def __init__(self, data: Any = None, **kwargs):
        super().__init__()
        if isinstance(data, (list, tuple)):
            self.update(_parse_argv(list(data)))
        elif isinstance(data, dict):
            self.update(data)
        elif data is not None:
            raise TypeError(f"unsupported Namespace source: {type(data)}")
        self.update(kwargs)

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    def argv(self) -> list[str]:
        """Render back to an argv list (inverse of parsing)."""
        out: list[str] = []
        for k, v in self.items():
            if isinstance(v, bool):
                if v:
                    out.append(f"--{k}")
            else:
                out.extend([f"--{k}", str(v)])
        return out


def _parse_argv(argv: list[str]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    i = 0
    while i < len(argv):
        tok = argv[i]
        if not tok.startswith("--"):
            raise ValueError(f"expected --flag, got {tok!r}")
        key = tok[2:]
        if "=" in key:
            key, val = key.split("=", 1)
            out[key] = val
            i += 1
        elif i + 1 < len(argv) and not argv[i + 1].startswith("--"):
            out[key] = argv[i + 1]
            i += 2
        else:
            out[key] = True
            i += 1
    return out


class _HasParams:
    """Typed param plumbing — the reference's ``Has*`` mixin stack
    (HasBatchSize, HasClusterSize, HasEpochs, HasInputMapping,
    HasOutputMapping, HasInputMode, HasModelDir, HasExportDir, HasSteps,
    HasGraceSecs, ... — ``pipeline.py ~L60-300``) collapsed into one
    declarative table."""

    PARAMS: dict[str, Any] = {
        "batch_size": 64,
        "cluster_size": 1,
        "num_ps": 0,
        "epochs": 1,
        "steps": 0,
        "input_mapping": None,
        "output_mapping": None,
        "input_mode": 1,  # InputMode.SPARK
        "master_node": None,
        "model_dir": None,
        "export_dir": None,
        "tfrecord_dir": None,
        "tensorboard": False,
        "grace_secs": 0.0,
        "reservation_timeout": 600.0,
        "distributed": False,
        "protocol": "ici",  # reference: grpc|grpc+verbs; here informational
        "readers": 1,
        "signature_def_key": None,
        "tag_set": None,
    }

    def _init_params(self, tf_args: Any, overrides: dict[str, Any]) -> Namespace:
        """Merge precedence: defaults < tf_args < explicit params.

        (The reference's ``ArgvParams`` merge did the same: Spark ML Params
        override the argv-derived namespace.)
        """
        ns = Namespace(dict(self.PARAMS))
        if tf_args:
            ns.update(Namespace(tf_args))
        ns.update(overrides)
        return ns

    # reference-style setter/getter surface
    def setParam(self, name: str, value: Any):  # noqa: N802
        self.args[name] = value
        return self

    def getParam(self, name: str) -> Any:  # noqa: N802
        return self.args[name]

    # The reference's per-param accessors (``setBatchSize``, ``setNumPS``,
    # ``getModelDir``, ... — one Has* mixin each, pipeline.py ~L60-300)
    # are generated from the table: chainable setters, plain getters.
    _CAMEL_OVERRIDES = {"num_ps": "NumPS", "tfrecord_dir": "TFRecordDir"}

    @classmethod
    def _accessor_map(cls) -> dict[str, tuple[str, str]]:
        if "_ACCESSORS" not in cls.__dict__:
            table = {}
            for key in cls.PARAMS:
                camel = cls._CAMEL_OVERRIDES.get(
                    key, "".join(p.capitalize() for p in key.split("_"))
                )
                table["set" + camel] = ("set", key)
                table["get" + camel] = ("get", key)
            cls._ACCESSORS = table
        return cls._ACCESSORS

    def __getattr__(self, name: str):
        kind_key = self._accessor_map().get(name)
        if kind_key is not None:
            kind, key = kind_key
            if kind == "set":

                def setter(value):
                    return self.setParam(key, value)

                return setter

            def getter():
                return self.getParam(key)

            return getter
        raise AttributeError(
            f"{type(self).__name__!s} has no attribute {name!r}"
        )


class TFEstimator(_HasParams):
    """Train via a full cluster job; returns a :class:`TFModel`.

    ``train_fn(args, ctx)`` is the same map_fun ``TFCluster.run`` takes.
    ``export_fn(args) -> (apply_fn, target_state)`` tells ``TFModel`` how to
    rebuild the model function and the checkpoint's pytree structure at
    transform time (the role the SavedModel signature played in the
    reference).
    """

    def __init__(
        self,
        train_fn: Callable[[Any, Any], Any],
        tf_args: Any = None,
        export_fn: Callable[[Namespace], tuple[Callable, Any]] | None = None,
        **params,
    ):
        self.train_fn = train_fn
        self.export_fn = export_fn
        self.args = self._init_params(tf_args, params)

    def fit(self, data: Iterable, launcher=None, env=None) -> "TFModel":
        """Reference: ``TFEstimator._fit`` — run TFCluster, train, shutdown.

        In ``InputMode.TENSORFLOW`` with ``tfrecord_dir`` set, ``data`` is
        staged as TFRecords first and the nodes read the files themselves
        (reference ``_fit``: ``dfutil.saveAsTFRecords(df)`` when
        ``tfrecord_dir`` is configured); the path is handed to ``train_fn``
        via ``args.tfrecord_dir``.
        """
        from tensorflowonspark_tpu.cluster import tfcluster
        from tensorflowonspark_tpu.cluster.tfcluster import InputMode

        args = self.args
        if (
            int(args.input_mode) == InputMode.TENSORFLOW
            and args.tfrecord_dir
            and data is not None
        ):
            import glob as _glob
            import os as _os

            from tensorflowonspark_tpu.data import dfutil

            # Restaging must replace, not mix: a prior (larger) run's
            # leftover shards would otherwise be globbed in silently.
            for stale in _glob.glob(
                _os.path.join(args.tfrecord_dir, "part-*")
            ):
                _os.remove(stale)
            rows = (
                row if isinstance(row, dict) else self._rowdict(row)
                for row in data
            )
            dfutil.saveAsTFRecords(rows, args.tfrecord_dir)
        cluster = tfcluster.run(
            self.train_fn,
            args,
            num_executors=int(args.cluster_size),
            num_ps=int(args.num_ps),
            tensorboard=bool(args.tensorboard),
            input_mode=int(args.input_mode),
            master_node=args.master_node,
            reservation_timeout=float(args.reservation_timeout),
            launcher=launcher,
            env=env,
            distributed=bool(args.distributed),
        )
        if int(args.input_mode) == InputMode.SPARK:
            cluster.train(data, num_epochs=int(args.epochs))
        cluster.shutdown(grace_secs=float(args.grace_secs))
        model = TFModel(self.args, export_fn=self.export_fn)
        # transform inherits cluster_size from fit, so it also inherits
        # fit's env: a model fitted under cpu_only_env must not scale
        # out its inference through TPU-dialing default workers. (The
        # launcher instance is NOT inherited — launchers are single-use.)
        model._fit_env = env
        return model

    def _rowdict(self, row) -> dict[str, Any]:
        """Tuple row → dict keyed by input_mapping columns (the positional
        contract of :func:`columnize`)."""
        mapping = self.args.input_mapping
        if mapping is None:
            raise ValueError(
                "tfrecord_dir staging needs dict rows or an input_mapping "
                "naming the tuple fields in order"
            )
        cols = list(mapping.keys())
        if len(row) != len(cols):
            raise ValueError(
                f"record has {len(row)} fields but input_mapping names "
                f"{len(cols)} columns"
            )
        return dict(zip(cols, row))


class TFModel(_HasParams):
    """Batch inference from an exported checkpoint.

    Reference: ``TFModel._transform`` / ``_run_model`` — each worker lazily
    loads the exported model ONCE (global singleton), maps input/output
    columns, batches rows, yields outputs. Here the singleton is the
    restored orbax state + the jit-compiled apply fn.
    """

    _singleton: tuple[Any, Any] | None = None
    _singleton_key: tuple | None = None
    _singleton_aot_mappings: tuple[Any, Any] = (None, None)
    # export_fn-path models accept resharded inputs; AOT replays cannot.
    _singleton_shardable: bool = False
    # Marks that the singleton's state has been replicated over the local
    # mesh (done once per loaded model, replacing the device-0-committed
    # copy so only one copy of the weights survives).
    _replicated_key: tuple | None = None

    def __init__(
        self,
        tf_args: Any = None,
        export_fn: Callable[[Namespace], tuple[Callable, Any]] | None = None,
        **params,
    ):
        self.export_fn = export_fn
        self.args = self._init_params(tf_args, params)

    def _load(self):
        """Model-load singleton (reference: ``_get_saved_model_session``)."""
        import jax

        args = self.args
        export_dir = args.export_dir or args.model_dir
        if export_dir is None:
            raise ValueError("TFModel needs export_dir or model_dir")
        if self.export_fn is None:
            from tensorflowonspark_tpu.api import export as aot_export

            if not aot_export.is_aot_export(export_dir):
                raise ValueError(
                    "TFModel needs export_fn=(args)->(apply_fn, target_state) "
                    "to rebuild the model, or an export_dir written by "
                    "api.export.export_model (a self-describing AOT artifact, "
                    "the SavedModel-signature analog)"
                )
            try:
                mtime = os.path.getmtime(export_dir)
            except OSError:
                mtime = None
            key = (export_dir, "aot", mtime)
            if TFModel._singleton_key != key:
                aot = aot_export.load_model(export_dir)
                TFModel._singleton = (
                    lambda state, batch: aot(batch),
                    aot.state,
                )
                TFModel._singleton_key = key
                TFModel._singleton_shardable = False
                TFModel._singleton_aot_mappings = (
                    aot.input_mapping,
                    aot.output_mapping,
                )
            if args.input_mapping is None:
                args.input_mapping = TFModel._singleton_aot_mappings[0]
            if args.output_mapping is None:
                args.output_mapping = TFModel._singleton_aot_mappings[1]
            return TFModel._singleton
        # Key by checkpoint mtime and export_fn identity too, so refitting
        # into the same directory (or swapping export_fn) invalidates the
        # cached model instead of serving stale predictions.
        try:
            mtime = os.path.getmtime(export_dir)
        except OSError:
            mtime = None
        key = (export_dir, id(self.export_fn), mtime)
        if TFModel._singleton_key != key:
            from tensorflowonspark_tpu.compute.checkpoint import (
                restore_checkpoint,
            )

            apply_fn, target = self.export_fn(args)
            state = restore_checkpoint(export_dir, target=target)
            TFModel._singleton = (jax.jit(apply_fn), state)
            TFModel._singleton_key = key
            TFModel._singleton_shardable = True
        return TFModel._singleton

    def transform(self, data: Iterable, launcher=None, env=None) -> list[Any]:
        """Map records through the model in batches, preserving order.

        Materializes :meth:`transform_iter`'s stream into a list — use
        the iterator directly when the OUTPUT is also too big to hold.
        """
        return list(self.transform_iter(data, launcher=launcher, env=env))

    def transform_iter(self, data: Iterable, launcher=None, env=None):
        """Streaming transform: yields one result per input record, in
        order, consuming ``data`` incrementally batch-by-batch — O(batch)
        resident input, never O(dataset) (the scale contract the
        reference got from ``mapPartitions``, SURVEY §3.4).

        ``cluster_size > 1`` scales out like the reference's
        ``TFModel._transform`` (which ran ``_run_model`` on every
        executor over its partitions, ``pipeline.py`` §3.4): a cluster
        of worker processes each load the model ONCE (per-node
        singleton) and serve batch-sized partitions through the
        order-preserving ``cluster.inference_stream`` plumbing.
        ``launcher``/``env`` pass through to ``tfcluster.run`` in that
        mode.

        Single-process (``cluster_size == 1``): on multi-device hosts
        the export_fn path runs data-parallel — each batch is sharded
        over the local devices (ragged tails padded with the last
        record, trimmed from the output). AOT artifacts replay a fixed
        StableHLO program and keep single-device placement.
        """
        if int(self.args.cluster_size) > 1:
            yield from self._transform_distributed_iter(data, launcher, env)
            return
        import jax as _jax

        apply_fn, state = self._load()
        args = self.args
        batch_size = int(args.batch_size)
        dc = _jax.local_device_count()
        shard = TFModel._singleton_shardable and dc > 1
        if shard:
            from tensorflowonspark_tpu.compute.mesh import (
                make_mesh,
                replicated,
                shard_batch,
            )

            mesh = make_mesh({"data": dc}, devices=_jax.local_devices())
            # The restored state sits committed on device 0; a batch that
            # spans the mesh needs it replicated across every device. Done
            # once per loaded model, and written back into the singleton so
            # the device-0-only copy is dropped (keeping both would double
            # device-0 memory).
            rkey = (TFModel._singleton_key, dc)
            if TFModel._replicated_key != rkey:
                state = _jax.device_put(state, replicated(mesh))
                TFModel._singleton = (apply_fn, state)
                TFModel._replicated_key = rkey
            else:
                state = TFModel._singleton[1]
        from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher

        def host_batches():
            for chunk in _chunked(data, batch_size):
                n = len(chunk)
                if shard and n % dc:
                    chunk = list(chunk) + [chunk[-1]] * (dc - n % dc)
                yield self._columnize(chunk), n

        if shard:
            transfer = lambda b: shard_batch(mesh, b)  # noqa: E731
        else:
            transfer = _jax.device_put
        batches = host_batches()
        first = next(batches, None)
        if first is None:
            return
        second = next(batches, None)
        if second is None:
            # Single chunk (the per-fed-batch _transform_node_fn hot
            # path): there is no chunk N+1 to prefetch, so skip the
            # producer thread + queue round-trip and transfer inline.
            cols, n = first
            yield from self._rowize(apply_fn(state, transfer(cols)), n)
            return
        import itertools as _it

        # Columnize + H2D of chunk N+1 runs on the prefetcher's producer
        # thread while apply_fn(chunk N) computes — the transfer fully
        # hides behind step compute instead of serializing with it.
        pf = DevicePrefetcher(
            _it.chain([first, second], batches),
            depth=2,
            transform=lambda item: (transfer(item[0]), item[1]),
        )
        try:
            for batch, n in pf:
                result = apply_fn(state, batch)
                yield from self._rowize(result, n)
        finally:
            pf.close()

    def _transform_distributed_iter(self, data: Iterable, launcher, env):
        """Scale-out transform over a cluster of per-node model singletons."""
        import itertools

        from tensorflowonspark_tpu.cluster import tfcluster
        from tensorflowonspark_tpu.cluster.tfcluster import InputMode

        # env (an inert dict) is inherited from fit; a launcher INSTANCE
        # is not — launchers are single-use (their proc tables outlive a
        # cluster; see run_with_restarts' fresh-launcher requirement), so
        # scaled-out transform over custom hosts takes its own launcher.
        if env is None:
            env = getattr(self, "_fit_env", None)
        node_args = Namespace(dict(self.args))
        # the node runs the LOCAL path; without this every node would
        # recursively launch its own cluster
        node_args["cluster_size"] = 1
        # module-level export_fns pickle by qualified name to the
        # spawned node processes, exactly like the map_fun itself
        node_args["_export_fn"] = self.export_fn
        # Batch-sized partitions, every element a RECORD, pulled lazily:
        # inference_stream takes partitions as-is, so list-typed records
        # can't be reinterpreted as partitions (the _as_partitions
        # hazard), and its backpressure caps how far workers run ahead
        # of the consumer.
        cluster_size = int(self.args.cluster_size)
        chunks = _chunked(data, int(self.args.batch_size))
        # Peek up to cluster_size chunks: short datasets shouldn't pay
        # whole-cluster startup for workers that would get no records.
        head = list(itertools.islice(chunks, cluster_size))
        if not head:
            return
        cluster = tfcluster.run(
            _transform_node_fn,
            node_args,
            num_executors=len(head),  # islice caps this at cluster_size
            input_mode=InputMode.SPARK,
            reservation_timeout=float(self.args.reservation_timeout),
            launcher=launcher,
            env=env,
        )
        try:
            yield from cluster.inference_stream(
                itertools.chain(head, chunks)
            )
        finally:
            cluster.shutdown(grace_secs=float(self.args.grace_secs))

    def _columnize(self, chunk: Sequence[Any]):
        return columnize(chunk, self.args.input_mapping)

    def _rowize(self, result: Any, n: int) -> list[Any]:
        return rowize(result, n, self.args.output_mapping)


def _transform_node_fn(args, ctx):
    """Per-node worker for the distributed :meth:`TFModel.transform`.

    Loads the model once (the TFModel singleton lives per node process —
    the reference's per-executor SavedModel-session pattern), then serves
    fed partitions through the equal-count inference contract: exactly
    one result per input record, in order.
    """
    export_fn = args.pop("_export_fn", None)
    model = TFModel(args, export_fn=export_fn)
    feed = ctx.get_data_feed(train_mode=False)
    batch_size = int(args.batch_size)
    # Per fed batch, lock-step: inference_stream's backpressure window
    # assumes a node emits results for batch N before pulling far past
    # it, so the whole-feed prefetcher look-ahead of transform_iter
    # (fine for local data) must NOT wrap the feed here.
    while not feed.should_stop():
        batch = feed.next_batch(batch_size)
        if batch:
            feed.batch_results(model.transform(batch))


def _chunked(data: Iterable, n: int):
    """Lazily batch an iterable into lists of ``n`` (last may be short)."""
    import itertools

    it = iter(data)
    while True:
        chunk = list(itertools.islice(it, n))
        if not chunk:
            return
        yield chunk


def columnize(chunk: Sequence[Any], mapping: dict[str, str] | None):
    """Rows → named (or bare) input arrays per ``input_mapping``.

    The mapping path (positional contract for tuple records, loud
    missing-field errors for dict records) is the shared
    ``feed.datafeed.columnize_rows`` — one implementation for the feed
    and pipeline planes."""
    if mapping is None:
        return np.asarray(chunk)
    from tensorflowonspark_tpu.feed.datafeed import columnize_rows

    return columnize_rows(chunk, mapping)


def rowize(result: Any, n: int, mapping: dict[str, str] | None) -> list[Any]:
    """Model output → per-row results per ``output_mapping``."""
    if mapping is None:
        arr = np.asarray(result)
        return [arr[i] for i in range(n)]
    named = {
        out_col: np.asarray(result[tensor]) for tensor, out_col in mapping.items()
    }
    return [{col: vals[i] for col, vals in named.items()} for i in range(n)]
