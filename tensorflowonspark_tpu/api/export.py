"""Self-contained model export + AOT batch inference.

Reference parity: the Scala inference API
(``src/main/scala/com/yahoo/tensorflowonspark/TFModel.scala`` + ``DFUtil``,
SURVEY.md §2.2) — DataFrame batch inference from a SavedModel with *no user
Python code*, via TF Java's ``SavedModelBundle``. The TPU-native artifact is:

- ``stablehlo.bin`` — a :mod:`jax.export` serialization of the apply
  function (StableHLO, language-neutral, loadable from any PJRT frontend),
  batch-dimension-polymorphic so one artifact serves any batch size;
- ``params/`` — the model state as an orbax checkpoint;
- ``aot_meta.json`` — input/output column↔tensor mappings and provenance,
  the analog of a SavedModel's signature-def (reference:
  ``pipeline.py:TFModel`` signature/tag params).

``python -m tensorflowonspark_tpu.tools.run_model`` is the no-user-code
entry: TFRecords in → TFRecords/JSONL out, like the Scala API's
DataFrame → DataFrame ``transform``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Iterable, Sequence

import numpy as np

_STABLEHLO = "stablehlo.bin"
_META = "aot_meta.json"
_PARAMS = "params"


def export_model(
    apply_fn: Callable[[Any, Any], Any],
    state: Any,
    example_batch: Any,
    export_dir: str,
    input_mapping: dict[str, str] | None = None,
    output_mapping: dict[str, str] | None = None,
    platforms: Sequence[str] | None = None,
) -> str:
    """Serialize ``apply_fn(state, batch)`` + ``state`` into ``export_dir``.

    ``example_batch`` fixes every shape except the leading (batch) dim of
    each batch leaf, which is exported symbolically. ``platforms`` defaults
    to the current default export platform; pass ``("cpu", "tpu")`` for an
    artifact that runs on either.
    """
    import jax
    import jax.export as jex

    from tensorflowonspark_tpu.compute.checkpoint import save_checkpoint

    scope = jex.SymbolicScope()
    (b,) = jex.symbolic_shape("b", scope=scope)
    batch_specs = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            (b,) + np.shape(x)[1:], np.asarray(x).dtype
        ),
        example_batch,
    )
    state_specs = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        state,
    )
    kwargs = {"platforms": tuple(platforms)} if platforms else {}
    exported = jex.export(jax.jit(apply_fn), **kwargs)(
        state_specs, batch_specs
    )

    os.makedirs(export_dir, exist_ok=True)
    with open(os.path.join(export_dir, _STABLEHLO), "wb") as f:
        f.write(exported.serialize())
    save_checkpoint(os.path.join(export_dir, _PARAMS), state)
    with open(os.path.join(export_dir, _META), "w") as f:
        json.dump(
            {
                "input_mapping": input_mapping,
                "output_mapping": output_mapping,
                "platforms": list(exported.platforms),
                "jax_version": jax.__version__,
            },
            f,
            indent=2,
        )
    return export_dir


def is_aot_export(path: str) -> bool:
    return os.path.isfile(os.path.join(path, _STABLEHLO))


class AOTModel:
    """A loaded export: callable on batches, knows its column mappings."""

    def __init__(self, exported, state: Any, meta: dict[str, Any]):
        import jax

        self._exported = exported
        # jit once: per-call jax.jit(...) would rebuild the wrapper (and its
        # trace/compile cache) for every batch.
        self._call = jax.jit(exported.call)
        self.state = state
        self.meta = meta
        self.input_mapping = meta.get("input_mapping")
        self.output_mapping = meta.get("output_mapping")

    def __call__(self, batch: Any) -> Any:
        return self._call(self.state, batch)

    def transform(
        self, records: Iterable[Any], batch_size: int = 64
    ) -> list[Any]:
        """Batch rows through the model, preserving order (equal-count
        contract, like ``TFModel.transform``)."""
        from tensorflowonspark_tpu.api.pipeline import columnize, rowize

        records = list(records)
        out: list[Any] = []
        for start in range(0, len(records), batch_size):
            chunk = records[start : start + batch_size]
            batch = columnize(chunk, self.input_mapping)
            out.extend(rowize(self(batch), len(chunk), self.output_mapping))
        return out


def load_model(export_dir: str) -> AOTModel:
    """Load an :func:`export_model` artifact. No user code needed — the
    function, weights, and signature all come from the artifact."""
    import jax.export as jex

    from tensorflowonspark_tpu.compute.checkpoint import restore_checkpoint

    with open(os.path.join(export_dir, _STABLEHLO), "rb") as f:
        exported = jex.deserialize(f.read())
    with open(os.path.join(export_dir, _META)) as f:
        meta = json.load(f)
    state = restore_checkpoint(os.path.join(export_dir, _PARAMS))
    return AOTModel(exported, state, meta)


CPP_RUNNER_MANIFEST = "cpp_runner_manifest.txt"

# TF DataType enum -> numpy-style name (the values the C runner maps back
# to TF_* dtypes; tensorflow/core/framework/types.proto)
_TF_DTYPE_NAMES = {
    1: "float32", 2: "float64", 3: "int32", 4: "uint8", 9: "int64",
    10: "bool", 14: "bfloat16",
}


def export_tf_saved_model(
    apply_fn: Callable[[Any, Any], Any],
    state: Any,
    example_batch: Any,
    export_dir: str,
) -> str:
    """Export as a TensorFlow SavedModel via ``jax2tf`` (TF-serving interop;
    the closest analog of the artifact the reference's Scala API consumed).
    Requires the optional TensorFlow install.

    Besides the SavedModel itself, writes ``cpp_runner_manifest.txt`` —
    the serving_default signature's tensor names and dtypes in a plain
    line format — so the no-Python C++ runner (``native/aot_runner.cc``)
    can bind inputs/outputs without parsing protos."""
    import tensorflow as tf
    from jax.experimental import jax2tf

    tf_fn = tf.function(
        jax2tf.convert(
            lambda batch: apply_fn(state, batch), polymorphic_shapes="(b, ...)"
        ),
        autograph=False,
        input_signature=[
            tf.TensorSpec(
                (None,) + np.shape(example_batch)[1:],
                np.asarray(example_batch).dtype.name,
            )
        ],
    )
    module = tf.Module()
    module.f = tf_fn
    tf.saved_model.save(module, export_dir)
    _write_cpp_runner_manifest(export_dir)
    return export_dir


def _write_cpp_runner_manifest(export_dir: str) -> None:
    from tensorflow.python.tools import saved_model_utils

    meta = saved_model_utils.get_meta_graph_def(export_dir, "serve")
    sig = meta.signature_def["serving_default"]
    lines = ["signature serving_default"]
    for kind, entries in (("input", sig.inputs), ("output", sig.outputs)):
        for key in sorted(entries):
            v = entries[key]
            dtype = _TF_DTYPE_NAMES.get(int(v.dtype), str(int(v.dtype)))
            lines.append(f"{kind} {key} {v.name} {dtype}")
    with open(os.path.join(export_dir, CPP_RUNNER_MANIFEST), "w") as f:
        f.write("\n".join(lines) + "\n")
