"""High-level estimator-style API (reference: ``tensorflowonspark/pipeline.py``)."""

from tensorflowonspark_tpu.api.pipeline import TFEstimator, TFModel, Namespace

__all__ = ["TFEstimator", "TFModel", "Namespace"]
