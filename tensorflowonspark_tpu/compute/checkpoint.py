"""Checkpoint / restore via orbax.

Reference parity (SURVEY.md §5.4): the reference delegated checkpointing to
TF (``ModelCheckpoint``/``BackupAndRestore``) and contributed pathing plus a
chief-only export convention. Here orbax gives async + sharded checkpoints;
the chief-writes convention is enforced by the caller
(``TFNodeContext.export_saved_model``).

Sharded-state contract: save/restore is placement-agnostic — a
ZeRO-partitioned optimizer tree (Adam moments / mixed-precision masters
data-axis sharded per ``LAYOUT_TABLES['optimizer']``) round-trips
byte-identically, with restore committing each array to the TARGET's
sharding (so restoring into a ``shard_state(..., zero_sharding=...)``
target reproduces either knob setting's placement regardless of which
one wrote the checkpoint). Pinned by tests/test_elastic.py's orbax
round-trip of a ZeRO-sharded TrainState.
"""

from __future__ import annotations

import os
from typing import Any

import orbax.checkpoint as ocp

from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.utils.failpoints import FailpointError, failpoint
from tensorflowonspark_tpu.utils.retry import RetryPolicy

# Orbax IO rides shared filesystems (GCS/NFS) whose transient errors are
# routine at pod scale; retry them with backoff rather than failing a
# multi-hour training step. Injected FailpointErrors are retryable here
# so chaos runs can exercise exactly this path.
_IO_RETRY = RetryPolicy(max_attempts=3, base_delay=0.2, max_delay=5.0)
_IO_RETRYABLE = (OSError, ConnectionError, TimeoutError, FailpointError)


def _abs(path: str) -> str:
    if "://" in path:
        return path
    return os.path.abspath(path)


def _canonicalize_leaves(state: Any) -> Any:
    """Version shim (the ``utils/compat.py`` pattern): current orbax's
    StandardSave validator rejects numpy *scalar* leaves (``np.float32``,
    ``np.int64``, ``np.bool_`` — the types a host-side metrics dict or a
    ``jax.device_get`` of a 0-d array naturally produces) while accepting
    0-d ``np.ndarray``s of the same dtype. Canonicalize scalars to 0-d
    arrays at every save boundary; dtype and value round-trip, and orbax
    versions that accepted scalars store the identical array."""
    import jax
    import numpy as np

    return jax.tree.map(
        lambda x: np.asarray(x) if isinstance(x, np.generic) else x, state
    )


def checkpoint_complete(path: str) -> bool:
    """True iff ``path`` holds a COMMITTED orbax checkpoint.

    The serving rollout channel (``serving/rollout.py``) publishes
    checkpoint directories to live engines; a torn or in-progress write
    must never be hot-swapped into a serving fleet. Two signals, both
    required: the directory exists under its FINAL name (orbax writes
    into a ``*.orbax-checkpoint-tmp-*`` directory and renames at
    commit — on posix the final name existing IS the commit), and the
    ``_CHECKPOINT_METADATA`` finalization marker is present (guards
    partially-copied directories, e.g. an interrupted rsync between
    filesystems, where the rename atomicity did not travel).

    Remote URIs (``gs://...`` and friends) cannot be probed with local
    filesystem calls: the tmp-name rejection still applies (orbax's
    rename-at-commit naming travels with the store), but a final-named
    remote path is TRUSTED — the publisher's contract is to publish
    only after the save fully landed (``CheckpointManager.wait()``)."""
    path = _abs(path)
    if "orbax-checkpoint-tmp" in os.path.basename(path.rstrip("/")):
        return False
    if "://" in path:
        return True
    if not os.path.isdir(path):
        return False
    return os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA"))


def save_checkpoint(path: str, state: Any, force: bool = True) -> str:
    """Synchronously write ``state`` (any pytree) to ``path``."""
    path = _abs(path)
    state = _canonicalize_leaves(state)
    with obs_spans.span("train.checkpoint"):
        with ocp.StandardCheckpointer() as ckptr:

            def do_save():
                failpoint("checkpoint.save")
                ckptr.save(path, state, force=force)

            _IO_RETRY.call(
                do_save, retry_on=_IO_RETRYABLE, site="checkpoint.save"
            )
    return path


def restore_checkpoint(path: str, target: Any | None = None) -> Any:
    """Restore a pytree; ``target`` (abstract or concrete) pins structure,
    dtypes, and — when built from abstract arrays with shardings — the
    placement of restored arrays on the mesh."""
    path = _abs(path)
    with ocp.StandardCheckpointer() as ckptr:
        if target is None:
            def do_restore():
                failpoint("checkpoint.restore")
                return ckptr.restore(path)

        else:
            import jax

            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)

            def do_restore():
                failpoint("checkpoint.restore")
                return ckptr.restore(path, abstract)

        return _IO_RETRY.call(
            do_restore, retry_on=_IO_RETRYABLE, site="checkpoint.restore"
        )


class CheckpointManager:
    """Step-numbered checkpoints with retention + async write.

    The async writer overlaps checkpoint I/O with the next training steps —
    part of the MFU recipe (SURVEY.md §7 "hard parts").
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        async_save: bool = True,
        save_interval_steps: int = 1,
        keep_best_metric: str | None = None,
        keep_best_mode: str = "min",
    ):
        """``save_interval_steps``: calls to :meth:`save` off the interval
        are no-ops returning False (callers can save unconditionally every
        step and let the policy decide). ``keep_best_metric``: retain the
        ``max_to_keep`` checkpoints with the best value of that key in the
        metrics dict passed to :meth:`save` (``keep_best_mode`` 'min' for
        losses, 'max' for accuracies) instead of the most recent ones.
        """
        self.directory = _abs(directory)
        if keep_best_mode not in ("min", "max"):
            raise ValueError("keep_best_mode must be 'min' or 'max'")
        best: dict[str, Any] = {}
        if keep_best_metric is not None:
            best = dict(
                best_fn=lambda metrics: metrics[keep_best_metric],
                best_mode=keep_best_mode,
            )
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
            save_interval_steps=save_interval_steps,
            **best,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(
        self,
        step: int,
        state: Any,
        metrics: dict[str, Any] | None = None,
        force: bool = False,
    ) -> bool:
        """``force=True`` bypasses the save-interval policy (use for the
        end-of-training save, which must land regardless of interval)."""
        # The span measures the BLOCKING portion only: with async_save
        # the actual I/O overlaps subsequent steps, and the interesting
        # host cost is exactly how long the training loop stalled here.
        state = _canonicalize_leaves(state)
        with obs_spans.span("train.checkpoint", step=step):

            def do_save():
                failpoint("checkpoint.save")
                return self._mgr.save(
                    step,
                    args=ocp.args.StandardSave(state),
                    metrics=metrics,
                    force=force,
                )

            return _IO_RETRY.call(
                do_save, retry_on=_IO_RETRYABLE, site="checkpoint.save"
            )

    def restore(self, step: int | None = None, target: Any | None = None) -> Any:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if target is not None:
            import jax

            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)

            def do_restore():
                failpoint("checkpoint.restore")
                return self._mgr.restore(
                    step, args=ocp.args.StandardRestore(abstract)
                )

        else:

            def do_restore():
                failpoint("checkpoint.restore")
                try:
                    return self._mgr.restore(step)
                except KeyError:
                    # Layout drift shim (the utils/compat.py probe
                    # pattern): a CheckpointManager-written step stores
                    # its tree under the composite item name "default",
                    # and current orbax refuses an args-less restore on
                    # a manager that has not saved in this process ("no
                    # handler registered for item 'default'"). Naming
                    # the handler explicitly restores the same tree on
                    # every orbax version that has StandardRestore.
                    return self._mgr.restore(
                        step, args=ocp.args.StandardRestore()
                    )

        return _IO_RETRY.call(
            do_restore, retry_on=_IO_RETRYABLE, site="checkpoint.restore"
        )

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def step_path(self, step: int) -> str:
        """Directory of one saved step (the unit the rollout channel
        publishes: ``serving.rollout.publish_checkpoint(path=
        mgr.step_path(step), ...)`` after :meth:`wait`)."""
        return os.path.join(self.directory, str(int(step)))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.wait()
        self.close()


def restore_latest(ckpt: CheckpointManager, target: Any):
    """Resume convention: restore the newest checkpoint into ``target``'s
    structure. Returns ``(step, restored)``, or ``(None, target)`` when
    the directory has no checkpoints. A structure mismatch (e.g. a
    directory written by different code) fails with a clear error
    instead of an orbax tree-diff traceback."""
    step = ckpt.latest_step()
    if step is None:
        return None, target
    try:
        return step, ckpt.restore(step, target=target)
    except Exception as e:
        # Only claim "wrong trainer" when the stored tree's top-level
        # keys genuinely differ from the target's; any other failure
        # (IO, partial step dir, truncated arrays) propagates unchanged
        # so operators retry instead of deleting good checkpoints.
        stored_keys = _stored_top_level_keys(ckpt, step)
        if (
            isinstance(target, dict)
            and stored_keys is not None
            and stored_keys != set(target)
        ):
            raise ValueError(
                f"checkpoint step {step} in {ckpt.directory} has keys "
                f"{sorted(stored_keys)} but this trainer expects "
                f"{sorted(target)}; it was written by a different trainer "
                "— delete the directory or point the model dir elsewhere"
            ) from e
        raise


def _stored_top_level_keys(ckpt: CheckpointManager, step: int):
    """Top-level keys of a stored checkpoint's tree, or None if the
    metadata cannot be read (caller treats that as 'unknown')."""
    try:
        meta = ckpt._mgr.item_metadata(step)
        tree = getattr(meta, "tree", meta)
        return set(tree) if isinstance(tree, dict) else None
    except Exception:
        return None


def hydration_restore(directory: str, target: Any):
    """Elastic-rejoin fallback: restore the newest checkpoint under
    ``directory`` into ``target``'s structure. Returns ``(step,
    state)`` or ``(None, None)`` when the directory holds no
    checkpoints (including a directory that does not exist yet — a
    joiner probing an optional fallback must not crash on it).

    This is the "checkpoint restore is the fallback, not the recovery
    path" half of the elastic contract (compute/elastic.py): peers'
    in-memory state is tried first; only when that is impossible does
    the joiner pay a full checkpoint read.
    """
    with CheckpointManager(directory) as ckpt:
        step, state = restore_latest(ckpt, target)
        if step is None:
            return None, None
        return step, state


def saves_on_this_process(is_chief: bool) -> bool:
    """Which processes must call ``save`` (and ``wait``):

    - **Single-controller** (``jax.process_count() == 1`` — e.g. the local
      launcher, where every node is an independent JAX runtime holding a
      full replica): chief only. Concurrent saves of the same fully-
      addressable state to one orbax directory would race.
    - **Multi-controller** (``jax.distributed`` initialized,
      ``process_count > 1``): EVERY process. State is jax.Arrays sharded
      across processes; orbax save/restore of non-fully-addressable
      arrays is a collective — each process writes its addressable
      shards and process 0 coordinates the commit. A chief-only save
      there raises or hangs.

    Gate *logging* on ``is_chief``; gate *saving* on this.
    """
    import jax

    return is_chief or jax.process_count() > 1


def _final_save_needed(ckpt: CheckpointManager, step: int) -> bool:
    """Collectively consistent "does the final save still need to run".

    Under multi-controller, the save of cross-process-sharded arrays is a
    collective — every process must enter it or none. A per-process
    ``latest_step() != step`` check can disagree across processes on
    eventually-consistent shared filesystems (GCS/NFS): some would enter
    the collective save and others skip, deadlocking the job. Process 0's
    view is authoritative (orbax's commit is coordinated by process 0, so
    if process 0 sees the step landed, every process participated in that
    save) and is broadcast to all."""
    import jax

    needed = ckpt.latest_step() != step
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        needed = bool(
            multihost_utils.broadcast_one_to_all(
                np.asarray(needed, dtype=np.int32)
            )
        )
    return needed


def chief_final_save(
    ckpt: CheckpointManager, state: Any, step: int, is_chief: bool
) -> None:
    """End-of-training save convention: forced past any save-interval
    policy, and skipped when a previous attempt (e.g. a
    ``run_with_restarts`` relaunch or an in-loop interval save) already
    landed this step (``force=True`` also makes a redundant save on a
    stale-FS miss an overwrite, not an error).

    "chief" in the name is the single-controller convention; under
    multi-controller (``jax.process_count() > 1``) the save runs on
    every process because sharded-state checkpointing is a collective
    (see :func:`saves_on_this_process`), and the skip decision is made
    collectively (see :func:`_final_save_needed`) so no process enters
    the collective alone. Every process closes the manager."""
    if saves_on_this_process(is_chief):
        ckpt.wait()  # async in-loop saves may still be landing
        if _final_save_needed(ckpt, step):
            ckpt.save(step, state, force=True)
    ckpt.close()
