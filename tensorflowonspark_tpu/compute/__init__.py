"""TPU compute layer: device mesh, sharded train steps, checkpointing.

This layer replaces the reference's delegation to TensorFlow's distributed
runtime (PS + MultiWorkerMirroredStrategy, SURVEY.md §2.3): data-parallel
and FSDP training are expressed as ``jax.jit`` over a ``Mesh`` with
``NamedSharding``; XLA inserts the collectives (psum over ICI) that NCCL
all-reduce performed in the reference.
"""

from tensorflowonspark_tpu.compute.elastic import (
    ElasticTrainer,
    host_snapshot,
    reshard_state,
)
from tensorflowonspark_tpu.compute.layout import (
    LAYOUT_TABLES,
    SpecLayout,
    get_layout,
    optimizer_state_spec,
    param_shardings,
)
from tensorflowonspark_tpu.compute.mesh import (
    MESH_AXES,
    fit_axis_shapes,
    make_mesh,
    batch_sharding,
    replicated,
)
from tensorflowonspark_tpu.compute.optim import (
    adamw,
    mixed_precision_adamw,
)
from tensorflowonspark_tpu.compute.train import (
    TrainState,
    build_train_step,
    build_eval_step,
    build_update_step,
    fsdp_shardings,
    shard_state,
    state_shardings,
    zero_update_shardings,
)

__all__ = [
    "LAYOUT_TABLES",
    "MESH_AXES",
    "SpecLayout",
    "get_layout",
    "optimizer_state_spec",
    "param_shardings",
    "ElasticTrainer",
    "host_snapshot",
    "reshard_state",
    "fit_axis_shapes",
    "make_mesh",
    "batch_sharding",
    "replicated",
    "TrainState",
    "build_train_step",
    "build_eval_step",
    "build_update_step",
    "fsdp_shardings",
    "shard_state",
    "state_shardings",
    "zero_update_shardings",
    "adamw",
    "mixed_precision_adamw",
]
