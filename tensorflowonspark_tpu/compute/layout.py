"""The declarative sharding layout table — THE source of truth for specs.

Before this module, PartitionSpecs were hand-built in 18 sites across
compute/, parallel/, models/, serving/ and tools/, so nothing could
prove a layout change was consistent or that a jitted step wasn't
paying hidden all-gathers (the 57–58 % MFU plateau of ROADMAP item 2).
TF-Replicator's argument (PAPERS.md, arXiv 1902.00465) applies
structurally: replica placement/layout must be a *declared, checkable
artifact*, not a convention scattered through model code. This module
is that artifact, in three parts:

- **Declarative tables** (:data:`LAYOUT_TABLES`, :data:`ACTIVATION_SPECS`,
  :data:`DECODE_CACHE_SPECS`, :data:`SERVE_CACHE_SPECS`) — *pure
  literals*, deliberately: the ``analysis/sharding.py`` static head
  (SH001–SH004) reads them by AST parse without importing jax, so a
  layout edit and its lint gate can never drift apart. Every axis name
  used anywhere in the package must be declared in :data:`MESH_AXES`
  (SH002), and every ``with_sharding_constraint`` literal must match a
  declared rule (SH004).
- **The rule engine** (:class:`SpecLayout`, :func:`param_shardings`) —
  first-match-wins name-pattern → PartitionSpec evaluation with
  per-table divisibility semantics, replacing each model's hand-rolled
  ``*_param_shardings``.
- **Role helpers** (:func:`batch_sharding`, :func:`replicated`,
  :func:`decode_cache_sharding`, :func:`tp_only`, …) — the only
  functions in the package allowed to construct ``PartitionSpec`` /
  ``NamedSharding`` (SH001 flags raw construction anywhere else;
  escape: ``# lint: layout-ok: <why>``).

``tools/shardcheck.py`` closes the loop dynamically: it lowers the
train step against these tables and diffs the collective census
against a committed baseline, so an unintended all-gather introduced
by a table edit becomes a tier-1 diff, not a silent MFU regression.
"""

from __future__ import annotations

import math
import re
from typing import Any, Mapping

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "ACTIVATION_SPECS",
    "BATCH_AXES",
    "DECODE_CACHE_SPECS",
    "LAYOUT_TABLES",
    "MESH_AXES",
    "OPTIMIZER_PARAM_STATE_PATTERN",
    "SERVE_CACHE_SPECS",
    "SpecLayout",
    "activation_sharding",
    "activation_spec",
    "batch_sharding",
    "batch_spec",
    "decode_cache_sharding",
    "decode_cache_spec",
    "expert_bank_spec",
    "fsdp_leaf_sharding",
    "fsdp_leaf_spec",
    "get_layout",
    "optimizer_state_sharding",
    "optimizer_state_spec",
    "param_shardings",
    "replicated",
    "serve_cache_sharding",
    "serve_cache_spec",
    "sharding",
    "tp_only",
]

# ---------------------------------------------------------------------------
# Declared axes (SURVEY.md §7 step 3). SH002 rejects any spec axis name
# not listed here. Keep these literals — the analyzer ast-parses them.
# ---------------------------------------------------------------------------

# - ``data``  — pure data parallel (replicated params, sharded batch)
# - ``fsdp``  — data parallel with sharded params/optimizer state
# - ``pipe``  — pipeline parallel (parallel/pipeline.py)
# - ``expert`` — expert parallel (parallel/moe.py)
# - ``model`` — tensor parallel (Megatron column/row shardings)
# - ``seq``   — sequence/context parallel (parallel/ring_attention.py)
MESH_AXES = ("data", "fsdp", "pipe", "expert", "model", "seq")

# Batch dimension shards over every data-like axis.
BATCH_AXES = ("data", "fsdp")

# ---------------------------------------------------------------------------
# Name-pattern → PartitionSpec tables (pure literals; analyzer-readable).
#
# Rule keys:
#   pattern    — regex, re.search()ed against the '/'-joined param path
#                (dict keys AND dataclass-leaf attr names, so a LoRA
#                factor inside a wrapped kernel reads
#                'layer0/attn/q_proj/kernel/a')
#   spec       — per-dim axis assignment: an axis name, None, or a
#                tuple of axis names (multi-axis dim)
#   ndim       — rule applies only to leaves of exactly this rank
#   max_ndim   — … of at most this rank
#   divisible  — divisibility semantics for named dims:
#                  "strict"  (default) spec applies as-is; divisibility
#                            is the caller's contract (llama raises at
#                            device_put, by design)
#                  "require" rule matches only if every named dim
#                            divides its axis extent, else fall through
#                            to the next rule (bert/resnet/unet)
#                  "drop_or_unit" keep the rule but null out any axis
#                            whose extent is 1 or does not divide the
#                            dim (vit)
#
# First match wins; every table MUST end in a catch-all. Editing a rule
# here is the whole blast radius of a layout change — the SH static
# head checks consistency, tools/shardcheck.py diffs the resulting
# collective census against its committed baseline.
# ---------------------------------------------------------------------------

LAYOUT_TABLES = {
    # Megatron layout on ('fsdp', 'model'); biases/norms replicated.
    # With mesh model=1 this degrades to pure FSDP (the Llama-2-7B
    # baseline config); with fsdp=1 to pure TP. LoRA factors inside a
    # wrapped kernel: the base shards like the kernel it replaces; 'a'
    # (in, r) keeps the input half of the base pair, 'b' (r, out) the
    # output half — consistent with the TP math (the rank dim stays
    # replicated; it is tiny by construction). For a multi-LoRA BANK
    # the same halves apply behind the leading K slots dim (replicated
    # — every chip serves every adapter).
    "llama": (
        {"pattern": r".*", "max_ndim": 1, "spec": ()},
        # LoRA 'a' factors: input half of the enclosing kernel's pair
        {"pattern": r".*(o_proj|down_proj).*/a$", "ndim": 2,
         "spec": ("model", None)},
        {"pattern": r".*/a$", "ndim": 2, "spec": ("fsdp", None)},
        {"pattern": r".*(o_proj|down_proj).*/a$", "ndim": 3,
         "spec": (None, "model", None)},
        {"pattern": r".*/a$", "ndim": 3, "spec": (None, "fsdp", None)},
        # LoRA 'b' factors: output half
        {"pattern":
         r".*(embed|lm_head|q_proj|k_proj|v_proj|gate_proj|up_proj).*/b$",
         "ndim": 2, "spec": (None, "model")},
        {"pattern": r".*(o_proj|down_proj).*/b$", "ndim": 2,
         "spec": (None, "fsdp")},
        {"pattern": r".*/b$", "ndim": 2, "spec": ()},
        {"pattern":
         r".*(embed|lm_head|q_proj|k_proj|v_proj|gate_proj|up_proj).*/b$",
         "ndim": 3, "spec": (None, None, "model")},
        {"pattern": r".*(o_proj|down_proj).*/b$", "ndim": 3,
         "spec": (None, None, "fsdp")},
        {"pattern": r".*/b$", "ndim": 3, "spec": ()},
        # MoE expert banks are the remaining ndim-3 leaves: stacked dim
        # on 'expert', FFN hidden on 'model', the rest on 'fsdp'
        {"pattern": r".*w_down.*", "ndim": 3,
         "spec": ("expert", "model", "fsdp")},
        {"pattern": r".*", "ndim": 3, "spec": ("expert", "fsdp", "model")},
        {"pattern": r".*router.*", "spec": ()},
        # column-parallel projections
        {"pattern":
         r".*(embed|lm_head|q_proj|k_proj|v_proj|gate_proj|up_proj).*",
         "spec": ("fsdp", "model")},
        # row-parallel projections
        {"pattern": r".*(o_proj|down_proj).*", "spec": ("model", "fsdp")},
        {"pattern": r".*", "spec": ("fsdp", None)},
    ),
    # Megatron-style rules keyed on bert param names; a rule whose
    # named dims don't divide the mesh extents falls through.
    "bert": (
        {"pattern": r".*(query|key|value|ffn_in).*", "ndim": 2,
         "spec": ("fsdp", "model"), "divisible": "require"},
        {"pattern": r".*(attn_out|ffn_out).*", "ndim": 2,
         "spec": ("model", "fsdp"), "divisible": "require"},
        {"pattern": r".*", "ndim": 2, "spec": ("fsdp", None),
         "divisible": "require"},
        {"pattern": r".*", "spec": ()},
    ),
    # 2D kernels over ('fsdp','model'); a dim that does not divide its
    # mesh axis (or whose axis extent is 1) falls back to replication
    # for THAT dim (e.g. the (hidden, 10) classifier head under
    # model>1) rather than erroring at device_put.
    "vit": (
        {"pattern": r".*", "ndim": 2, "spec": ("fsdp", "model"),
         "divisible": "drop_or_unit"},
        {"pattern": r".*", "ndim": 4,  # patch-embed conv kernel
         "spec": (None, None, None, "model"), "divisible": "drop_or_unit"},
        {"pattern": r".*", "spec": ()},
    ),
    # FSDP rules: shard large kernels' output-channel dim over 'fsdp';
    # replicate BN scale/bias (tiny). Shared by resnet/inception/vgg.
    "resnet": (
        {"pattern": r".*", "ndim": 4, "spec": (None, None, None, "fsdp"),
         "divisible": "require"},
        {"pattern": r".*", "ndim": 2, "spec": ("fsdp", None),
         "divisible": "require"},
        {"pattern": r".*", "spec": ()},
    ),
    # conv kernels' output channels over 'fsdp' where divisible.
    "unet": (
        {"pattern": r".*", "ndim": 4, "spec": (None, None, None, "fsdp"),
         "divisible": "require"},
        {"pattern": r".*", "spec": ()},
    ),
    # MoEMLP param tree: expert banks on ('expert','fsdp'/'model'),
    # router replicated. llama's ndim-3 rules delegate here in spirit —
    # the two tables MUST stay in lockstep (tests/test_layout.py pins
    # them equal).
    "moe": (
        {"pattern": r".*w_down.*", "ndim": 3,
         "spec": ("expert", "model", "fsdp")},
        {"pattern": r".*", "ndim": 3, "spec": ("expert", "fsdp", "model")},
        {"pattern": r".*", "spec": ()},
    ),
    # Optimizer-state rules — the ZeRO-style cross-replica weight-update
    # partition (PAPERS.md, arXiv 2004.13336). Patterns match the
    # '/'-joined opt-state field path PREFIXED to the param path (an
    # Adam moment for a wrapped kernel reads '0/mu/layer0/attn/q_proj/
    # kernel'); unlike the model tables above, a matching rule's spec is
    # MERGED onto the param leaf's own table spec dim-by-dim by
    # :func:`optimizer_state_spec` — the rule names the EXTRA axes the
    # state leaf shards over, not its full layout. Per-param state
    # (Adam moments mu/nu, mixed-precision fp32 masters, SGD momentum
    # traces — and the in-step gradient 'update' tensors feeding them)
    # additionally partitions its leading dim over the 'data' replica
    # axis, so the weight update computes on 1/data_extent of each leaf
    # instead of redundantly on every replica; 'drop_or_unit' keeps the
    # existing divisibility semantics — an indivisible (or data=1) leaf
    # drops back to mirroring its param. Scalars (Adam's bias-correction
    # 'count') and any undeclared field mirror/replicate unchanged.
    "optimizer": (
        {"pattern": r".*", "max_ndim": 0, "spec": ()},
        {"pattern": r"(^|/)(mu|nu|master|trace|momentum|update)(/|$)",
         "spec": ("data",), "divisible": "drop_or_unit"},
        {"pattern": r".*", "spec": ()},
    ),
}

# The optimizer table's per-param-state field pattern, re-declared for
# consumers that need the ROLE without a shape (train.state_shardings'
# explicit mirror-vs-replicate resolution). MUST stay textually equal to
# the 'optimizer' table rule above (tests/test_layout.py pins them; the
# table itself must stay a pure literal for the AST analyzer, so the
# string is duplicated rather than referenced).
OPTIMIZER_PARAM_STATE_PATTERN = (
    r"(^|/)(mu|nu|master|trace|momentum|update)(/|$)"
)

# Activation / host-IO placements, by role.
ACTIVATION_SPECS = {
    # leading (batch) dim over every data-like axis, rest replicated
    "batch": (("data", "fsdp"),),
    # (B, S) token prompts: batch on 'data', positions replicated
    "prompt": ("data", None),
    # (B,) per-row planes (prompt lengths, row flags)
    "per_row": ("data",),
    # scalars / rng keys / whole-tree replication
    "replicated": (),
    # (B, S, H, D) attention operands under mesh flash-attention
    # shard_map: batch over the data axes, heads TP on 'model'
    "attn_bshd": (("data", "fsdp"), None, "model", None),
}

# KV-cache leaves under mesh-sharded decode, keyed by leaf rank:
# K/V (B, S, kv_heads, D) shard batch on 'data' and heads on 'model'
# (each TP shard holds only its heads' cache — the HBM split that makes
# 7B-class serving fit), int8-KV scale planes (B, S, kv_heads) follow
# their heads, the segment-id plane (B, S) shards on 'data', the scalar
# write index replicates.
DECODE_CACHE_SPECS = {
    4: ("data", None, "model", None),
    3: ("data", None, "model"),
    2: ("data", None),
}

# The continuous engine's row-admitted cache: TP on 'model' only, batch
# replicated (row-wise admission keeps the batch axis unsharded).
SERVE_CACHE_SPECS = {
    4: (None, None, "model", None),
    3: (None, None, "model"),
}


# ---------------------------------------------------------------------------
# rule engine
# ---------------------------------------------------------------------------


def _axis_extent(axis_sizes: Mapping[str, int], entry) -> int:
    if entry is None:
        return 1
    if isinstance(entry, (tuple, list)):
        return math.prod(_axis_extent(axis_sizes, a) for a in entry)
    return int(axis_sizes.get(entry, 1))


def _apply_divisibility(
    spec: tuple, shape: tuple, axis_sizes: Mapping[str, int], mode: str
) -> tuple | None:
    """Resolve a rule's spec against a leaf shape. Returns the concrete
    spec tuple, or None when mode='require' and a named dim does not
    divide (the rule falls through)."""
    if mode == "strict":
        return spec
    out = []
    for d, entry in enumerate(spec):
        if entry is None:
            out.append(None)
            continue
        extent = _axis_extent(axis_sizes, entry)
        size = shape[d] if d < len(shape) else 0
        if mode == "require":
            if extent and size % extent:
                return None
            out.append(entry)
        elif mode == "drop_or_unit":
            out.append(
                entry if extent > 1 and size % extent == 0 else None
            )
        else:  # pragma: no cover - table validation catches this
            raise ValueError(f"unknown divisibility mode {mode!r}")
    return tuple(out)


class SpecLayout:
    """One compiled layout table: named axes + pattern rules.

    The declarative source lives in :data:`LAYOUT_TABLES`; instances
    are created once per table by :func:`get_layout` and cached.
    """

    def __init__(self, name: str, rules: tuple):
        self.name = name
        self._rules = tuple(
            (
                re.compile(r["pattern"]),
                tuple(r["spec"]),
                r.get("ndim"),
                r.get("max_ndim"),
                r.get("divisible", "strict"),
            )
            for r in rules
        )

    def spec(
        self,
        path_name: str,
        shape: tuple,
        axis_sizes: Mapping[str, int] | None = None,
    ) -> P:
        """PartitionSpec for one leaf: first rule whose pattern matches
        ``path_name`` and whose rank filter admits ``shape`` (subject
        to the rule's divisibility mode) wins."""
        ndim = len(shape)
        axis_sizes = axis_sizes or {}
        for pat, spec, r_ndim, r_max, divisible in self._rules:
            if r_ndim is not None and ndim != r_ndim:
                continue
            if r_max is not None and ndim > r_max:
                continue
            if not pat.search(path_name):
                continue
            resolved = _apply_divisibility(spec, shape, axis_sizes, divisible)
            if resolved is None:
                continue  # 'require' rule fell through
            return P(*resolved)
        raise ValueError(
            f"layout table {self.name!r} has no rule for {path_name!r} "
            f"(shape {shape}); tables must end in a catch-all"
        )


_LAYOUTS: dict[str, SpecLayout] = {}


def get_layout(name: str) -> SpecLayout:
    """The compiled :class:`SpecLayout` for one table in
    :data:`LAYOUT_TABLES` (cached)."""
    layout = _LAYOUTS.get(name)
    if layout is None:
        try:
            rules = LAYOUT_TABLES[name]
        except KeyError:
            raise KeyError(
                f"unknown layout table {name!r}; declared: "
                f"{sorted(LAYOUT_TABLES)}"
            ) from None
        layout = _LAYOUTS[name] = SpecLayout(name, rules)
    return layout


def _dim_axes(entry) -> tuple:
    """One spec dim entry as a flat tuple of axis names."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def optimizer_state_spec(
    path_name: str,
    shape: tuple,
    base_spec,
    axis_sizes: Mapping[str, int] | None = None,
) -> P:
    """PartitionSpec for one optimizer-state leaf: the 'optimizer'
    table's first matching rule MERGED onto the leaf's mirrored param
    spec (``base_spec``) dim-by-dim.

    A rule dim naming an axis prepends that axis to the base dim's axis
    set; under ``drop_or_unit`` the axis is kept only when its extent is
    > 1 and the dim size divides the COMBINED extent (new axis × the
    base spec's axes on that dim) — otherwise the dim falls back to the
    mirrored base, which is exactly the drop-to-replicated-across-data
    contract for indivisible leaves. ``base_spec`` of ``P()`` (a
    replicated param, pure-DP training) makes the merge a plain
    data-axis partition — the arXiv 2004.13336 setting.
    """
    axis_sizes = axis_sizes or {}
    base = tuple(base_spec)
    ndim = len(shape)
    for pat, spec, r_ndim, r_max, divisible in get_layout("optimizer")._rules:
        if r_ndim is not None and ndim != r_ndim:
            continue
        if r_max is not None and ndim > r_max:
            continue
        if not pat.search(path_name):
            continue
        out = []
        fell_through = False
        changed = False
        for d in range(ndim):
            base_entry = base[d] if d < len(base) else None
            add = spec[d] if d < len(spec) else None
            base_axes = _dim_axes(base_entry)
            if add is None or add in base_axes:
                out.append(base_entry)
                continue
            add_extent = _axis_extent(axis_sizes, add)
            combined = add_extent * _axis_extent(
                axis_sizes, base_axes or None
            )
            divides = combined > 0 and shape[d] % combined == 0
            if divisible == "drop_or_unit":
                if add_extent <= 1 or not divides:
                    out.append(base_entry)
                    continue
            elif divisible == "require":
                if not divides:
                    fell_through = True
                    break
            # 'strict': divisibility is the caller's contract
            out.append((add, *base_axes) if base_axes else add)
            changed = True
        if fell_through:
            continue
        if not changed:
            # nothing merged: return the base VERBATIM (trailing Nones
            # and all), so a fully-dropped leaf compares equal to its
            # mirrored param spec — consumers no-op on that equality
            return P(*base)
        while out and out[-1] is None:
            out.pop()
        return P(*out)
    raise ValueError(
        f"optimizer layout table has no rule for {path_name!r} "
        f"(shape {shape}); tables must end in a catch-all"
    )


def optimizer_state_sharding(
    mesh: Mesh,
    path_name: str,
    shape: tuple,
    base_spec,
) -> NamedSharding:
    return NamedSharding(
        mesh,
        optimizer_state_spec(
            path_name, tuple(shape), base_spec, dict(mesh.shape)
        ),
    )


def _path_name(path) -> str:
    """'/'-joined tree path: dict keys AND dataclass-leaf attr names,
    so a LoRA factor reads 'layer0/attn/q_proj/kernel/a'."""
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is not None:
            parts.append(str(key))
            continue
        name = getattr(p, "name", None)
        if name is not None:
            parts.append(str(name))
            continue
        idx = getattr(p, "idx", None)
        parts.append(str(idx) if idx is not None else str(p))
    return "/".join(parts)


def param_shardings(params: Any, mesh: Mesh, layout: str | SpecLayout):
    """NamedShardings for a param pytree from one layout table.

    Works on concrete arrays and ``ShapeDtypeStruct`` leaves alike
    (tools/shardcheck.py lowers abstractly), so the table is usable
    before any memory is allocated.
    """
    import jax

    table = layout if isinstance(layout, SpecLayout) else get_layout(layout)
    axis_sizes = dict(mesh.shape)

    def rule(path, leaf) -> NamedSharding:
        return NamedSharding(
            mesh,
            table.spec(_path_name(path), tuple(leaf.shape), axis_sizes),
        )

    return jax.tree_util.tree_map_with_path(rule, params)


# ---------------------------------------------------------------------------
# role helpers — the only sanctioned PartitionSpec/NamedSharding
# constructors outside this module's tables (SH001)
# ---------------------------------------------------------------------------


def sharding(mesh: Mesh, spec: P | tuple) -> NamedSharding:
    """Wrap a spec (PartitionSpec or plain axis tuple) for ``mesh``."""
    if not isinstance(spec, P):
        spec = P(*spec)
    return NamedSharding(mesh, spec)


def activation_spec(role: str, ndim: int | None = None) -> P:
    """The declared activation/IO spec for one role in
    :data:`ACTIVATION_SPECS`; ``ndim`` pads trailing dims with None
    (a PartitionSpec shorter than the rank leaves trailing dims
    unsharded anyway — padding only matters for readability)."""
    try:
        spec = ACTIVATION_SPECS[role]
    except KeyError:
        raise KeyError(
            f"unknown activation role {role!r}; declared: "
            f"{sorted(ACTIVATION_SPECS)}"
        ) from None
    if ndim is not None and ndim > len(spec):
        spec = tuple(spec) + (None,) * (ndim - len(spec))
    return P(*spec)


def activation_sharding(
    mesh: Mesh, role: str, ndim: int | None = None
) -> NamedSharding:
    return NamedSharding(mesh, activation_spec(role, ndim))


def batch_spec(ndim: int = 1) -> P:
    """Batch pytree leaf: leading dim over ('data','fsdp'), rest
    replicated."""
    return activation_spec("batch", ndim)


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(ndim))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def decode_cache_spec(x, tp: bool = True) -> P:
    """PartitionSpec for one KV-cache leaf under mesh-sharded decode
    (see :data:`DECODE_CACHE_SPECS`). ``tp=False`` drops the 'model'
    head sharding — the speculative draft's cache, whose weights are
    replicated."""
    spec = DECODE_CACHE_SPECS.get(x.ndim, ())
    if not tp:
        spec = tuple(None if a == "model" else a for a in spec)
    return P(*spec)


def decode_cache_sharding(mesh: Mesh, x, tp: bool = True) -> NamedSharding:
    return NamedSharding(mesh, decode_cache_spec(x, tp=tp))


def serve_cache_spec(x) -> P:
    """The continuous engine's cache spec (see
    :data:`SERVE_CACHE_SPECS`): TP on 'model' only, batch replicated."""
    return P(*SERVE_CACHE_SPECS.get(x.ndim, ()))


def serve_cache_sharding(mesh: Mesh, x) -> NamedSharding:
    return NamedSharding(mesh, serve_cache_spec(x))


def expert_bank_spec(param_name: str) -> P:
    """PartitionSpec for one 3-dim MoE expert bank leaf, from the 'moe'
    table — single source of truth; the llama table carries the same
    rules so model-level and module-level specs cannot diverge."""
    return get_layout("moe").spec(param_name, (0, 0, 0))


def fsdp_leaf_spec(
    shape: tuple,
    n_shard: int,
    axis: str = "fsdp",
    min_shard_elements: int = 1024,
) -> P:
    """The generic shape-driven FSDP rule: shard the LARGEST dim
    divisible by the fsdp axis size; tiny tensors (biases, norms) stay
    replicated. This mirrors how the reference's PS spread variables
    across ps shards (greedy variable placement), re-expressed as mesh
    sharding."""
    if n_shard == 1 or math.prod(shape) < min_shard_elements:
        return P()
    for d in sorted(range(len(shape)), key=lambda i: shape[i], reverse=True):
        if shape[d] % n_shard == 0:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def fsdp_leaf_sharding(
    mesh: Mesh,
    shape: tuple,
    axis: str = "fsdp",
    min_shard_elements: int = 1024,
) -> NamedSharding:
    return NamedSharding(
        mesh,
        fsdp_leaf_spec(
            tuple(shape), mesh.shape[axis], axis, min_shard_elements
        ),
    )


def tp_only(mesh: Mesh, sh: NamedSharding) -> NamedSharding:
    """Project a sharding onto the 'model' (TP) axis only — the serving
    engine's weight placement: the training rules also shard on 'fsdp',
    which with a replicated batch would force a weight all-gather on
    every per-token decode step."""

    def keep(ax):
        if isinstance(ax, (tuple, list)):  # multi-axis dim
            kept = tuple(a for a in ax if a == "model")
            return kept[0] if kept else None
        return ax if ax == "model" else None

    return NamedSharding(mesh, P(*(keep(ax) for ax in sh.spec)))
