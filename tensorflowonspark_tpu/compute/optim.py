"""Memory-footprint-aware optimizers (TPU HBM is the scarce resource).

Why this module exists: on a 16 GB v5e chip, a ~1B-param model trained
with stock fp32 AdamW needs 15.2 GB for params+grads+moments alone —
right at HBM capacity — and XLA's scheduler pays for it in spills and
serialization (measured on the llama1b benchmark config: 478 ms/step
fp32-everything vs 393 ms with the state in bf16; the *isolated*
optimizer update is bandwidth-bound either way, the difference is
capacity pressure on the whole step). The reference delegated this
problem to parameter servers — state sharded across PS hosts
(`tensorflowonspark/TFNode.py:start_cluster_server`, SURVEY.md §2.3);
on TPU the equivalent levers are FSDP sharding (``fsdp_shardings``) and
the state dtypes here.

Two transformations, both optax-compatible:

- :func:`adamw` — drop-in ``optax.adamw`` with *both* moments storable
  in a narrow dtype (optax only offers ``mu_dtype``). Moment math is
  fp32; only the stored state is narrow. bf16 moments cost ~0.2%
  relative error on the update (8-bit mantissa under a sqrt) — the
  standard large-model tradeoff.
- :func:`mixed_precision_adamw` — for bf16-stored params: keeps an fp32
  master copy *inside the optimizer state* (the Megatron-style recipe).
  Updates are applied to the master; params are exactly
  ``master.astype(param_dtype)`` every step, so tiny updates accumulate
  in fp32 instead of vanishing into bf16 round-off.

Sharding contract (the ZeRO cross-replica weight update, arXiv
2004.13336): both optimizers' per-param state is partitionable along
the ``'data'`` replica axis — every update is ELEMENTWISE per leaf
(moment EMAs, bias correction by the replicated scalar ``count``,
decoupled weight decay, the master delta), so GSPMD computes it on a
1/N shard and the result is byte-identical to the replicated
computation. The state FIELD NAMES are load-bearing: ``mu``/``nu``/
``master`` (and the scalar ``count``) are what
``LAYOUT_TABLES['optimizer']`` (compute/layout.py) keys the
data-partition and replication rules on, and what
``train.state_shardings`` resolves explicitly — rename a field and the
layout silently degrades to replicated, so tests/test_layout.py pins
the pattern and tests/test_compute.py the resolution.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


def _cast_tree(tree: Any, dtype) -> Any:
    if dtype is None:
        return tree
    return jax.tree.map(lambda x: x.astype(dtype), tree)


class ScaleByAdamState(NamedTuple):
    count: jax.Array
    mu: Any
    nu: Any


def scale_by_adam(
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    moment_dtype: Optional[jnp.dtype] = None,
) -> optax.GradientTransformation:
    """``optax.scale_by_adam`` with both moments stored in ``moment_dtype``.

    All arithmetic runs in fp32 (narrow state is widened per step, the
    new state re-narrowed); gradients of any dtype are accepted and
    widened. ``moment_dtype=None`` stores moments in fp32.
    """

    def init(params):
        # zeros_like (not zeros): inherits each param's committed sharding,
        # so FSDP-sharded params get FSDP-sharded moments at init. Plain
        # jnp.zeros would land moments on the default device — uncommitted
        # arrays that jit happens to reshard, but that poison a checkpoint
        # restore target with single-device placements (restored arrays
        # come back committed there, and the AOT train step then rejects
        # them under multi-controller FSDP).
        zeros = lambda p: jnp.zeros_like(  # noqa: E731
            p, dtype=moment_dtype or jnp.float32
        )
        return ScaleByAdamState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(zeros, params),
            nu=jax.tree.map(zeros, params),
        )

    def update(updates, state, params=None):
        del params
        g32 = _cast_tree(updates, jnp.float32)
        mu32 = jax.tree.map(
            lambda m, g: b1 * m.astype(jnp.float32) + (1 - b1) * g,
            state.mu,
            g32,
        )
        nu32 = jax.tree.map(
            lambda v, g: b2 * v.astype(jnp.float32) + (1 - b2) * g * g,
            state.nu,
            g32,
        )
        count = state.count + 1
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)
        out = jax.tree.map(
            lambda m, v: (m / c1) / (jnp.sqrt(v / c2) + eps), mu32, nu32
        )
        return out, ScaleByAdamState(
            count=count,
            mu=_cast_tree(mu32, moment_dtype),
            nu=_cast_tree(nu32, moment_dtype),
        )

    return optax.GradientTransformation(init, update)


def adamw(
    learning_rate: float | optax.Schedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,
    moment_dtype: Optional[jnp.dtype] = None,
) -> optax.GradientTransformation:
    """AdamW whose stored moments can be bf16 (``moment_dtype=jnp.bfloat16``).

    With fp32 params this alone freed 3.8 GB on the llama1b config and
    moved the measured train step from 49.8% to 57.3% MFU.
    """
    return optax.chain(
        scale_by_adam(b1, b2, eps, moment_dtype=moment_dtype),
        optax.add_decayed_weights(weight_decay),
        optax.scale_by_learning_rate(learning_rate),
    )


class MixedPrecisionAdamWState(NamedTuple):
    count: jax.Array
    mu: Any
    nu: Any
    master: Any  # fp32 copy of the (narrow) params


def mixed_precision_adamw(
    learning_rate: float | optax.Schedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,
    moment_dtype: Optional[jnp.dtype] = jnp.bfloat16,
) -> optax.GradientTransformation:
    """AdamW for bf16-stored params with an fp32 master in the state.

    Init with the *narrow* (e.g. bf16) param tree; the transformation
    snapshots an fp32 master copy. Each step the AdamW update (fp32
    math, bias-corrected, decoupled weight decay on the master) advances
    the master, and the emitted update is exactly
    ``master_new.astype(param_dtype) - params`` in fp32 — so
    ``optax.apply_updates`` lands the params on the bf16 rounding of the
    master with no cumulative drift, and sub-bf16-ulp updates still
    accumulate (in the master) instead of rounding to zero.

    Supports learning-rate schedules via a callable ``learning_rate``.
    """

    adam = scale_by_adam(b1, b2, eps, moment_dtype=moment_dtype)

    def init(params):
        inner = adam.init(params)
        return MixedPrecisionAdamWState(
            count=inner.count,
            mu=inner.mu,
            nu=inner.nu,
            master=_cast_tree(params, jnp.float32),
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("mixed_precision_adamw requires params")
        direction, inner = adam.update(
            grads, ScaleByAdamState(state.count, state.mu, state.nu)
        )
        # schedule indexed at the pre-increment count: first step uses
        # schedule(0), matching optax/scale_by_learning_rate convention
        lr = (
            learning_rate(state.count)
            if callable(learning_rate)
            else learning_rate
        )
        master = jax.tree.map(
            lambda w, d: w - lr * (d + weight_decay * w),
            state.master,
            direction,
        )
        # fp32 delta landing params exactly on master's narrow rounding
        updates = jax.tree.map(
            lambda w, p: w.astype(p.dtype).astype(jnp.float32)
            - p.astype(jnp.float32),
            master,
            params,
        )
        return updates, MixedPrecisionAdamWState(
            count=inner.count,
            mu=inner.mu,
            nu=inner.nu,
            master=master,
        )

    return optax.GradientTransformationExtraArgs(init, update)
