"""Elastic training: survive a membership change mid-run — reshard, don't restart.

PR 4's liveness plane made node death *detectable* in seconds, but the
recovery was still "tear the whole cluster down and relaunch"
(``run_with_restarts``). This module is the next step, the TF-Replicator
recipe (PAPERS.md, arXiv 1902.00465) composed with deterministic
cross-replica state sharding (arXiv 2004.13336): when membership changes,
the surviving processes *reconfigure* —

1. the driver bumps a monotonic **membership epoch** and publishes the new
   roster (``cluster/reservation.py``); every node learns of it within one
   heartbeat (the beat reply piggybacks the epoch);
2. survivors gather their state to an **in-memory host snapshot**
   (:func:`host_snapshot`), re-init ``jax.distributed`` against the new
   topology (``TFNodeContext.reinitialize_distributed``), re-form the mesh
   (:func:`fit_axis_shapes <tensorflowonspark_tpu.compute.mesh.fit_axis_shapes>`
   + ``make_mesh``), and deterministically commit params + optimizer state
   onto the new shardings (:func:`reshard_state`) — byte-identical values,
   new placement;
3. a **joining** node hydrates its state from a peer's published in-memory
   snapshot (:meth:`ElasticTrainer.hydrate`), falling back to the latest
   orbax checkpoint only when in-memory recovery is impossible — the
   checkpoint is the fallback, not the recovery path.

Every decision is failpoint-injectable (``elastic.epoch_bump``,
``elastic.reshard_gather``, ``elastic.rejoin_init``) and recorded as obs
events + flight-recorder entries, so chaos runs are auditable end to end:
``cluster_membership_epoch`` (gauge), ``elastic_reshard_seconds``
(histogram), ``elastic_recoveries_total{outcome=}`` (counter).

The driver-side half lives in ``TFCluster.supervise()`` (elastic mode):
instead of raising on a dead node, it removes the node, bumps the epoch,
and keeps supervising the survivors.
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
from typing import Any, Callable, Mapping

import numpy as np

from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.obs import flightrec
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.obs.registry import default_registry
from tensorflowonspark_tpu.utils.failpoints import failpoint

logger = logging.getLogger(__name__)

__all__ = [
    "ElasticTrainer",
    "InMemoryRecoveryUnavailable",
    "MembershipWatcher",
    "current_epoch",
    "host_snapshot",
    "membership",
    "notify_membership",
    "reshard_state",
    "wait_for_epoch",
]

# Default manager-KV key a survivor publishes its host snapshot under
# (what a joiner's peer hydration reads). Declared in cluster/wire.py
# WIRE_SCHEMAS ("kv.elastic_state") — this re-export keeps the
# compute-plane import name.
STATE_KEY = wire.ELASTIC_STATE_KEY


class InMemoryRecoveryUnavailable(RuntimeError):
    """A state leaf is not fully addressable from this process (its
    shards live on departed peers' devices), so the in-memory recovery
    path cannot produce a complete snapshot — fall back to the latest
    checkpoint."""


def _metrics():
    reg = default_registry()
    return (
        reg.gauge(
            "cluster_membership_epoch",
            "current membership epoch (bumped on every reconfigure)",
        ),
        reg.histogram(
            "elastic_reshard_seconds",
            "wall seconds spent resharding state on a membership change",
        ),
        reg.counter(
            "elastic_recoveries_total",
            "elastic recovery attempts, by outcome",
        ),
    )


# ---------------------------------------------------------------------------
# membership watcher (node side)
# ---------------------------------------------------------------------------


class MembershipWatcher:
    """Process-local view of the cluster membership epoch.

    The node heartbeater calls :meth:`notify` when a beat reply shows
    the epoch moved (after refetching the roster via ``QEPOCH``);
    training loops poll :meth:`current` / ``ElasticTrainer.changed()``
    — one integer compare per step — and tests block on
    :meth:`wait_for_epoch`. Epochs only move forward; a stale notify
    (reordered beat replies) is ignored.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._epoch = 0  # guarded-by: self._cond
        self._roster: list[dict[str, Any]] | None = None  # guarded-by: self._cond

    def notify(self, epoch: int, roster: list[dict[str, Any]]) -> bool:
        """Record a membership change; returns False for stale epochs."""
        epoch = int(epoch)
        with self._cond:
            if epoch <= self._epoch and self._roster is not None:
                return False
            self._epoch = max(self._epoch, epoch)
            self._roster = list(roster)
            self._cond.notify_all()
        _metrics()[0].set(epoch)
        flightrec.note(
            "membership_epoch",
            epoch=epoch,
            nodes=[n.get("executor_id") for n in roster],
        )
        return True

    def current(self) -> tuple[int, list[dict[str, Any]] | None]:
        with self._cond:
            return self._epoch, (
                None if self._roster is None else list(self._roster)
            )

    def wait_for_epoch(self, min_epoch: int, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._epoch < min_epoch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 1.0))
            return True

    def reset(self) -> None:
        """Back to the never-notified state (tests; a fresh cluster in
        the same process)."""
        with self._cond:
            self._epoch = 0
            self._roster = None
            self._cond.notify_all()


_watcher = MembershipWatcher()


def notify_membership(epoch: int, roster: list[dict[str, Any]]) -> bool:
    """Entry point for the heartbeater: publish a membership change to
    this process's training loop."""
    return _watcher.notify(epoch, roster)


def membership() -> tuple[int, list[dict[str, Any]] | None]:
    """(epoch, roster) as last notified; roster None before any notify."""
    return _watcher.current()


def current_epoch() -> int:
    """The membership epoch alone — the per-block poll of the ingest
    handover protocol (``IngestFeed._handover_due``): the SAME
    heartbeat-fed watcher ``ElasticTrainer.changed()`` reads, so the
    data plane and the compute plane observe one consistent epoch
    sequence."""
    return _watcher.current()[0]


def wait_for_epoch(min_epoch: int, timeout: float = 30.0) -> bool:
    return _watcher.wait_for_epoch(min_epoch, timeout)


# ---------------------------------------------------------------------------
# deterministic resharding
# ---------------------------------------------------------------------------


def host_snapshot(state: Any) -> Any:
    """In-memory host copy of ``state``: same pytree, numpy leaves.

    THE recovery artifact of the elastic plane — byte-exact (device_get
    round-trips bitwise), so a reshard built from it is byte-identical
    to the pre-change state. Raises :class:`InMemoryRecoveryUnavailable`
    when a leaf is not fully addressable from this process (its shards
    lived on departed peers): that is the precise condition under which
    the checkpoint fallback is the only honest recovery.
    """
    import jax

    def pull(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            raise InMemoryRecoveryUnavailable(
                "state leaf is not fully addressable from this process; "
                "in-memory recovery needs every shard locally — falling "
                "back to the latest checkpoint is the supported path"
            )
        return np.asarray(jax.device_get(x))

    return jax.tree.map(pull, state)


def reshard_state(state: Any, shardings: Any) -> Any:
    """Deterministically commit ``state`` onto ``shardings`` through
    host memory: ``device_get`` each leaf (a no-op for an existing
    :func:`host_snapshot`) then ``device_put`` to its target sharding.
    Values are untouched — an N→N−1→N round trip is byte-identical
    (proven by ``tests/test_elastic.py``)."""
    import jax

    host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), state)
    return jax.tree.map(jax.device_put, host, shardings)


def default_shardings_fn(state: Any, mesh) -> Any:
    """Shardings for a (re-formed) mesh: FSDP over params via
    :func:`~tensorflowonspark_tpu.compute.train.fsdp_shardings` (the
    layout table's generic shape-driven rule), the optimizer tree
    mirrored — ZeRO data-axis partitioned by ``state_shardings``'s
    default, so a reconfigure re-derives the same cross-replica weight
    update layout training ran with, for the NEW device count — and
    scalars replicated. Model-table consumers pass
    ``shardings_fn=lambda s, m: state_shardings(s, m,
    layout.param_shardings(s.params, m, "<table>"))`` instead; either
    way the reshard round-trip is byte-identical (values never change,
    only placement — ``reshard_state`` moves bytes through host memory)
    and its shardcheck collective census is stable
    (tests/test_layout.py, incl. the ZeRO-partitioned moments and
    mixed-precision masters)."""
    from tensorflowonspark_tpu.compute.train import (
        fsdp_shardings,
        state_shardings,
    )

    if hasattr(state, "params"):
        psh = fsdp_shardings(state.params, mesh)
        return state_shardings(state, mesh, psh)
    return fsdp_shardings(state, mesh)


# ---------------------------------------------------------------------------
# the node-side state machine
# ---------------------------------------------------------------------------


class ElasticTrainer:
    """Node-side reshard/epoch state machine.

    Usage (the shape ``tests/cluster_fns.elastic_train_fn`` follows)::

        trainer = ElasticTrainer(ctx, axis_shapes={"data": -1})
        mesh = trainer.mesh()
        step_fn = build_train_step(loss_fn, tx, mesh)
        step = start
        while step < total:
            if trainer.changed():                      # one int compare
                state, mesh = trainer.reconfigure(state)
                step_fn = build_train_step(loss_fn, tx, mesh)
                if trainer.resume_step is not None:    # ckpt fallback:
                    step = trainer.resume_step         # rewind + replay
            state, loss = step_fn(state, batch_for(step))
            trainer.publish(state, step + 1)           # peers can hydrate
            step += 1

    ``axis_shapes`` follows ``make_mesh`` (the elastic axis absorbs
    device-count changes — :func:`fit_axis_shapes`); ``shardings_fn(state,
    mesh)`` derives the new placement (default: FSDP params + mirrored
    optimizer tree); ``checkpoint_dir`` arms the fallback;
    ``publish_steps`` throttles peer-hydration snapshots;
    ``devices_fn(roster)`` overrides device discovery (tests shrink a
    local device set with it — production uses the post-reinit global
    device list).
    """

    # Max wall-clock a rejoiner waits for its own admission bump while
    # excluded from the roster (see changed()); admission normally
    # lands within one driver supervise poll (seconds). On expiry the
    # exclusion is treated as a real removal — the loud error, never a
    # silent wedge.
    ADMISSION_GRACE_S = 120.0

    def __init__(
        self,
        ctx,
        axis_shapes: Mapping[str, int] | None = None,
        elastic_axis: str = "fsdp",
        shardings_fn: Callable[[Any, Any], Any] | None = None,
        checkpoint_dir: str | None = None,
        publish_steps: int = 1,
        state_key: str = STATE_KEY,
        devices_fn: Callable[[list[dict[str, Any]]], list] | None = None,
    ):
        self._ctx = ctx
        self._axis_shapes = dict(axis_shapes) if axis_shapes else None
        self._elastic_axis = elastic_axis
        self._shardings_fn = shardings_fn or default_shardings_fn
        self._checkpoint_dir = checkpoint_dir
        self._publish_steps = max(1, int(publish_steps))
        self._state_key = state_key
        self._devices_fn = devices_fn
        self._last_published: int | None = None
        epoch, roster = membership()
        self._cur_epoch = epoch
        self._cur_roster = (
            roster
            if roster is not None
            else list(getattr(ctx, "cluster_info", None) or [])
        )
        self._mesh = None
        # True between hydrate() (the rejoin path) and this node's own
        # admission bump landing: a replacement's _cur_roster can
        # contain its executor id only because its dead PREDECESSOR was
        # in it, which defeated changed()'s not-yet-admitted guard — a
        # stale departure bump arriving before the admit bump made the
        # rejoiner reconfigure onto a roster excluding itself and die
        # loudly (race exposed by the tfsan-era instrumented chaos
        # runs under host load). The wait is BOUNDED (one excluded
        # epoch, one grace window) so a rejoiner that really was
        # removed still fails loudly instead of wedging silently.
        self._awaiting_admission = False
        self._await_excluded_epoch: int | None = None
        self._await_since: float | None = None
        # Set by reconfigure: None after an in-memory reshard (resume
        # where you were), or the restored checkpoint step after a
        # checkpoint_fallback — the training loop MUST rewind its step
        # counter to it (replaying the same data order) or it silently
        # skips the steps between the checkpoint and the failure.
        self.resume_step: int | None = None

    # -- cheap per-step surface ---------------------------------------

    @property
    def epoch(self) -> int:
        return self._cur_epoch

    @property
    def roster(self) -> list[dict[str, Any]]:
        return list(self._cur_roster)

    def _is_member(self, roster: list[dict[str, Any]]) -> bool:
        eid = getattr(self._ctx, "executor_id", None)
        return any(n.get("executor_id") == eid for n in roster)

    def changed(self) -> bool:
        """True when the cluster membership moved past the epoch this
        trainer last reconfigured for — one integer compare on the hot
        path, safe to call every step.

        One refinement for joiners: a freshly-registered node may see a
        bump it is in NEITHER side of (the departure bump published
        just before its own admission). Reconfiguring onto a roster
        that excludes it would be wrong either way, so such bumps are
        not "changes" — its own admission bump follows within a poll.
        A REPLACEMENT needs the explicit ``_awaiting_admission`` flag
        for this (set by :meth:`hydrate`): its ``_cur_roster`` is the
        original cluster roster, which contains its executor id via
        the dead predecessor, so roster membership alone cannot tell
        "was admitted" from "inherited the dead node's seat". The wait
        is bounded two ways — the driver folds concurrent removals and
        admissions into one bump per supervise poll, so a SECOND
        distinct epoch that still excludes this node means the admit
        bump is not coming (return True; reconfigure raises the loud
        "was removed"); and ADMISSION_GRACE_S caps the wall-clock wait
        against a wedged driver, so a genuinely-removed rejoiner can
        never wedge silently on a stale mesh."""
        epoch, roster = _watcher.current()
        if epoch <= self._cur_epoch:
            return False
        if roster is not None and not self._is_member(roster):
            if self._awaiting_admission:
                if self._await_excluded_epoch is None:
                    self._await_excluded_epoch = epoch
                waited = time.monotonic() - (
                    self._await_since or time.monotonic()
                )
                if (
                    epoch == self._await_excluded_epoch
                    and waited < self.ADMISSION_GRACE_S
                ):
                    return False  # the predecessor's departure bump
                return True  # excluded again/too long: really removed
            if not self._is_member(self._cur_roster):
                return False  # registered but not yet admitted
        return True

    def mesh(self):
        """The device mesh for the current epoch (cached until the next
        :meth:`reconfigure`)."""
        if self._mesh is None:
            from tensorflowonspark_tpu.compute.mesh import (
                fit_axis_shapes,
                make_mesh,
            )

            devices = self._devices()
            shapes = fit_axis_shapes(
                self._axis_shapes, len(devices), self._elastic_axis
            )
            self._mesh = make_mesh(shapes, devices=devices)
        return self._mesh

    def _devices(self) -> list:
        import jax

        if self._devices_fn is not None:
            return list(self._devices_fn(self._cur_roster))
        # Multi-controller: the global device set (post-reinit it spans
        # exactly the surviving processes). Single-controller-per-node:
        # membership does not change this node's local devices.
        if getattr(self._ctx, "distributed", False):
            return list(jax.devices())
        return list(jax.local_devices())

    # -- the reconfigure ----------------------------------------------

    def reconfigure(self, state: Any) -> tuple[Any, Any]:
        """Drive one membership reconfigure; returns ``(state, mesh)``.

        Order matters: (1) gather the in-memory snapshot while the OLD
        arrays are still healthy, (2) re-init the distributed runtime
        against the new roster, (3) re-form the mesh, (4) commit the
        snapshot onto the new shardings. A failed gather (shards on
        departed peers; an armed ``elastic.reshard_gather``) falls back
        to the latest checkpoint — outcome ``checkpoint_fallback``,
        with :attr:`resume_step` set to the restored step so the
        training loop rewinds to it (replaying the same data order)
        instead of silently skipping the steps between the checkpoint
        and the failure — and with no ``checkpoint_dir`` the
        reconfigure fails loudly (outcome ``failed``): training on
        silently-stale state is the one unacceptable result.
        """
        gauge, hist, recoveries = _metrics()
        epoch, roster = membership()
        if roster is None:
            roster = self._cur_roster
        if not self._is_member(roster):
            # The driver removed THIS node (a false-positive death
            # verdict — e.g. a GC pause outliving the grace — or a
            # voluntary leave). Continuing to train outside membership
            # is zombie work; rejoining goes through registration, not
            # reconfigure.
            raise RuntimeError(
                f"executor {getattr(self._ctx, 'executor_id', '?')} is "
                f"not in membership epoch {epoch} "
                f"({[n.get('executor_id') for n in roster]}): this node "
                "was removed — re-register to rejoin instead of "
                "reconfiguring"
            )
        # admitted: this roster includes us — future exclusions are
        # real removals again, not a pending admission
        self._awaiting_admission = False
        self._await_excluded_epoch = None
        self._await_since = None
        t0 = time.monotonic()
        outcome = "resharded"
        restored_step: int | None = None
        with obs_spans.span(
            "elastic.reshard", epoch=epoch, nodes=len(roster)
        ):
            snapshot = None
            gather_err: BaseException | None = None
            try:
                failpoint("elastic.reshard_gather")
                snapshot = host_snapshot(state)
            except BaseException as e:  # noqa: BLE001 - fallback decides
                gather_err = e
                logger.warning(
                    "elastic: in-memory gather failed (%s); trying the "
                    "checkpoint fallback",
                    e,
                )
            reinit = getattr(self._ctx, "reinitialize_distributed", None)
            if reinit is not None:
                reinit(roster)
            self._cur_epoch, self._cur_roster, self._mesh = epoch, roster, None
            mesh = self.mesh()
            if snapshot is None:
                snapshot, outcome, restored_step = self._fallback_snapshot(
                    state, gather_err
                )
            shardings = self._shardings_fn(snapshot, mesh)
            state = reshard_state(snapshot, shardings)
        self.resume_step = restored_step
        dt = time.monotonic() - t0
        hist.observe(dt)
        recoveries.inc(outcome=outcome)
        gauge.set(epoch)
        flightrec.note(
            "elastic_reconfigure",
            epoch=epoch,
            outcome=outcome,
            nodes=len(roster),
            resume_step=restored_step,
            seconds=round(dt, 3),
        )
        logger.info(
            "elastic: reconfigured to epoch %d (%d node(s), %s, %.3fs)",
            epoch,
            len(roster),
            outcome,
            dt,
        )
        # The snapshot published for joiners must reflect the new epoch
        # — and, after a fallback, the step it was actually rewound to.
        self.publish(
            state,
            restored_step
            if restored_step is not None
            else (self._last_published or 0),
            force=True,
        )
        return state, mesh

    def _fallback_snapshot(
        self, state: Any, gather_err: BaseException | None
    ) -> tuple[Any, str, int]:
        if self._checkpoint_dir is None:
            _metrics()[2].inc(outcome="failed")
            flightrec.note(
                "elastic_reconfigure_failed", error=repr(gather_err)
            )
            flightrec.dump_now("elastic_reconfigure_failed")
            raise RuntimeError(
                "elastic reconfigure: in-memory recovery impossible and "
                "no checkpoint_dir configured"
            ) from gather_err
        from tensorflowonspark_tpu.compute import checkpoint as ckpt

        step, restored = ckpt.hydration_restore(
            self._checkpoint_dir, target=state
        )
        if restored is None:
            _metrics()[2].inc(outcome="failed")
            flightrec.note(
                "elastic_reconfigure_failed",
                error=repr(gather_err),
                checkpoint_dir=self._checkpoint_dir,
            )
            flightrec.dump_now("elastic_reconfigure_failed")
            raise RuntimeError(
                f"elastic reconfigure: in-memory recovery impossible and "
                f"no checkpoint found under {self._checkpoint_dir!r}"
            ) from gather_err
        logger.warning(
            "elastic: recovered from checkpoint step %s (in-memory "
            "snapshot unavailable); the training loop must rewind to it",
            step,
        )
        return host_snapshot(restored), "checkpoint_fallback", int(step)

    # -- peer hydration (the joiner path) ------------------------------

    def publish(self, state: Any, step: int, force: bool = False) -> None:
        """Publish this node's host snapshot to its manager KV so a
        joiner can hydrate from in-memory state instead of a checkpoint.
        Throttled to every ``publish_steps`` steps; best-effort (a
        failed publish degrades the joiner to the checkpoint fallback,
        it never fails training)."""
        mgr = getattr(self._ctx, "mgr", None)
        if mgr is None:
            return
        if (
            not force
            and self._last_published is not None
            and step - self._last_published < self._publish_steps
        ):
            return
        try:
            blob = pickle.dumps(
                (self._cur_epoch, int(step), host_snapshot(state)),
                protocol=pickle.HIGHEST_PROTOCOL,
            )
            mgr.set(self._state_key, blob)
            self._last_published = int(step)
        except Exception as e:  # noqa: BLE001 - best-effort by contract
            logger.debug("elastic publish skipped: %s", e)

    def hydrate(self, default: Any = None) -> tuple[int | None, Any]:
        """Joining-node recovery: ``(step, state)`` from the freshest
        peer-published in-memory snapshot, else the latest checkpoint
        (outcome ``checkpoint_fallback``), else ``(None, default)``
        (outcome ``fresh_init`` — a genuinely new cluster). The
        returned state is committed onto this node's current mesh via
        ``shardings_fn``. Peer snapshots ride the authkey-authenticated
        manager channel the data plane already trusts.

        Calling this marks the trainer as awaiting its own admission
        bump: membership bumps whose roster excludes this node are not
        "changes" until the driver has admitted it (see
        :meth:`changed`) — the stale departure bump of the seat it is
        replacing must not trigger a reconfigure."""
        self._awaiting_admission = True
        self._await_excluded_epoch = None
        self._await_since = time.monotonic()
        failpoint("elastic.rejoin_init")
        from tensorflowonspark_tpu.cluster.node import connect_manager

        recoveries = _metrics()[2]
        best: tuple[int, Any] | None = None
        for node in sorted(
            self._cur_roster, key=lambda n: n.get("executor_id", -1)
        ):
            if node.get("executor_id") == getattr(
                self._ctx, "executor_id", None
            ):
                continue
            try:
                blob = connect_manager(node).get(self._state_key)
                if not blob:
                    continue
                _ep, step, snap = pickle.loads(blob)
            except Exception as e:  # noqa: BLE001 - peers may be dying
                logger.debug(
                    "elastic hydrate: peer %s unavailable (%s)",
                    node.get("executor_id"),
                    e,
                )
                continue
            if best is None or int(step) > best[0]:
                best = (int(step), snap)
        outcome = "peer_hydrate"
        if best is None:
            step_snap = self._checkpoint_hydrate(default)
            if step_snap is None:
                recoveries.inc(outcome="fresh_init")
                flightrec.note("elastic_hydrate", outcome="fresh_init")
                return None, default
            best, outcome = step_snap, "checkpoint_fallback"
        step, snap = best
        state = reshard_state(
            snap, self._shardings_fn(snap, self.mesh())
        )
        recoveries.inc(outcome=outcome)
        flightrec.note("elastic_hydrate", outcome=outcome, step=step)
        logger.info(
            "elastic: hydrated at step %d via %s", step, outcome
        )
        return step, state

    def _checkpoint_hydrate(self, default: Any) -> tuple[int, Any] | None:
        if self._checkpoint_dir is None:
            return None
        from tensorflowonspark_tpu.compute import checkpoint as ckpt

        step, restored = ckpt.hydration_restore(
            self._checkpoint_dir, target=default
        )
        if restored is None:
            return None
        return int(step), host_snapshot(restored)
