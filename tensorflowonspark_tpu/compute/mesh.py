"""Device mesh construction and common shardings.

The mesh axes are fixed project-wide (SURVEY.md §7 step 3):

- ``data``  — pure data parallel (replicated params, sharded batch)
- ``fsdp``  — data parallel with sharded params/optimizer state (the TPU
  replacement for the reference's parameter servers)
- ``pipe``  — pipeline parallel: layer stages ring-scheduled with
  collective permutes (:mod:`tensorflowonspark_tpu.parallel.pipeline`)
- ``expert`` — expert parallel: MoE expert banks sharded across devices,
  tokens exchanged via XLA all_to_all
  (:mod:`tensorflowonspark_tpu.parallel.moe`)
- ``model`` — tensor parallel (Megatron-style column/row shardings)
- ``seq``   — sequence/context parallel for ring attention
  (:mod:`tensorflowonspark_tpu.parallel.ring_attention`)

The reference had none of these beyond plain DP (SURVEY.md §2.3).

Axis *placement* determines which interconnect collectives ride: inner axes
map to ICI within a slice, outer axes to DCN across slices — use
``create_hybrid_device_mesh`` when spanning slices.
"""

from __future__ import annotations

import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding

# The axis names (and every spec built over them) are DECLARED in the
# layout table; this module re-exports them for its long-standing
# importers. See docs/DESIGN.md "Layout table".
from tensorflowonspark_tpu.compute.layout import (  # noqa: F401
    BATCH_AXES,
    MESH_AXES,
)
from tensorflowonspark_tpu.compute import layout as _layout


def make_mesh(
    axis_shapes: Mapping[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all global devices).

    ``axis_shapes`` maps axis name → size; at most one axis may be ``-1``
    (inferred). Missing axes get size 1, so downstream code can always
    refer to every name in :data:`MESH_AXES`. Default: everything on
    ``data``.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    shapes = dict(axis_shapes or {"data": n})
    for ax in shapes:
        if ax not in MESH_AXES:
            raise ValueError(f"unknown mesh axis {ax!r}; expected {MESH_AXES}")
    infer = [ax for ax, s in shapes.items() if s == -1]
    if len(infer) > 1:
        raise ValueError("at most one axis may be -1")
    known = math.prod(s for s in shapes.values() if s != -1)
    if infer:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        shapes[infer[0]] = n // known
    full = [shapes.get(ax, 1) for ax in MESH_AXES]
    if math.prod(full) != n:
        raise ValueError(
            f"mesh {dict(zip(MESH_AXES, full))} needs {math.prod(full)} "
            f"devices, have {n}"
        )
    try:
        dev_array = mesh_utils.create_device_mesh(full, devices=devices)
    except (ValueError, AssertionError):
        # CPU/test meshes where ICI topology assignment has no meaning
        dev_array = np.asarray(devices).reshape(full)
    return Mesh(dev_array, MESH_AXES)


def fit_axis_shapes(
    axis_shapes: Mapping[str, int] | None,
    n_devices: int,
    elastic_axis: str = "fsdp",
) -> dict[str, int]:
    """Deterministically re-fit an axis spec to a changed device count.

    The elastic plane re-forms the mesh after a membership change, and
    every process must derive the SAME shape from (spec, device count)
    alone — no negotiation. Rule: a spec that already defers an axis
    (``-1``) keeps its own inference; otherwise the ``elastic_axis``
    absorbs the change (its pinned size is replaced by ``-1``). Either
    way the non-inferred axes must divide ``n_devices`` — an impossible
    fit raises rather than silently padding, because a mesh the caller
    did not ask for is exactly the nondeterminism resharding cannot
    survive.
    """
    shapes = dict(axis_shapes) if axis_shapes else {elastic_axis: -1}
    if not any(s == -1 for s in shapes.values()):
        if elastic_axis not in MESH_AXES:
            raise ValueError(
                f"unknown elastic axis {elastic_axis!r}; expected one "
                f"of {MESH_AXES}"
            )
        shapes[elastic_axis] = -1
    known = math.prod(s for s in shapes.values() if s != -1)
    if known <= 0 or n_devices % known:
        raise ValueError(
            f"axis spec {dict(shapes)} cannot fit {n_devices} devices: "
            f"fixed axes multiply to {known}"
        )
    return shapes


def parse_axis_spec(spec: str) -> dict[str, int]:
    """Parse a CLI mesh spec ``'data=2,model=4'`` into the axis-shape
    mapping :func:`make_mesh` takes (``-1`` = infer, like make_mesh).
    Axis-name validation is make_mesh's job; this only parses."""
    out: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad mesh spec segment {part!r}; expected axis=size "
                "(e.g. 'data=2,model=4')"
            )
        key, val = part.split("=", 1)
        out[key.strip()] = int(val)
    if not out:
        raise ValueError(f"empty mesh spec {spec!r}")
    return out


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Sharding for a batch: leading dim over (data, fsdp), rest replicated.

    A PartitionSpec shorter than the array rank leaves trailing dims
    unsharded, so the default works for any-rank leaves of a batch pytree.
    (Delegates to the layout table's 'batch' activation role.)
    """
    return _layout.batch_sharding(mesh, ndim)


def replicated(mesh: Mesh) -> NamedSharding:
    return _layout.replicated(mesh)


def data_parallel_size(mesh: Mesh) -> int:
    return mesh.shape["data"] * mesh.shape["fsdp"]


def shard_batch(mesh: Mesh, batch):
    """Place a host batch onto the mesh, sharded on the batch dim.

    Single-controller: ``device_put`` of the full batch. Multi-process
    (``jax.process_count() > 1``): each process passes its *local* slice
    of the global batch — the per-host share the feed plane delivered —
    and :func:`jax.make_array_from_process_local_data` assembles the
    global array (the TPU equivalent of the reference's per-worker
    MWMS input pipelines: every host contributes distinct data,
    ``compat.disable_auto_shard`` semantics by construction).
    """
    if jax.process_count() > 1:
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                batch_sharding(mesh, np.ndim(x)), np.asarray(x)
            ),
            batch,
        )
    return jax.tree.map(
        lambda x: jax.device_put(x, batch_sharding(mesh, np.ndim(x))), batch
    )
