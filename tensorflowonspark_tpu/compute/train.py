"""Sharded train/eval step builders.

This module replaces the reference's two data-parallel families
(async parameter-server and MultiWorkerMirroredStrategy, SURVEY.md §2.3)
with one mechanism: ``jax.jit`` over a mesh with ``NamedSharding``.

- DP   = params replicated, batch sharded on ``('data','fsdp')`` — XLA
  inserts the gradient psum that NCCL all-reduce did in the reference.
- FSDP = additionally shard params/optimizer state on ``'fsdp'`` — the
  sharded-state role the reference's parameter servers played, without the
  asymmetric-role processes.
- ZeRO (``zero_sharding=True``, the default) = additionally partition
  the optimizer state and the weight update across the ``'data'``
  replica axis (arXiv 2004.13336, the PAPERS.md recipe): the gradient
  mean's psum lowers to a reduce-scatter the scheduler overlaps into
  the backward, the Adam/master update computes on 1/N of every leaf,
  and one all-gather republishes the updated params. The layout is
  derived from ``LAYOUT_TABLES['optimizer']``
  (:func:`layout.optimizer_state_spec`), never hand-built here; the
  replicated path stays available as ``zero_sharding=False`` for A/B.

Adding TP/SP later is a sharding-rule change, not a rewrite (the mesh
already carries ``model``/``seq`` axes).
"""

from __future__ import annotations

import re
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding

from tensorflowonspark_tpu.compute import layout as _layout
from tensorflowonspark_tpu.compute.mesh import batch_sharding, replicated
from tensorflowonspark_tpu.obs import spans as obs_spans

# The layout table's declared per-param optimizer-state roles (Adam
# moments, masters, momentum traces): the EXPLICIT resolution
# state_shardings uses instead of shape-coincidence guessing.
_PER_PARAM_STATE_RE = re.compile(_layout.OPTIMIZER_PARAM_STATE_PATTERN)

# The named scope grouping the optimizer's device ops in traces.
# obs/trace_report.py's 'weight_update' classifier keys on this literal
# (lockstep-pinned by tests/test_obs.py).
WEIGHT_UPDATE_SCOPE = "train.weight_update"


@struct.dataclass
class TrainState:
    """Minimal train state pytree: step counter, params, optimizer state.

    (flax's ``train_state.TrainState`` keeps ``apply_fn``/``tx`` inside the
    pytree; we keep the state pure data so it shards, checkpoints, and
    crosses process boundaries cleanly.)
    """

    step: jax.Array
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )


def fsdp_shardings(
    params: Any,
    mesh: Mesh,
    min_shard_elements: int = 1024,
    axis: str = "fsdp",
) -> Any:
    """Derive FSDP NamedShardings for a param pytree.

    Rule: shard the *largest* dimension divisible by the fsdp axis size;
    tiny tensors (biases, norms) stay replicated (the layout table's
    generic shape-driven rule, :func:`layout.fsdp_leaf_spec`). This
    mirrors how the reference's PS spread variables across ps shards
    (greedy variable placement), re-expressed as mesh sharding.
    """

    def rule(x) -> NamedSharding:
        return _layout.fsdp_leaf_sharding(
            mesh, np.shape(x), axis=axis,
            min_shard_elements=min_shard_elements,
        )

    return jax.tree.map(rule, params)


def state_shardings(
    state: TrainState,
    mesh: Mesh,
    param_shardings: Any,
    zero_sharding: bool = True,
) -> TrainState:
    """Shardings for a full TrainState, derived from the layout table's
    optimizer-state rules (``LAYOUT_TABLES['optimizer']``).

    Optimizer-state subtrees that structurally mirror the param tree
    (Adam moments, momentum traces, mixed-precision masters) reuse the
    param shardings position-for-position; with ``zero_sharding=True``
    (the default) the per-param state fields the table declares
    additionally partition over the ``'data'`` replica axis — the
    ZeRO-style cross-replica weight update (arXiv 2004.13336) — with
    the table's divisibility semantics dropping indivisible leaves back
    to the mirrored spec. Scalars and undeclared fields replicate.

    Resolution is EXPLICIT: whether a subtree mirrors the param tree is
    decided by tree structure, and — for the one-leaf param tree where
    ANY lone array matches structurally (e.g. Adam's scalar ``count``)
    — by the field's declared role in the table, not by the old
    shape-coincidence special case.
    """
    params_treedef = jax.tree.structure(state.params)
    multi_leaf = params_treedef.num_leaves > 1

    def mirrors_params(node, path: str) -> bool:
        if jax.tree.structure(node) != params_treedef:
            return False
        if multi_leaf:
            return True
        return bool(_PER_PARAM_STATE_RE.search(path))

    def mirrored(node, path: str):
        def leaf_rule(ppath, psh, leaf) -> NamedSharding:
            if not zero_sharding:
                return psh
            name = _layout._path_name(ppath)
            return _layout.optimizer_state_sharding(
                mesh,
                f"{path}/{name}" if name else path,
                np.shape(leaf),
                psh.spec,
            )

        return jax.tree_util.tree_map_with_path(
            leaf_rule, param_shardings, node
        )

    def rec(node, path: str):
        if mirrors_params(node, path):
            return mirrored(node, path)
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(
                rec(getattr(node, f), f"{path}/{f}" if path else f)
                for f in node._fields
            ))
        if isinstance(node, (tuple, list)):
            return type(node)(
                rec(c, f"{path}/{i}" if path else str(i))
                for i, c in enumerate(node)
            )
        if isinstance(node, dict):
            return {
                k: rec(v, f"{path}/{k}" if path else str(k))
                for k, v in node.items()
            }
        return jax.tree.map(lambda _: replicated(mesh), node)

    return TrainState(
        step=replicated(mesh),
        params=param_shardings,
        opt_state=rec(state.opt_state, ""),
    )


def zero_update_shardings(
    params: Any, mesh: Mesh, param_shardings: Any
) -> Any:
    """NamedShardings for a param-shaped UPDATE tree (gradients,
    optimizer deltas) under the layout table's ZeRO rules: each leaf's
    param spec plus the ``'data'`` partition where divisible. This is
    the sharding the gradient reduce-scatters INTO and the sharded Adam
    update computes in."""

    def rule(path, p, psh) -> NamedSharding:
        return _layout.optimizer_state_sharding(
            mesh,
            "update/" + _layout._path_name(path),
            np.shape(p),
            psh.spec,
        )

    return jax.tree_util.tree_map_with_path(rule, params, param_shardings)


def shard_state(
    state: TrainState,
    mesh: Mesh,
    param_shardings: Any,
    zero_sharding: bool = True,
) -> TrainState:
    """Commit every leaf of ``state`` to its mesh sharding: params to
    ``param_shardings``, optimizer subtrees that mirror the param tree
    likewise (ZeRO data-axis partitioned by default — see
    :func:`state_shardings`), scalars (step, Adam count) replicated.

    Create train state as ``shard_state(TrainState.create(p, tx), mesh,
    psh)`` whenever it will be checkpointed: orbax restores each array to
    the *target's* committed sharding, and a target with stray
    default-device leaves (e.g. from an optimizer init that used plain
    ``jnp.zeros``) restores to committed single-device arrays, which the
    train step's explicit in_shardings then reject under
    multi-controller FSDP instead of implicitly resharding.
    """
    return jax.tree.map(
        jax.device_put,
        state,
        state_shardings(state, mesh, param_shardings, zero_sharding),
    )


def build_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    param_shardings: Any | None = None,
    donate: bool = True,
    accum_steps: int = 1,
    batch_weight_fn: Callable[[Any], jax.Array] | None = None,
    zero_sharding: bool = True,
) -> Callable[[TrainState, Any], tuple[TrainState, jax.Array]]:
    """Compile ``(state, batch) -> (state, loss)`` with mesh shardings.

    ``loss_fn(params, batch) -> scalar`` must mean-reduce over the global
    batch; since the batch is sharded over ``('data','fsdp')``, XLA lowers
    the mean's reduction to a psum over ICI — the entire gradient-sync
    machinery the reference delegated to NCCL/PS.

    ``zero_sharding`` (default True) turns that psum into the ZeRO
    decomposition where the mesh has a ``'data'`` axis wider than 1:
    gradients reduce-scatter into the layout table's data-partitioned
    update layout (overlappable with the backward), the optimizer state
    lives and updates in the same partition, and the updated params
    all-gather back to their table shardings. ``zero_sharding=False``
    is the replicated-optimizer escape hatch for A/B: the weight-update
    decomposition itself is elementwise, hence byte-identical across
    knobs on identical gradients (``bench.py --zero``'s smoke gate pins
    this); the full train paths agree to reduction-order tolerance
    (reduce-scatter vs all-reduce summation grouping, ~1 ulp). State
    committed with :func:`shard_state` should use the SAME knob value
    (a mismatched state is re-committed once at the first call).

    ``accum_steps > 1`` runs gradient accumulation: the batch's leading
    dim splits into that many microbatches, a ``lax.scan`` accumulates
    their gradients in fp32 (so bf16-param configs don't round 8-bit
    mantissas per add), and ONE optimizer update applies the mean. For
    losses whose mean weights every microbatch equally (fixed-shape
    batches — the usual case) this reproduces the full-batch step
    exactly. For losses that normalize by a per-call VALID count (e.g.
    the packed/masked CE: ``sum(nll*mask)/sum(mask)``), pass
    ``batch_weight_fn(microbatch) -> scalar`` returning that count
    (e.g. ``lambda b: b["mask"].sum()``): each microbatch's loss and
    gradients are then accumulated as (value·count, count) and divided
    once by the total, reproducing the full-batch token weighting
    exactly instead of weighting microbatch *means* equally.
    Accumulation is the memory lever when the target global batch's
    activations exceed HBM even after remat; each microbatch must still
    divide the ``('data','fsdp')`` mesh extent.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    compiled: dict[str, Any] = {}

    def wrapped(state: TrainState, batch):
        if "fn" not in compiled:
            psh = (
                param_shardings
                if param_shardings is not None
                else jax.tree.map(lambda _: replicated(mesh), state.params)
            )
            step = make_step_fn(
                loss_fn,
                tx,
                mesh,
                accum_steps=accum_steps,
                batch_weight_fn=batch_weight_fn,
                param_shardings=psh,
                zero_sharding=zero_sharding,
            )
            state_sh = state_shardings(state, mesh, psh, zero_sharding)
            compiled["fn"] = jax.jit(
                step,
                in_shardings=(state_sh, batch_sharding(mesh)),
                out_shardings=(state_sh, replicated(mesh)),
                donate_argnums=(0,) if donate else (),
            )
            # First-call commit: a state built without shard_state
            # (moments inherit the PARAM placement via zeros_like)
            # arrives committed off the ZeRO layout, which explicit
            # in_shardings reject rather than silently reshard.
            # device_put is a no-op for already-matching leaves, and
            # every subsequent step's input is this step's output.
            state = jax.tree.map(jax.device_put, state, state_sh)
        # Host-side step span (obs/): measures DISPATCH time — jit
        # returns as soon as the computation is enqueued, so the
        # data-wait vs step split reads as "host blocked here" only
        # when the caller's fetch forces it. StepTraceAnnotation makes
        # an active jax.profiler device trace group this step's XLA
        # ops under the same step number. A host-side call counter, not
        # state.step: fetching the device scalar per step would sync.
        n = compiled["n"] = compiled.get("n", 0) + 1
        with obs_spans.get_tracer().step_span("train.step", step_num=n):
            return compiled["fn"](state, batch)

    return wrapped


def make_step_fn(
    loss_fn: Callable[[Any, Any], jax.Array],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    accum_steps: int = 1,
    batch_weight_fn: Callable[[Any], jax.Array] | None = None,
    param_shardings: Any | None = None,
    zero_sharding: bool = True,
) -> Callable[[TrainState, Any], tuple[TrainState, jax.Array]]:
    """The UNJITTED ``(state, batch) -> (state, loss)`` train step.

    :func:`build_train_step` jits this with shardings/donation;
    ``tools/shardcheck.py`` lowers it abstractly (AOT, on faux CPU
    devices) to census the collectives the layout table implies — both
    consumers must see the SAME program, which is why this is one
    function and not two copies.

    With ``zero_sharding`` on (and ``param_shardings`` given, on a mesh
    whose ``'data'`` axis is wider than 1) the gradient tree is pinned
    to the layout table's data-partitioned update layout before the
    optimizer update: GSPMD then lowers the grad mean's psum to a
    reduce-scatter (which the latency-hiding scheduler overlaps into
    the backward), the Adam/master arithmetic runs on the shard, and
    the updated params all-gather back to their own shardings — the
    arXiv 2004.13336 dataflow. The optimizer arithmetic itself is
    grouped under a ``train.weight_update`` ``jax.named_scope`` so
    device traces attribute its ops (``obs.trace_report``'s
    ``weight_update`` category).
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    zero_on = (
        zero_sharding
        and param_shardings is not None
        and dict(mesh.shape).get("data", 1) > 1
    )

    def scatter(tree):
        """Pin a param-shaped gradient/carry tree to the ZeRO update
        layout (a no-op leaf-wise where the table dropped the data
        axis, and entirely when the knob is off)."""
        if not zero_on:
            return tree
        shardings = zero_update_shardings(tree, mesh, param_shardings)

        def pin(g, sh, psh):
            if sh.spec == psh.spec:
                return g  # dropped-to-mirrored leaf: nothing to add
            return jax.lax.with_sharding_constraint(g, sh)

        return jax.tree.map(pin, tree, shardings, param_shardings)

    def grads_of(state: TrainState, batch):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
            return loss, scatter(grads)

        dp_extent = mesh.shape["data"] * mesh.shape["fsdp"]

        def split(x):
            if x.shape[0] % accum_steps:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by "
                    f"accum_steps {accum_steps}"
                )
            if (x.shape[0] // accum_steps) % dp_extent:
                # silent GSPMD padding would idle chips on exactly the
                # big-pod configs accumulation targets — fail fast
                raise ValueError(
                    f"microbatch dim {x.shape[0] // accum_steps} "
                    f"(batch {x.shape[0]} / accum_steps {accum_steps}) "
                    f"not divisible by the (data, fsdp) mesh extent "
                    f"{dp_extent}"
                )
            return x.reshape(
                accum_steps, x.shape[0] // accum_steps, *x.shape[1:]
            )

        micro = jax.tree.map(split, batch)
        # fp32 carry regardless of param dtype: summing bf16 gradient
        # trees would round at each add; optax updates widen anyway.
        # Under ZeRO the carry lives scattered too: each microbatch's
        # reduce lands as a reduce-scatter accumulated into the shard.
        zeros = scatter(
            jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )
        )

        def body(carry, mb):
            loss_sum, grad_sum, w_sum = carry
            loss, grads = jax.value_and_grad(loss_fn)(state.params, mb)
            grads = scatter(grads)
            w = (
                jnp.ones((), jnp.float32)
                if batch_weight_fn is None
                else batch_weight_fn(mb).astype(jnp.float32)
            )
            return (
                loss_sum + loss * w,
                jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32) * w,
                    grad_sum,
                    grads,
                ),
                w_sum + w,
            ), None

        (loss_sum, grad_sum, w_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros, jnp.zeros((), jnp.float32)), micro
        )
        # w_sum == accum_steps for the unweighted path; guard a fully
        # masked-out batch (all counts zero) against 0/0
        inv = 1.0 / jnp.maximum(w_sum, 1e-6)
        return loss_sum * inv, jax.tree.map(lambda g: g * inv, grad_sum)

    def step(state: TrainState, batch):
        # Publish the mesh for the duration of the trace: model code deep
        # inside loss_fn keys mesh-aware dispatch on the ambient mesh
        # (ops.attention's auto -> mesh_flash_attention shard_map route,
        # impl='ring'/'ulysses') and must see it without the caller
        # remembering to wrap every train call in parallel.use_mesh.
        from tensorflowonspark_tpu.parallel import use_mesh

        with use_mesh(mesh):
            loss, grads = grads_of(state, batch)
        return _apply_weight_update(tx, state, grads), loss

    return step


def _apply_weight_update(
    tx: optax.GradientTransformation, state: TrainState, grads
) -> TrainState:
    """The optimizer apply shared by :func:`make_step_fn` and
    :func:`build_update_step` — ONE implementation, so the isolated
    A/B span (bench.py --zero) measures exactly what the train step
    runs, under the named scope device traces attribute
    (obs.trace_report's ``weight_update`` category — the before/after
    evidence for the ZeRO A/B)."""
    with jax.named_scope(WEIGHT_UPDATE_SCOPE):
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
    return TrainState(
        step=state.step + 1, params=new_params, opt_state=new_opt
    )


def build_update_step(
    tx: optax.GradientTransformation,
    mesh: Mesh,
    param_shardings: Any | None = None,
    zero_sharding: bool = True,
    donate: bool = True,
) -> Callable[[TrainState, Any], TrainState]:
    """Compile ``(state, grads) -> state`` — the weight update ALONE.

    Same shardings/donation discipline as :func:`build_train_step`
    (gradients arrive in the ZeRO update layout when the knob is on),
    so the optimizer fraction of step time is measurable in isolation:
    the ``bench.py --zero`` A/B leg times this against fixed gradients.
    Every call runs under a ``train.weight_update`` span and is
    observed into the ``train_weight_update_seconds`` histogram; like
    ``train.step`` the span measures DISPATCH — callers timing the
    device must barrier on a fetched leaf.
    """
    from tensorflowonspark_tpu.obs.registry import default_registry

    hist = default_registry().histogram(
        "train_weight_update_seconds",
        "wall seconds per optimizer weight-update dispatch",
    )

    def update(state: TrainState, grads) -> TrainState:
        return _apply_weight_update(tx, state, grads)

    compiled: dict[str, Any] = {}

    def wrapped(state: TrainState, grads) -> TrainState:
        if "fn" not in compiled:
            psh = (
                param_shardings
                if param_shardings is not None
                else jax.tree.map(lambda _: replicated(mesh), state.params)
            )
            state_sh = state_shardings(state, mesh, psh, zero_sharding)
            grad_sh = (
                zero_update_shardings(state.params, mesh, psh)
                if zero_sharding
                else psh
            )
            compiled["fn"] = jax.jit(
                update,
                in_shardings=(state_sh, grad_sh),
                out_shardings=state_sh,
                donate_argnums=(0,) if donate else (),
            )
            # same first-call commit as build_train_step: accept states
            # built without shard_state
            state = jax.tree.map(jax.device_put, state, state_sh)
        t0 = time.perf_counter()
        with obs_spans.span(WEIGHT_UPDATE_SCOPE):
            out = compiled["fn"](state, grads)
        hist.observe(time.perf_counter() - t0)
        return out

    return wrapped


def build_eval_step(
    metric_fn: Callable[[Any, Any], Any], mesh: Mesh
) -> Callable[[Any, Any], Any]:
    """Compile ``(params, batch) -> metrics`` with batch sharded on the mesh."""

    def traced(params, batch):
        # same ambient-mesh publication as build_train_step: eval-path
        # model code keys mesh-aware dispatch on it too
        from tensorflowonspark_tpu.parallel import use_mesh

        with use_mesh(mesh):
            return metric_fn(params, batch)

    return jax.jit(
        traced,
        in_shardings=(None, batch_sharding(mesh)),
        out_shardings=replicated(mesh),
    )
