"""Sharded train/eval step builders.

This module replaces the reference's two data-parallel families
(async parameter-server and MultiWorkerMirroredStrategy, SURVEY.md §2.3)
with one mechanism: ``jax.jit`` over a mesh with ``NamedSharding``.

- DP   = params replicated, batch sharded on ``('data','fsdp')`` — XLA
  inserts the gradient psum that NCCL all-reduce did in the reference.
- FSDP = additionally shard params/optimizer state on ``'fsdp'`` — the
  sharded-state role the reference's parameter servers played, without the
  asymmetric-role processes.

Adding TP/SP later is a sharding-rule change, not a rewrite (the mesh
already carries ``model``/``seq`` axes).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding

from tensorflowonspark_tpu.compute import layout as _layout
from tensorflowonspark_tpu.compute.mesh import batch_sharding, replicated
from tensorflowonspark_tpu.obs import spans as obs_spans


@struct.dataclass
class TrainState:
    """Minimal train state pytree: step counter, params, optimizer state.

    (flax's ``train_state.TrainState`` keeps ``apply_fn``/``tx`` inside the
    pytree; we keep the state pure data so it shards, checkpoints, and
    crosses process boundaries cleanly.)
    """

    step: jax.Array
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )


def fsdp_shardings(
    params: Any,
    mesh: Mesh,
    min_shard_elements: int = 1024,
    axis: str = "fsdp",
) -> Any:
    """Derive FSDP NamedShardings for a param pytree.

    Rule: shard the *largest* dimension divisible by the fsdp axis size;
    tiny tensors (biases, norms) stay replicated (the layout table's
    generic shape-driven rule, :func:`layout.fsdp_leaf_spec`). This
    mirrors how the reference's PS spread variables across ps shards
    (greedy variable placement), re-expressed as mesh sharding.
    """

    def rule(x) -> NamedSharding:
        return _layout.fsdp_leaf_sharding(
            mesh, np.shape(x), axis=axis,
            min_shard_elements=min_shard_elements,
        )

    return jax.tree.map(rule, params)


def state_shardings(state: TrainState, mesh: Mesh, param_shardings: Any) -> TrainState:
    """Shardings for a full TrainState.

    Optimizer-state subtrees that structurally mirror the param tree (Adam
    moments, momentum, etc.) reuse the param shardings position-for-
    position; everything else (step counts, scalars) is replicated.
    """
    params_treedef = jax.tree.structure(state.params)
    single_param = params_treedef.num_leaves == 1
    param_leaf_shapes = [np.shape(p) for p in jax.tree.leaves(state.params)]

    def mirrors_params(node) -> bool:
        if jax.tree.structure(node) != params_treedef:
            return False
        if single_param:
            # A one-leaf treedef matches any lone array (e.g. Adam's
            # `count` scalar); require the shape to match too.
            return [np.shape(x) for x in jax.tree.leaves(node)] == param_leaf_shapes
        return True

    def rec(node):
        if mirrors_params(node):
            return param_shardings
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(rec(c) for c in node))
        if isinstance(node, (tuple, list)):
            return type(node)(rec(c) for c in node)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return jax.tree.map(lambda _: replicated(mesh), node)

    return TrainState(
        step=replicated(mesh),
        params=param_shardings,
        opt_state=rec(state.opt_state),
    )


def shard_state(
    state: TrainState, mesh: Mesh, param_shardings: Any
) -> TrainState:
    """Commit every leaf of ``state`` to its mesh sharding: params to
    ``param_shardings``, optimizer subtrees that mirror the param tree
    likewise, scalars (step, Adam count) replicated.

    Create train state as ``shard_state(TrainState.create(p, tx), mesh,
    psh)`` whenever it will be checkpointed: orbax restores each array to
    the *target's* committed sharding, and a target with stray
    default-device leaves (e.g. from an optimizer init that used plain
    ``jnp.zeros``) restores to committed single-device arrays, which the
    train step's explicit in_shardings then reject under
    multi-controller FSDP instead of implicitly resharding.
    """
    return jax.tree.map(
        jax.device_put, state, state_shardings(state, mesh, param_shardings)
    )


def build_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    param_shardings: Any | None = None,
    donate: bool = True,
    accum_steps: int = 1,
    batch_weight_fn: Callable[[Any], jax.Array] | None = None,
) -> Callable[[TrainState, Any], tuple[TrainState, jax.Array]]:
    """Compile ``(state, batch) -> (state, loss)`` with mesh shardings.

    ``loss_fn(params, batch) -> scalar`` must mean-reduce over the global
    batch; since the batch is sharded over ``('data','fsdp')``, XLA lowers
    the mean's reduction to a psum over ICI — the entire gradient-sync
    machinery the reference delegated to NCCL/PS.

    ``accum_steps > 1`` runs gradient accumulation: the batch's leading
    dim splits into that many microbatches, a ``lax.scan`` accumulates
    their gradients in fp32 (so bf16-param configs don't round 8-bit
    mantissas per add), and ONE optimizer update applies the mean. For
    losses whose mean weights every microbatch equally (fixed-shape
    batches — the usual case) this reproduces the full-batch step
    exactly. For losses that normalize by a per-call VALID count (e.g.
    the packed/masked CE: ``sum(nll*mask)/sum(mask)``), pass
    ``batch_weight_fn(microbatch) -> scalar`` returning that count
    (e.g. ``lambda b: b["mask"].sum()``): each microbatch's loss and
    gradients are then accumulated as (value·count, count) and divided
    once by the total, reproducing the full-batch token weighting
    exactly instead of weighting microbatch *means* equally.
    Accumulation is the memory lever when the target global batch's
    activations exceed HBM even after remat; each microbatch must still
    divide the ``('data','fsdp')`` mesh extent.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    step = make_step_fn(
        loss_fn,
        tx,
        mesh,
        accum_steps=accum_steps,
        batch_weight_fn=batch_weight_fn,
    )

    def jit_with(state_sh):
        return jax.jit(
            step,
            in_shardings=(state_sh, batch_sharding(mesh)),
            out_shardings=(state_sh, replicated(mesh)),
            donate_argnums=(0,) if donate else (),
        )

    compiled: dict[str, Any] = {}

    def wrapped(state: TrainState, batch):
        if "fn" not in compiled:
            psh = (
                param_shardings
                if param_shardings is not None
                else jax.tree.map(lambda _: replicated(mesh), state.params)
            )
            compiled["fn"] = jit_with(state_shardings(state, mesh, psh))
        # Host-side step span (obs/): measures DISPATCH time — jit
        # returns as soon as the computation is enqueued, so the
        # data-wait vs step split reads as "host blocked here" only
        # when the caller's fetch forces it. StepTraceAnnotation makes
        # an active jax.profiler device trace group this step's XLA
        # ops under the same step number. A host-side call counter, not
        # state.step: fetching the device scalar per step would sync.
        n = compiled["n"] = compiled.get("n", 0) + 1
        with obs_spans.get_tracer().step_span("train.step", step_num=n):
            return compiled["fn"](state, batch)

    return wrapped


def make_step_fn(
    loss_fn: Callable[[Any, Any], jax.Array],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    accum_steps: int = 1,
    batch_weight_fn: Callable[[Any], jax.Array] | None = None,
) -> Callable[[TrainState, Any], tuple[TrainState, jax.Array]]:
    """The UNJITTED ``(state, batch) -> (state, loss)`` train step.

    :func:`build_train_step` jits this with shardings/donation;
    ``tools/shardcheck.py`` lowers it abstractly (AOT, on faux CPU
    devices) to census the collectives the layout table implies — both
    consumers must see the SAME program, which is why this is one
    function and not two copies.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def grads_of(state: TrainState, batch):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn)(state.params, batch)

        dp_extent = mesh.shape["data"] * mesh.shape["fsdp"]

        def split(x):
            if x.shape[0] % accum_steps:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by "
                    f"accum_steps {accum_steps}"
                )
            if (x.shape[0] // accum_steps) % dp_extent:
                # silent GSPMD padding would idle chips on exactly the
                # big-pod configs accumulation targets — fail fast
                raise ValueError(
                    f"microbatch dim {x.shape[0] // accum_steps} "
                    f"(batch {x.shape[0]} / accum_steps {accum_steps}) "
                    f"not divisible by the (data, fsdp) mesh extent "
                    f"{dp_extent}"
                )
            return x.reshape(
                accum_steps, x.shape[0] // accum_steps, *x.shape[1:]
            )

        micro = jax.tree.map(split, batch)
        # fp32 carry regardless of param dtype: summing bf16 gradient
        # trees would round at each add; optax updates widen anyway
        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params
        )

        def body(carry, mb):
            loss_sum, grad_sum, w_sum = carry
            loss, grads = jax.value_and_grad(loss_fn)(state.params, mb)
            w = (
                jnp.ones((), jnp.float32)
                if batch_weight_fn is None
                else batch_weight_fn(mb).astype(jnp.float32)
            )
            return (
                loss_sum + loss * w,
                jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32) * w,
                    grad_sum,
                    grads,
                ),
                w_sum + w,
            ), None

        (loss_sum, grad_sum, w_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros, jnp.zeros((), jnp.float32)), micro
        )
        # w_sum == accum_steps for the unweighted path; guard a fully
        # masked-out batch (all counts zero) against 0/0
        inv = 1.0 / jnp.maximum(w_sum, 1e-6)
        return loss_sum * inv, jax.tree.map(lambda g: g * inv, grad_sum)

    def step(state: TrainState, batch):
        # Publish the mesh for the duration of the trace: model code deep
        # inside loss_fn keys mesh-aware dispatch on the ambient mesh
        # (ops.attention's auto -> mesh_flash_attention shard_map route,
        # impl='ring'/'ulysses') and must see it without the caller
        # remembering to wrap every train call in parallel.use_mesh.
        from tensorflowonspark_tpu.parallel import use_mesh

        with use_mesh(mesh):
            loss, grads = grads_of(state, batch)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            ),
            loss,
        )

    return step


def build_eval_step(
    metric_fn: Callable[[Any, Any], Any], mesh: Mesh
) -> Callable[[Any, Any], Any]:
    """Compile ``(params, batch) -> metrics`` with batch sharded on the mesh."""

    def traced(params, batch):
        # same ambient-mesh publication as build_train_step: eval-path
        # model code keys mesh-aware dispatch on it too
        from tensorflowonspark_tpu.parallel import use_mesh

        with use_mesh(mesh):
            return metric_fn(params, batch)

    return jax.jit(
        traced,
        in_shardings=(None, batch_sharding(mesh)),
        out_shardings=replicated(mesh),
    )
