"""Concrete knob policies over the repo's own telemetry.

Each builder wires ONE component's live-settable knob (its declared
actuation method — the registry is the only writer) to the history
signals named in docs/AUTOTUNE.md:

- :func:`prefetch_depth_policy` — grow ``DevicePrefetcher`` depth while
  ``feed.data_wait`` dominates the step, shrink when the queue is
  already hiding the producer;
- :func:`engine_knob_policies` — trade ``decode_block`` /
  ``pipeline_depth`` throughput against an admission-latency budget
  (``history.percentile`` of the request-latency histogram vs the
  deadline);
- :func:`router_estimate_policy` — tighten the ``FleetRouter``'s
  completion estimate from the measured duration distribution (direct
  mode: an estimate only informs admission, there is nothing to
  revert);
- :func:`ingest_publish_policy` — adapt ``publish_blocks`` to the
  measured cursor-publish overhead (publish often enough for a tight
  crash-replay bound, rarely enough that the RPC cost stays noise);
- :func:`cache_budget_policy` — grow the ``cachetier.CacheTier``
  byte budget while the hit share is still rising and host memory
  headroom exists, shrink before the host hits reclaim.

Builders return ``(Knob, Policy)`` pairs; callers register the knob
and hand the policy to a :class:`~tensorflowonspark_tpu.autotune.
controller.Controller`. Objectives/hints returning ``None`` (no
in-window signal) make the controller hold still — never guess.
"""

from __future__ import annotations

from typing import Any, Callable

from tensorflowonspark_tpu.autotune.controller import Policy
from tensorflowonspark_tpu.autotune.registry import Knob
from tensorflowonspark_tpu.obs.history import History

__all__ = [
    "cache_budget_policy",
    "counter_rate_objective",
    "engine_knob_policies",
    "ingest_publish_policy",
    "prefetch_depth_policy",
    "router_estimate_policy",
]


def counter_rate_objective(
    metric: str,
    labels: dict | None = None,
    window_s: float = 30.0,
) -> Callable[[History, float], float | None]:
    """The throughput objective: per-second increase of a counter over
    the trailing window (None while the window lacks two points)."""

    def objective(hist: History, now: float) -> float | None:
        return hist.rate(metric, labels, window_s=window_s, now=now)

    return objective


# -- feed plane --------------------------------------------------------------


def prefetch_depth_policy(
    prefetcher,
    *,
    objective_metric: str = "feed_batches_total",
    lo: int = 1,
    hi: int = 16,
    window_s: float = 30.0,
    wait_dominance: float = 0.15,
) -> tuple[Knob, Policy]:
    """Depth knob for a live :class:`~tensorflowonspark_tpu.feed.
    prefetch.DevicePrefetcher`. Hint: grow while the consumer spends
    more than ``wait_dominance`` of wall time blocked in
    ``feed.data_wait`` (the queue is starving the device — the
    dominance signal the tf.data controller keys on); shrink when the
    wait share is negligible (staged buffers are just pinning host
    memory). The objective is the prefetcher's delivered batches/sec."""
    knob = Knob(
        name="feed.prefetch_depth",
        lo=float(lo),
        hi=float(hi),
        step=1.0,
        apply=prefetcher.set_depth,
        get=lambda: prefetcher.stats()["depth"],
        cost_hint="queue-resize",
    )

    def hint(hist: History, now: float) -> int:
        wait_s = hist.delta_sum(
            "feed_data_wait_seconds", window_s=window_s, now=now
        )
        share = wait_s / window_s
        if share > wait_dominance:
            return 1
        if share < wait_dominance / 4.0:
            return -1
        return 0

    return knob, Policy(
        knob=knob.name,
        objective=counter_rate_objective(
            objective_metric, window_s=window_s
        ),
        hint=hint,
    )


# -- serving engine ----------------------------------------------------------


def engine_knob_policies(
    engine,
    *,
    deadline_s: float,
    latency_metric: str = "router_request_seconds",
    throughput_metric: str = "engine_tokens_emitted_total",
    decode_block_hi: int = 32,
    pipeline_depth_hi: int = 4,
    window_s: float = 30.0,
    headroom: float = 0.8,
) -> list[tuple[Knob, Policy]]:
    """``decode_block`` and ``pipeline_depth`` knobs for a running
    engine, actuated through ``ContinuousBatcher.set_knobs`` (installed
    between decode blocks, exactly like a weight swap). Hint: while the
    admission p99 sits above ``headroom × deadline_s`` the latency
    budget is being eaten — shrink (a smaller block retires requests at
    finer granularity); with p99 comfortably inside the budget, grow
    toward throughput. Objective: decoded tokens/sec."""

    def latency_hint(hist: History, now: float) -> int:
        p99 = hist.percentile(
            latency_metric, 0.99, window_s=window_s, now=now
        )
        if p99 is None:
            return 0
        if p99 > headroom * deadline_s:
            return -1
        if p99 < 0.5 * headroom * deadline_s:
            return 1
        return 0

    objective = counter_rate_objective(
        throughput_metric, window_s=window_s
    )
    block = Knob(
        name="engine.decode_block",
        lo=1.0,
        hi=float(decode_block_hi),
        step=1.0,
        apply=lambda v: engine.set_knobs(decode_block=int(v)),
        get=lambda: engine.stats()["decode_block"],
        cost_hint="recompile-per-new-k",
    )
    depth = Knob(
        name="engine.pipeline_depth",
        lo=1.0,
        hi=float(pipeline_depth_hi),
        step=1.0,
        apply=lambda v: engine.set_knobs(pipeline_depth=int(v)),
        get=lambda: engine.stats()["pipeline_depth"],
        cost_hint="window-drain",
    )
    return [
        (block, Policy(knob=block.name, objective=objective, hint=latency_hint)),
        (depth, Policy(knob=depth.name, objective=objective, hint=latency_hint)),
    ]


# -- fleet router ------------------------------------------------------------


def router_estimate_policy(
    router,
    *,
    latency_metric: str = "router_request_seconds",
    q: float = 0.9,
    lo_s: float = 0.001,
    hi_s: float = 120.0,
    window_s: float = 60.0,
) -> tuple[Knob, Policy]:
    """Direct policy: every eligible window, re-seed the router's
    cold-start service estimate from the measured latency distribution
    (q-quantile), replacing the ctor's hardcoded
    ``service_time_hint_s`` guess. Direct mode — an estimate only
    informs admission feasibility, so there is no objective to judge
    and nothing to revert."""
    knob = Knob(
        name="router.service_estimate_s",
        lo=lo_s,
        hi=hi_s,
        step=lo_s,
        apply=router.set_service_estimate,
        get=router.service_estimate,
        cost_hint="estimate-only",
        integer=False,
    )

    def target(hist: History, now: float) -> float | None:
        return hist.percentile(
            latency_metric, q, window_s=window_s, now=now
        )

    return knob, Policy(knob=knob.name, target=target)


# -- cache tier --------------------------------------------------------------


def _meminfo_headroom() -> float | None:
    """Fraction of physical memory still available
    (``MemAvailable / MemTotal`` from /proc/meminfo), or None when the
    file is unreadable (non-Linux) — the policy then holds still
    rather than guess."""
    try:
        fields: dict[str, int] = {}
        with open("/proc/meminfo", encoding="ascii") as f:
            for line in f:
                name, _, rest = line.partition(":")
                if name in ("MemTotal", "MemAvailable"):
                    fields[name] = int(rest.split()[0])
        total = fields.get("MemTotal", 0)
        avail = fields.get("MemAvailable")
        if total <= 0 or avail is None:
            return None
        return avail / total
    except OSError:
        return None


def cache_budget_policy(
    tier,
    *,
    objective_metric: str = "cachetier_hits_total",
    lo_bytes: int = 64 << 20,
    hi_bytes: int = 4 << 30,
    step_bytes: int = 64 << 20,
    window_s: float = 30.0,
    min_headroom_frac: float = 0.2,
    headroom_fn: Callable[[], float | None] | None = None,
) -> tuple[Knob, Policy]:
    """Capacity knob for a live :class:`~tensorflowonspark_tpu.
    cachetier.service.CacheTier`, actuated through
    ``CacheTier.set_capacity`` (shrink evicts immediately — the cost
    hint). Hint: GROW while the tier's hit share is still rising across
    the window (more budget is still converting misses into hits) AND
    host memory headroom exists; SHRINK when headroom drops below half
    the floor (the cache must never push the host into reclaim — it is
    an optimization, not a tenant); hold otherwise. Objective: cache
    hits/sec — the controller's objective-revert undoes a grow that
    stopped paying. ``headroom_fn`` is injectable for tests; the
    default reads ``/proc/meminfo`` and holds still when unreadable."""
    knob = Knob(
        name="cachetier.capacity_bytes",
        lo=float(lo_bytes),
        hi=float(hi_bytes),
        step=float(step_bytes),
        apply=tier.set_capacity,
        get=lambda: tier.capacity_bytes,
        cost_hint="evict-on-shrink",
    )
    headroom = headroom_fn if headroom_fn is not None else _meminfo_headroom

    def _hit_share(hist: History, now: float, w: float) -> float | None:
        # delta (not delta_sum): hits/misses are counters, and
        # delta_sum only reads histogram `sum` increases
        hits = hist.delta(
            "cachetier_hits_total", window_s=w, now=now
        )
        misses = hist.delta(
            "cachetier_misses_total", window_s=w, now=now
        )
        if hits + misses <= 0:
            return None
        return hits / (hits + misses)

    def hint(hist: History, now: float) -> int:
        head = headroom()
        if head is None:
            return 0
        if head < min_headroom_frac / 2.0:
            return -1
        # "rising" = the trailing window's hit share beats the window
        # before it (both derived from the same counters: the older
        # window is the 2w delta minus the recent w delta)
        recent = _hit_share(hist, now, window_s)
        if recent is None:
            return 0
        hits_2w = hist.delta(
            "cachetier_hits_total", window_s=2 * window_s, now=now
        )
        misses_2w = hist.delta(
            "cachetier_misses_total", window_s=2 * window_s, now=now
        )
        hits_w = hist.delta(
            "cachetier_hits_total", window_s=window_s, now=now
        )
        misses_w = hist.delta(
            "cachetier_misses_total", window_s=window_s, now=now
        )
        prior_hits = hits_2w - hits_w
        prior_misses = misses_2w - misses_w
        if prior_hits + prior_misses <= 0:
            # no prior-window traffic to compare against: grow only on
            # real recent traffic with headroom (cold start)
            return 1 if head > min_headroom_frac else 0
        prior = prior_hits / (prior_hits + prior_misses)
        if recent > prior and head > min_headroom_frac:
            return 1
        return 0

    return knob, Policy(
        knob=knob.name,
        objective=counter_rate_objective(
            objective_metric, window_s=window_s
        ),
        hint=hint,
    )


# -- ingest pull plane -------------------------------------------------------


def ingest_publish_policy(
    apply: Callable[[int], Any],
    get: Callable[[], int],
    *,
    objective_metric: str = "feed_ingest_records_total",
    lo: int = 1,
    hi: int = 256,
    step: int = 8,
    window_s: float = 30.0,
    overhead_budget: float = 0.02,
) -> tuple[Knob, Policy]:
    """``publish_blocks`` knob: how many fully-consumed blocks between
    replay-cursor publications. ``apply``/``get`` reach the feed —
    node-local runs pass ``feed.set_publish_blocks`` directly; a
    driver-side controller passes the KV re-publish path
    (``TFCluster.publish_feed_knobs``), which the node's ingest loop
    adopts at its next block boundary. Hint: while the measured
    cursor-publish overhead exceeds ``overhead_budget`` of ingest wall
    time, publish less often (grow); when overhead is negligible,
    shrink toward a tighter crash-replay duplicate bound."""
    knob = Knob(
        name="ingest.publish_blocks",
        lo=float(lo),
        hi=float(hi),
        step=float(step),
        apply=apply,
        get=get,
        cost_hint="kv-republish",
    )

    def hint(hist: History, now: float) -> int:
        publish_s = hist.delta_sum(
            "ingest_cursor_publish_seconds", window_s=window_s, now=now
        )
        if publish_s <= 0.0:
            return 0
        share = publish_s / window_s
        if share > overhead_budget:
            return 1
        if share < overhead_budget / 4.0:
            return -1
        return 0

    return knob, Policy(
        knob=knob.name,
        objective=counter_rate_objective(
            objective_metric, window_s=window_s
        ),
        hint=hint,
    )
