"""Feedback controller: hill-climb with hysteresis over obs history.

The tf.data Plateau/HillClimb shape (arXiv 2101.12127) adapted to this
repo's signals: each **window** (one :meth:`Controller.step` call,
typically driven right after a ``History.scrape_registry`` pump) the
controller makes at most ONE knob move, then spends the next window
judging it against the policy's objective:

- **improved** beyond the hysteresis band → keep, and keep direction
  (momentum);
- **regressed** beyond the band → revert through the registry, flip
  direction, and put the knob on **cooldown** for N windows;
- **inside the band** → keep the value, drop the momentum (plateau).

Gradient-free, single-writer, and fully auditable: every move/revert/
back-off is a registered flight-recorder event
(``autotune_decision`` / ``autotune_revert`` / ``autotune_frozen``),
an ``autotune_decisions_total{knob,direction}`` /
``autotune_reverts_total`` metric bump, and a row in the bounded
decision log (:meth:`Controller.decision_log`, dumped into incident
bundles by ``tools/obs_snapshot.py --autotune``).

SLO interaction: given an :class:`~tensorflowonspark_tpu.obs.slo.
SLOEvaluator`, the controller **backs off** while any SLO is in
breach — it reverts its unjudged move (the move may be the cause) and
makes no new ones until the burn clears. Tuning must never fight the
alert that pages a human (docs/AUTOTUNE.md).

Kill switch: with ``TFOS_AUTOTUNE=0`` :meth:`step` is one env read and
an immediate return — the disabled path is micro-benched in
``tests/test_autotune.py`` alongside the failpoint/tfsan bars.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

from tensorflowonspark_tpu.autotune.registry import KnobRegistry, enabled
from tensorflowonspark_tpu.obs import flightrec
from tensorflowonspark_tpu.obs.history import History
from tensorflowonspark_tpu.obs.registry import Registry, default_registry

logger = logging.getLogger(__name__)

__all__ = ["Controller", "Policy"]


@dataclass
class Policy:
    """How one registered knob is tuned.

    ``objective(history, now)`` returns the score the controller
    MAXIMIZES (throughput, negative latency, ...), or None while the
    window holds no signal. ``hint(history, now)`` optionally biases
    the next move's direction (+1 grow / -1 shrink / 0 no opinion) from
    a domain signal — e.g. grow prefetch depth while ``feed.data_wait``
    dominates the step time. ``target(history, now)`` switches the
    policy to DIRECT mode: each eligible window computes a target value
    (e.g. the router's measured p90 service time) and applies it —
    no verdict/revert cycle, because a direct policy only tightens an
    estimate rather than trading throughput against latency.
    """

    knob: str
    objective: Callable[[History, float], float | None] | None = None
    hint: Callable[[History, float], int] | None = None
    target: Callable[[History, float], float | None] | None = None
    rel_eps: float = 0.05  # hysteresis band, relative
    cooldown_windows: int = 2  # windows a reverted knob sits out
    max_pending_windows: int = 3  # verdict patience without signal

    def __post_init__(self):
        if (self.objective is None) == (self.target is None):
            raise ValueError(
                f"policy for {self.knob!r}: exactly one of objective "
                "(hill-climb) or target (direct) is required"
            )


class _KnobState:
    """Per-policy controller bookkeeping (guarded by Controller._lock)."""

    __slots__ = (
        "direction",
        "cooldown",
        "pending_from",
        "pending_to",
        "pending_baseline",
        "pending_windows",
    )

    def __init__(self):
        self.direction = 1
        self.cooldown = 0
        self.pending_from: float | None = None
        self.pending_to: float | None = None
        self.pending_baseline: float | None = None
        self.pending_windows = 0


class Controller:
    """One feedback loop over one History and one KnobRegistry.

    Driver-side (feed/ingest/router knobs over the driver's history
    pump) and engine-local (serving knobs over the replica's own
    registry) instances are the same class — what differs is which
    knobs/policies are wired in.
    """

    def __init__(
        self,
        knobs: KnobRegistry,
        history: History,
        policies: list[Policy] | tuple[Policy, ...],
        *,
        slo=None,
        metrics_registry: Registry | None = None,
        source: str = "autotune",
        log_capacity: int = 512,
    ):
        names = [p.knob for p in policies]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate policy knobs: {names}")
        for p in policies:
            knobs.knob(p.knob)  # unknown knob = loud ctor error
        self.knobs = knobs
        self.history = history
        self.policies = tuple(policies)
        self.slo = slo  # SLOEvaluator or None
        self.source = source
        reg = (
            metrics_registry
            if metrics_registry is not None
            else default_registry()
        )
        self._m_decisions = reg.counter(
            "autotune_decisions_total",
            "controller knob moves, by knob and direction",
        )
        self._m_reverts = reg.counter(
            "autotune_reverts_total",
            "controller moves undone after the objective regressed",
        )
        self._g_value = reg.gauge(
            "autotune_knob_value",
            "current value of each registered knob the controller "
            "drives",
        )
        self._lock = threading.Lock()
        self._state = {
            p.knob: _KnobState() for p in self.policies
        }  # guarded-by: self._lock
        self._log: deque = deque(
            maxlen=max(1, int(log_capacity))
        )  # guarded-by: self._lock
        self._rr = 0  # round-robin cursor  # guarded-by: self._lock
        self._windows = 0  # guarded-by: self._lock
        self._backing_off = False  # SLO-breach latch  # guarded-by: self._lock

    # -- audit trail ----------------------------------------------------

    def _record(self, action: str, knob: str, **details: Any) -> dict:
        row = {
            "t_unix": time.time(),
            "action": action,
            "knob": knob,
            **details,
        }
        with self._lock:
            self._log.append(row)
        return row

    def decision_log(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._log]

    def to_artifact(self) -> dict[str, Any]:
        """JSON-safe audit bundle: the decision log plus the knobs'
        final state — what bench commits and obs_snapshot collects."""
        with self._lock:
            log = [dict(r) for r in self._log]
            windows = self._windows
        return {
            "autotune_version": 1,
            "source": self.source,
            "windows": windows,
            "knobs": self.knobs.snapshot(),
            "decisions": log,
        }

    def dump(self, path: str | None = None) -> str:
        """Write the audit bundle to ``path`` (default
        ``logs/autotune-<source>.json`` — the glob
        ``tools/obs_snapshot.py --autotune`` folds into incident
        bundles). Atomic via rename."""
        if path is None:
            path = os.path.join("logs", f"autotune-{self.source}.json")
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_artifact(), f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path

    # -- the loop body --------------------------------------------------

    def step(self, now: float | None = None) -> list[dict]:
        """One controller window. Returns the decision rows recorded
        this window (empty when nothing moved). Call after the history
        pump's scrape so the objectives see fresh points."""
        if not enabled():
            return []  # the kill switch: one env read, nothing touched
        now = time.time() if now is None else float(now)
        with self._lock:
            self._windows += 1

        rows: list[dict] = []
        if self._slo_backoff(now, rows):
            return rows
        moved = self._judge_pending(now, rows)
        if not moved:
            self._propose_move(now, rows)
        return rows

    # -- SLO back-off ---------------------------------------------------

    def _slo_backoff(self, now: float, rows: list[dict]) -> bool:
        """While any SLO is in breach: revert the unjudged move (it may
        be the cause) and freeze all new moves. Returns True when
        backing off."""
        if self.slo is None:
            return False
        try:
            breaching = self.slo.breaching()
        except Exception:  # noqa: BLE001 - a broken evaluator must not
            # kill the tuning loop; fail open (no back-off)
            logger.exception("autotune: SLO evaluator failed")
            return False
        if not breaching:
            with self._lock:
                was = self._backing_off
                self._backing_off = False
            if was:
                rows.append(self._record("resume", "*", reason="slo_clear"))
            return False
        with self._lock:
            rising = not self._backing_off
            self._backing_off = True
        if rising:
            flightrec.note(
                "autotune_frozen",
                knob="*",
                reason="slo_breach",
                slos=",".join(breaching),
            )
            rows.append(
                self._record(
                    "backoff",
                    "*",
                    reason="slo_breach",
                    slos=list(breaching),
                )
            )
        # revert any move still awaiting a verdict: under a breach we
        # cannot attribute the burn, so undo our own last change
        for p in self.policies:
            with self._lock:
                st = self._state[p.knob]
                pending = st.pending_from is not None
            if pending:
                rows.append(self._revert(p, st, reason="slo_breach"))
        return True

    # -- verdict on the last move --------------------------------------

    def _judge_pending(self, now: float, rows: list[dict]) -> bool:
        """Resolve at most one pending move's verdict. A revert
        consumes the window's move budget (returns True)."""
        for p in self.policies:
            if p.target is not None:
                continue  # direct policies carry no verdict cycle
            with self._lock:
                st = self._state[p.knob]
                if st.pending_from is None:
                    continue
                baseline = st.pending_baseline
                st.pending_windows += 1
                patience_exhausted = (
                    st.pending_windows > p.max_pending_windows
                )
            score = p.objective(self.history, now)
            if score is None:
                if patience_exhausted:
                    # windows of silence: treat as a failed move (the
                    # signal died right after we touched the knob)
                    rows.append(self._revert(p, st, reason="no_signal"))
                    return True
                continue
            if baseline is None:
                # no pre-move baseline (cold start): accept and seed
                self._accept(p, st, score, rows, momentum=False)
                continue
            band = abs(baseline) * p.rel_eps
            if score >= baseline + band:
                self._accept(p, st, score, rows, momentum=True)
            elif score <= baseline - band:
                rows.append(self._revert(p, st, reason="regression"))
                return True
            else:
                self._accept(p, st, score, rows, momentum=False)
        return False

    def _accept(
        self,
        p: Policy,
        st: _KnobState,
        score: float,
        rows: list[dict],
        momentum: bool,
    ) -> None:
        with self._lock:
            frm, to = st.pending_from, st.pending_to
            st.pending_from = None
            if not momentum:
                st.direction = 0  # plateau: next hint re-picks
        rows.append(
            self._record(
                "accept",
                p.knob,
                value=to,
                moved_from=frm,
                score=score,
                momentum=momentum,
            )
        )

    def _revert(self, p: Policy, st: _KnobState, reason: str) -> dict:
        with self._lock:
            frm, to = st.pending_from, st.pending_to
            st.pending_from = None
            st.direction = -st.direction if st.direction else -1
            st.cooldown = p.cooldown_windows
        if frm is None:  # raced with another resolver: nothing to undo
            return self._record("revert", p.knob, reason=reason, noop=True)
        actual = self.knobs.set(p.knob, frm)
        self._m_reverts.inc(knob=p.knob)
        self._g_value.set(actual, knob=p.knob)
        flightrec.note(
            "autotune_revert",
            knob=p.knob,
            moved_to=to,
            reverted_to=actual,
            reason=reason,
        )
        return self._record(
            "revert", p.knob, value=actual, undone=to, reason=reason
        )

    # -- the next move --------------------------------------------------

    def _propose_move(self, now: float, rows: list[dict]) -> None:
        """One knob move per window: round-robin over eligible
        policies, direction from the policy hint (falling back to
        stored momentum, then +1)."""
        n = len(self.policies)
        for i in range(n):
            with self._lock:
                p = self.policies[(self._rr + i) % n]
                st = self._state[p.knob]
                if st.cooldown > 0:
                    st.cooldown -= 1
                    continue
                if st.pending_from is not None:
                    continue  # still awaiting a verdict
            if self.knobs.frozen(p.knob) is not None:
                continue
            if p.target is not None:
                if self._apply_direct(p, st, now, rows):
                    with self._lock:
                        self._rr = (self._rr + i + 1) % n
                    return
                continue
            if self._apply_climb(p, st, now, rows):
                with self._lock:
                    self._rr = (self._rr + i + 1) % n
                return
        with self._lock:
            self._rr = (self._rr + 1) % n if n else 0

    def _apply_direct(
        self, p: Policy, st: _KnobState, now: float, rows: list[dict]
    ) -> bool:
        tgt = p.target(self.history, now)
        if tgt is None:
            return False
        k = self.knobs.knob(p.knob)
        current = self.knobs.current(p.knob)
        want = k.clamp(tgt)
        if abs(want - current) < k.step:
            return False
        actual = self.knobs.set(p.knob, want)
        if actual == current:
            return False  # frozen race or dropped apply: no movement
        direction = "up" if actual > current else "down"
        self._m_decisions.inc(knob=p.knob, direction=direction)
        self._g_value.set(actual, knob=p.knob)
        flightrec.note(
            "autotune_decision",
            knob=p.knob,
            direction=direction,
            moved_from=current,
            moved_to=actual,
            mode="direct",
        )
        rows.append(
            self._record(
                "move",
                p.knob,
                mode="direct",
                direction=direction,
                moved_from=current,
                value=actual,
                cost_hint=k.cost_hint,
            )
        )
        return True

    def _apply_climb(
        self, p: Policy, st: _KnobState, now: float, rows: list[dict]
    ) -> bool:
        direction = 0
        if p.hint is not None:
            try:
                direction = int(p.hint(self.history, now) or 0)
            except Exception:  # noqa: BLE001 - a broken hint falls back
                # to momentum rather than killing the loop
                logger.exception("autotune: hint for %s failed", p.knob)
        if direction == 0:
            with self._lock:
                direction = st.direction or 1
        k = self.knobs.knob(p.knob)
        current = self.knobs.current(p.knob)
        want = k.clamp(current + direction * k.step)
        if want == current:
            # at a bound: try the other way once
            direction = -direction
            want = k.clamp(current + direction * k.step)
            if want == current:
                return False
        baseline = p.objective(self.history, now)
        actual = self.knobs.set(p.knob, want)
        if actual == current:
            return False  # dropped apply / frozen race: nothing moved
        dir_label = "up" if actual > current else "down"
        with self._lock:
            st.direction = 1 if actual > current else -1
            st.pending_from = current
            st.pending_to = actual
            st.pending_baseline = baseline
            st.pending_windows = 0
        self._m_decisions.inc(knob=p.knob, direction=dir_label)
        self._g_value.set(actual, knob=p.knob)
        flightrec.note(
            "autotune_decision",
            knob=p.knob,
            direction=dir_label,
            moved_from=current,
            moved_to=actual,
            mode="climb",
        )
        rows.append(
            self._record(
                "move",
                p.knob,
                mode="climb",
                direction=dir_label,
                moved_from=current,
                value=actual,
                baseline=baseline,
                cost_hint=k.cost_hint,
            )
        )
        return True
