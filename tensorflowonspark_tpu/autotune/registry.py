"""Knob registry: THE sanctioned mutation path for performance knobs.

The repo grew dozens of hand-set performance knobs (prefetch depth,
``decode_block``, ``pipeline_depth``, ``publish_blocks``, router
service-time estimates, ...). tf.data's core result (arXiv 2101.12127)
is that a feedback controller beats static hand-tuning — but a
controller is only trustworthy if it is the ONLY writer: a knob mutated
behind its back makes every revert decision wrong. So every tunable is
declared here as a :class:`Knob` (name, bounds, step granularity, the
actuation callback, a cost hint), and :meth:`KnobRegistry.set` is the
one path that mutates it. Raw attribute mutation of a tunable outside
its declared actuation methods is a build failure — tfoslint rule
AT001 (``analysis/autotune.py``) parses :data:`TUNABLE_ATTRS` and
:data:`SANCTIONED` from this file (the FP001 pattern) and flags
everything else; a justified exception carries
``# lint: knob-ok: <why>``.

Failure injection: the apply path threads the drop-aware
``autotune.apply`` failpoint. A dropped apply skips the actuation
callback entirely; the registry then records the READBACK value (what
the component actually runs with), so a lost apply can never wedge the
registry into believing a move happened — the controller sees no
movement, its objective does not improve, and it reverts cleanly.

Kill switch: ``TFOS_AUTOTUNE=0`` disables every controller
(:func:`enabled`); per-knob ``freeze`` pins one knob while the rest
keep tuning. With the switch off or all knobs frozen nothing in the
serving/feed path changes — the registry is pure bookkeeping until a
controller drives it.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable

from tensorflowonspark_tpu.obs import flightrec
from tensorflowonspark_tpu.utils.failpoints import failpoint

logger = logging.getLogger(__name__)

__all__ = [
    "Knob",
    "KnobRegistry",
    "SANCTIONED",
    "TUNABLE_ATTRS",
    "enabled",
]

#: Attribute names AT001 protects: an ``obj.<attr> = ...`` assignment
#: anywhere in the package is a violation unless it happens inside a
#: :data:`SANCTIONED` function or carries ``# lint: knob-ok: <why>``.
#: Kept as a plain literal frozenset — the lint rule parses this
#: assignment from DISK (ast, no import), exactly like FP001's SITES.
TUNABLE_ATTRS = frozenset(
    {
        "_capacity_bytes",  # cachetier/service.py CacheTier
        "_decode_block",  # serving/engine.py ContinuousBatcher
        "_pipeline_depth",  # serving/engine.py ContinuousBatcher
        "_prefetch_depth",  # feed/prefetch.py DevicePrefetcher
        "_publish_blocks",  # feed/ingest.py IngestFeed
        "_service_time_hint",  # serving/router.py FleetRouter
        "_seed_est_s",  # serving/router.py FleetRouter (history seed)
    }
)

#: ``ClassName.method`` qualified names allowed to assign the
#: attributes above: each knob's constructor default and its declared
#: live-actuation path. Everything else mutating a tunable is exactly
#: the ad-hoc knob poking this registry exists to end.
SANCTIONED = frozenset(
    {
        "CacheTier.__init__",
        "CacheTier.set_capacity",
        "ContinuousBatcher.__init__",
        "ContinuousBatcher._apply_pending_knobs",
        "DevicePrefetcher.__init__",
        "DevicePrefetcher.set_depth",
        "IngestFeed.__init__",
        "IngestFeed.set_publish_blocks",
        "FleetRouter.__init__",
        "FleetRouter.set_service_estimate",
        "FleetRouter.seed_from_history",
    }
)


def enabled() -> bool:
    """The process-wide kill switch: ``TFOS_AUTOTUNE=0`` (or
    false/no/off) disables every controller. Read per call — one dict
    lookup — so tests and operators can flip it live."""
    return os.environ.get("TFOS_AUTOTUNE", "1").strip().lower() not in (
        "0",
        "false",
        "no",
        "off",
    )


@dataclass
class Knob:
    """One registered tunable.

    ``apply`` is the actuation callback — always one of the component's
    declared live-set methods (``set_knobs``, ``set_depth``, ...), so
    the component's own locking/validation runs on every move. ``get``
    reads the value actually in effect (the readback); when provided,
    the registry trusts it over its own bookkeeping, which is what
    makes a dropped/failed apply self-correcting. ``cost_hint`` is a
    free-form note the controller surfaces in its decision log
    ("recompile", "queue-resize", "kv-republish") so an operator
    reading the audit trail knows what each move cost.
    """

    name: str
    lo: float
    hi: float
    step: float
    apply: Callable[[float], Any]
    get: Callable[[], float] | None = None
    cost_hint: str = ""
    integer: bool = True

    def __post_init__(self):
        if self.lo > self.hi:
            raise ValueError(
                f"knob {self.name!r}: lo {self.lo} > hi {self.hi}"
            )
        if self.step <= 0:
            raise ValueError(
                f"knob {self.name!r}: step must be > 0, got {self.step}"
            )

    def clamp(self, value: float) -> float:
        """Snap ``value`` to the knob's step grid (anchored at ``lo``)
        inside ``[lo, hi]``."""
        v = max(self.lo, min(self.hi, float(value)))
        v = self.lo + round((v - self.lo) / self.step) * self.step
        v = max(self.lo, min(self.hi, v))
        return float(int(round(v))) if self.integer else v


class KnobRegistry:
    """Declared knobs + freeze state; :meth:`set` is the one mutation
    path. Thread-safe: the lock covers bookkeeping only — actuation
    callbacks run OUTSIDE it (they may block on the component's own
    apply machinery, e.g. the engine scheduler's between-blocks
    install)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._knobs: dict[str, Knob] = {}  # guarded-by: self._lock
        self._frozen: dict[str, str] = {}  # name -> reason  # guarded-by: self._lock
        self._values: dict[str, float] = {}  # last readback  # guarded-by: self._lock

    # -- declaration ----------------------------------------------------

    def register(self, knob: Knob) -> Knob:
        seed = None
        if knob.get is not None:
            # readback OUTSIDE the registry lock: get() may take the
            # component's own lock, and nothing component-side may ever
            # nest under ours
            try:
                seed = float(knob.get())
            except Exception:  # noqa: BLE001 - readback is best-effort
                pass
        with self._lock:
            if knob.name in self._knobs:
                raise ValueError(f"knob {knob.name!r} already registered")
            self._knobs[knob.name] = knob
            if seed is not None:
                self._values[knob.name] = seed
        return knob

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._knobs)

    def knob(self, name: str) -> Knob:
        with self._lock:
            try:
                return self._knobs[name]
            except KeyError:
                raise KeyError(
                    f"unknown knob {name!r}; registered: "
                    f"{sorted(self._knobs)}"
                ) from None

    # -- freeze ---------------------------------------------------------

    def freeze(self, name: str, reason: str = "operator") -> None:
        """Pin one knob: the controller skips it until :meth:`unfreeze`.
        Audited — a frozen knob that silently stopped tuning would look
        identical to a broken controller."""
        k = self.knob(name)
        with self._lock:
            already = k.name in self._frozen
            self._frozen[k.name] = reason
        if not already:
            flightrec.note("autotune_frozen", knob=k.name, reason=reason)

    def unfreeze(self, name: str) -> None:
        with self._lock:
            self._frozen.pop(name, None)

    def frozen(self, name: str) -> str | None:
        """The freeze reason, or None when the knob is live."""
        with self._lock:
            return self._frozen.get(name)

    def all_frozen(self) -> bool:
        with self._lock:
            return bool(self._knobs) and set(self._frozen) >= set(
                self._knobs
            )

    # -- read -----------------------------------------------------------

    def current(self, name: str) -> float:
        """The value in effect: live readback when the knob declares
        ``get``, else the last value this registry applied."""
        k = self.knob(name)
        if k.get is not None:
            v = float(k.get())
            with self._lock:
                self._values[name] = v
            return v
        with self._lock:
            return self._values.get(name, k.lo)

    def snapshot(self) -> dict[str, dict[str, Any]]:
        """JSON-safe view of every knob (bench artifacts, /statusz)."""
        out: dict[str, dict[str, Any]] = {}
        for name in self.names():
            k = self.knob(name)
            out[name] = {
                "value": self.current(name),
                "lo": k.lo,
                "hi": k.hi,
                "step": k.step,
                "cost_hint": k.cost_hint,
                "frozen": self.frozen(name),
            }
        return out

    # -- the one mutation path ------------------------------------------

    def set(self, name: str, value: float) -> float:
        """Apply ``value`` (clamped to the knob's grid) through the
        knob's actuation callback; returns the value actually in effect
        afterwards. Frozen knobs do not move. A dropped apply (the
        ``autotune.apply`` failpoint) skips the callback — the readback
        keeps registry state truthful, so the caller observes no
        movement instead of a lie. A RAISING callback propagates after
        the registry re-reads the component (consistent either way)."""
        k = self.knob(name)
        if self.frozen(name) is not None:
            return self.current(name)
        target = k.clamp(value)
        if failpoint("autotune.apply") == "drop":
            # chaos: the lost apply. Nothing was actuated; re-read the
            # component so our bookkeeping cannot drift from reality.
            logger.warning(
                "autotune apply dropped (failpoint): knob %s -> %s "
                "not actuated",
                name,
                target,
            )
            return self.current(name)
        try:
            k.apply(int(target) if k.integer else target)
        finally:
            # success or raise, the registry's view is the readback
            actual = self.current(name)
        return actual
