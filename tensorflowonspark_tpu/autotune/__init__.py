"""tfos.autotune — feedback-controlled online knob tuning.

The tf.data result (arXiv 2101.12127) applied to this repo's own
knobs: a :class:`KnobRegistry` of declared tunables (the ONE sanctioned
mutation path — lint rule AT001 enforces it), a gradient-free
:class:`Controller` (hill-climb with hysteresis, per-knob cooldown,
one move per history window, automatic revert on regression, SLO-breach
back-off), and concrete :mod:`policies` for the feed, engine, router,
and ingest planes. Fully auditable (flightrec events + metrics +
decision log) and fully killable (``TFOS_AUTOTUNE=0``, per-knob
freeze). See docs/AUTOTUNE.md.
"""

from tensorflowonspark_tpu.autotune.controller import Controller, Policy
from tensorflowonspark_tpu.autotune.registry import (
    Knob,
    KnobRegistry,
    enabled,
)

__all__ = [
    "Controller",
    "Knob",
    "KnobRegistry",
    "Policy",
    "enabled",
]
