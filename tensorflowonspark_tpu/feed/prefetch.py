"""Host->device prefetch: overlap transfer with the training step.

The reference's feed path stopped at the host (Spark task -> manager queue
-> ``DataFeed`` -> ``tf.data``); TF's runtime hid the host->device copy.
In JAX that copy is explicit (``device_put`` / ``shard_batch``), and on
TPU hosts it is worth a dedicated thread: while step N executes, batch
N+1 is already in flight over PCIe/DCN. Measured on this environment's
tunneled chip: a transfer-bound MNIST loop went from ~432 ms to ~36 ms
per iteration with depth-2 prefetch (the transfer fully hides behind
compute once depth >= 2).

Usage::

    feed = ctx.get_data_feed()
    pf = DevicePrefetcher(
        (feed.next_batch(bs) for _ in iter(int, 1)), mesh, depth=2
    )
    for batch in pf:          # device-resident, mesh-sharded batches
        state, loss = step(state, batch)
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

import jax
import numpy as np

from tensorflowonspark_tpu.compute.mesh import shard_batch
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.utils.failpoints import failpoint

logger = logging.getLogger(__name__)

_DONE = object()


# -- obs ---------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: dict[str, Any] | None = None


def metrics() -> dict[str, Any]:
    """Consumer-side feed counters in the process-global obs registry.
    The ``feed.data_wait`` span already narrates per-wait timing into
    the trace plane, but spans do not land in the metrics registry —
    and the autotune prefetch-depth policy needs a *windowed* wait
    share (``History.delta_sum`` over ``feed_data_wait_seconds``) plus
    a delivered-batches throughput objective (``feed_batches_total``)
    to decide grow-vs-shrink. Registered lazily so merely importing the
    feed package never touches the registry."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from tensorflowonspark_tpu.obs.registry import default_registry

                r = default_registry()
                _metrics = {
                    "data_wait_s": r.histogram(
                        "feed_data_wait_seconds",
                        "seconds the training loop blocked waiting for "
                        "the next device batch",
                    ),
                    "batches": r.counter(
                        "feed_batches_total",
                        "device batches delivered to the training loop "
                        "by DevicePrefetcher",
                    ),
                }
    return _metrics


class _StagingPool:
    """Rotating host staging buffers for the producer thread.

    Columnar batches arrive as views over wire memory (ring slots, TCP
    bytes, mmaps); copying them into a small pool of REUSED contiguous
    host buffers right before ``device_put`` (a) releases the underlying
    ring frame the moment the batch is staged — the "consumed or
    transferred" end of the zero-copy lifetime — and (b) stops the
    steady-state loop from allocating fresh host arrays per batch. The
    pool holds ``depth + 2`` slots so a buffer is never rewritten while
    its batch can still be in flight (queue depth + the consumer's
    current batch + the one being staged) — and, because the Python-side
    window cannot bound XLA's async H2D copy, ``stage`` additionally
    blocks on the slot's PREVIOUS device transfer before rewriting it
    (``commit`` records each transfer result against its slot). Without
    that, an input-bound loop on TPU/GPU could overwrite host memory a
    still-running DMA is reading from."""

    def __init__(self, slots: int):
        self._slots: list[dict | None] = [None] * max(1, slots)
        self._inflight: list[Any] = [None] * max(1, slots)
        self._i = 0
        self._staged_i: int | None = None

    def ensure(self, slots: int) -> None:
        """Grow the pool (never shrink: a retired slot's buffer may
        still back an enqueued batch). Called from the producer thread
        between batches when a live ``set_depth`` widened the window
        past the pool built at construction — without this, a deeper
        queue would let ``stage`` rewrite a host buffer whose batch is
        still waiting to be consumed."""
        extra = int(slots) - len(self._slots)
        if extra > 0:
            self._slots.extend([None] * extra)
            self._inflight.extend([None] * extra)

    def stage(self, batch):
        if not isinstance(batch, dict):
            self._staged_i = None
            return batch  # row-list batches pass through untouched
        i = self._i
        prev = self._inflight[i]
        if prev is not None:
            jax.block_until_ready(prev)
            self._inflight[i] = None
        slot = self._slots[i]
        if (
            slot is None
            or len(slot) != len(batch)
            or any(
                k not in slot
                or slot[k].shape != v.shape
                or slot[k].dtype != v.dtype
                for k, v in batch.items()
            )
        ):
            slot = {
                k: np.empty(v.shape, v.dtype) for k, v in batch.items()
            }
            self._slots[i] = slot
        for k, v in batch.items():
            np.copyto(slot[k], v)
        self._staged_i = i
        self._i = (i + 1) % len(self._slots)
        return slot

    def commit(self, transferred) -> None:
        """Tie the device-side result of the just-staged batch to its
        slot, so the next ``stage`` of that slot can wait out the
        transfer before rewriting the host buffer."""
        if self._staged_i is not None:
            self._inflight[self._staged_i] = transferred
            self._staged_i = None


class DevicePrefetcher:
    """Iterate device-resident batches, transferring ``depth`` ahead.

    ``host_batches`` yields host batches (dict/list/array pytrees);
    ``transform`` (default :func:`shard_batch` over ``mesh``) moves one
    batch to device. The background (daemon) thread stops at iterator
    exhaustion or on ``close()`` — call ``close()`` (or use the context
    manager) when abandoning the iterator early, otherwise the producer
    keeps ``depth`` transferred batches alive until process exit. A raise
    in the producer (e.g. a feed timeout) is re-raised at the consumer's
    next ``__next__`` so errors keep flowing to the training loop.
    """

    def __init__(
        self,
        host_batches: Iterable[Any],
        mesh=None,
        depth: int = 2,
        transform: Callable[[Any], Any] | None = None,
    ):
        if transform is None:
            if mesh is None:
                raise ValueError("need a mesh or an explicit transform")
            transform = lambda b: shard_batch(mesh, b)  # noqa: E731
        self._transform = transform
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        # Cross-thread stats: the producer thread writes, consumers read
        # via stats() — the "is the input plane keeping up" numbers next
        # to the feed.transfer/feed.data_wait spans.
        self._lock = threading.Lock()
        self._prefetch_depth = max(1, int(depth))  # guarded-by: self._lock
        self._transferred = 0  # guarded-by: self._lock
        self._transfer_s = 0.0  # guarded-by: self._lock
        self._thread = threading.Thread(
            target=self._run, args=(iter(host_batches),), daemon=True
        )
        self._thread.start()

    @classmethod
    def from_feed(
        cls,
        feed,
        batch_size: int,
        mesh=None,
        depth: int = 2,
        multiple_of: int = 1,
        prepare: Callable[[Any], Any] | None = None,
        transform: Callable[[Any], Any] | None = None,
        input_mapping: dict[str, str] | None = None,
    ) -> "DevicePrefetcher":
        """THE default training-loop input: device batches straight off a
        :class:`~tensorflowonspark_tpu.feed.datafeed.DataFeed` — or any
        feed with its ``batch_stream`` contract: ``ManifestFeed``
        (manifest records expanded node-locally inside SPARK mode) and
        ``IngestFeed`` (the pull plane's executor-local shard readers)
        plug in unchanged, so both planes end at the same staging +
        H2D/compute overlap.

        The producer thread pulls ``feed.batch_stream(batch_size,
        multiple_of)`` — columnar wire chunks are batch-sliced as
        zero-copy views there — runs ``prepare`` (optional host-side
        transform: dtype casts, normalization), stages the batch into a
        reused host buffer (releasing the underlying ring frame), and
        issues ``shard_batch``/``device_put`` — so columnize + H2D fully
        hide behind step compute::

            feed = ctx.get_data_feed(input_mapping={...})
            with DevicePrefetcher.from_feed(
                feed, bs, mesh, multiple_of=jax.device_count()
            ) as pf:
                for batch in pf:
                    state, loss = step(state, batch)
        """
        staging = _StagingPool(depth + 2)
        if transform is None:
            if mesh is None:
                raise ValueError("need a mesh or an explicit transform")
            transform = lambda b: shard_batch(mesh, b)  # noqa: E731

        # ManifestFeed takes the column mapping at batch_stream (its feed
        # records are manifests, not rows); DataFeed holds it from the ctor.
        kwargs = {} if input_mapping is None else {"input_mapping": input_mapping}

        def host_batches():
            for cols in feed.batch_stream(batch_size, multiple_of, **kwargs):
                yield cols

        holder: dict = {}  # filled after cls() below; producer-thread read

        def stage_and_transfer(cols):
            pf = holder.get("pf")
            if pf is not None:
                # a live set_depth may have widened the window; the
                # pool must cover queue depth + consumer + staging
                staging.ensure(pf.stats()["depth"] + 2)
            if prepare is not None:
                cols = prepare(cols)
            out = transform(staging.stage(cols))
            staging.commit(out)
            return out

        pf = cls(host_batches(), depth=depth, transform=stage_and_transfer)
        holder["pf"] = pf
        return pf

    def stats(self) -> dict:
        """Producer-side counters: batches transferred to device and
        total transfer seconds (divide for the mean transfer cost this
        prefetcher is hiding), plus the current prefetch depth. Safe
        from any thread."""
        with self._lock:
            return {
                "transferred": self._transferred,
                "transfer_s": self._transfer_s,
                "depth": self._prefetch_depth,
            }

    def set_depth(self, depth: int) -> int:
        """Live-resize the prefetch window (the autotune actuation path
        for the ``feed.prefetch_depth`` knob). ``queue.Queue`` freezes
        ``maxsize`` at construction but only consults it under its own
        mutex, so a guarded rewrite plus ``not_full.notify_all()`` is a
        safe live resize: growing immediately unblocks a producer
        waiting in ``put``; shrinking takes effect as the consumer
        drains the (briefly oversized) queue down to the new bound.
        Returns the depth actually in effect."""
        depth = max(1, int(depth))
        q = self._queue
        with q.mutex:
            q.maxsize = depth
            q.not_full.notify_all()
        with self._lock:
            self._prefetch_depth = depth
        return depth

    def _run(self, it: Iterator[Any]) -> None:
        try:
            for batch in it:
                if self._stop.is_set():
                    return
                # chaos: a producer raise here must ferry to the
                # consumer's next __next__, like any real transfer error
                failpoint("prefetch.producer")
                # host->device transfer time, on the producer thread —
                # beside feed.data_wait it answers "is the input plane
                # keeping up or is the consumer starving"
                t0 = time.perf_counter()
                with obs_spans.span("feed.transfer"):
                    item = (self._transform(batch), None)
                with self._lock:
                    self._transferred += 1
                    self._transfer_s += time.perf_counter() - t0
                while not self._stop.is_set():
                    try:
                        self._queue.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        continue
            self._put_final((_DONE, None))
        except BaseException as e:  # ferry the error to the consumer
            self._put_final((_DONE, e))

    def _put_final(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.2)
                return
            except queue.Full:
                continue

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        if self._stop.is_set():  # exhausted or closed: stay stopped
            raise StopIteration
        # data-wait: how long the training loop sat here is THE
        # input-bound-vs-compute-bound discriminator (tf.data's
        # bottleneck analysis asks exactly this question)
        t0 = time.perf_counter()
        with obs_spans.span("feed.data_wait"):
            batch, err = self._queue.get()
        m = metrics()
        m["data_wait_s"].observe(time.perf_counter() - t0)
        if batch is _DONE:
            self._stop.set()
            if err is not None:
                raise err
            raise StopIteration
        m["batches"].inc()
        return batch

    def close(self) -> bool:
        """Stop the producer and drain the queue; returns whether the
        producer thread actually joined (mirrors ``EmitWorker.stop``:
        ``False`` means it is wedged mid-transfer and was abandoned)."""
        self._stop.set()

        # drain so the producer's blocked put can observe the stop flag;
        # a ferried terminal error found here would otherwise vanish
        # silently with the queue
        def _drain() -> BaseException | None:
            found: BaseException | None = None
            try:
                while True:
                    batch, err = self._queue.get_nowait()
                    if batch is _DONE and err is not None:
                        found = err
            except queue.Empty:
                return found

        swallowed = _drain()
        self._thread.join(timeout=5)
        joined = not self._thread.is_alive()
        # re-drain after the join: _put_final checks the stop flag only
        # BETWEEN put attempts, so an in-flight put can land the ferried
        # (_DONE, err) just after the first drain emptied the queue
        swallowed = _drain() or swallowed
        if swallowed is not None:
            logger.warning(
                "DevicePrefetcher.close: discarding ferried producer "
                "error (never observed by the consumer): %r",
                swallowed,
            )
        if not joined:
            logger.warning(
                "DevicePrefetcher.close: producer thread did not join "
                "within 5s (stuck in transform/transfer); abandoning it"
            )
        return joined

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
