"""Host->device prefetch: overlap transfer with the training step.

The reference's feed path stopped at the host (Spark task -> manager queue
-> ``DataFeed`` -> ``tf.data``); TF's runtime hid the host->device copy.
In JAX that copy is explicit (``device_put`` / ``shard_batch``), and on
TPU hosts it is worth a dedicated thread: while step N executes, batch
N+1 is already in flight over PCIe/DCN. Measured on this environment's
tunneled chip: a transfer-bound MNIST loop went from ~432 ms to ~36 ms
per iteration with depth-2 prefetch (the transfer fully hides behind
compute once depth >= 2).

Usage::

    feed = ctx.get_data_feed()
    pf = DevicePrefetcher(
        (feed.next_batch(bs) for _ in iter(int, 1)), mesh, depth=2
    )
    for batch in pf:          # device-resident, mesh-sharded batches
        state, loss = step(state, batch)
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

from tensorflowonspark_tpu.compute.mesh import shard_batch
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.utils.failpoints import failpoint

_DONE = object()


class DevicePrefetcher:
    """Iterate device-resident batches, transferring ``depth`` ahead.

    ``host_batches`` yields host batches (dict/list/array pytrees);
    ``transform`` (default :func:`shard_batch` over ``mesh``) moves one
    batch to device. The background (daemon) thread stops at iterator
    exhaustion or on ``close()`` — call ``close()`` (or use the context
    manager) when abandoning the iterator early, otherwise the producer
    keeps ``depth`` transferred batches alive until process exit. A raise
    in the producer (e.g. a feed timeout) is re-raised at the consumer's
    next ``__next__`` so errors keep flowing to the training loop.
    """

    def __init__(
        self,
        host_batches: Iterable[Any],
        mesh=None,
        depth: int = 2,
        transform: Callable[[Any], Any] | None = None,
    ):
        if transform is None:
            if mesh is None:
                raise ValueError("need a mesh or an explicit transform")
            transform = lambda b: shard_batch(mesh, b)  # noqa: E731
        self._transform = transform
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        # Cross-thread stats: the producer thread writes, consumers read
        # via stats() — the "is the input plane keeping up" numbers next
        # to the feed.transfer/feed.data_wait spans.
        self._lock = threading.Lock()
        self._transferred = 0  # guarded-by: self._lock
        self._transfer_s = 0.0  # guarded-by: self._lock
        self._thread = threading.Thread(
            target=self._run, args=(iter(host_batches),), daemon=True
        )
        self._thread.start()

    def stats(self) -> dict:
        """Producer-side counters: batches transferred to device and
        total transfer seconds (divide for the mean transfer cost this
        prefetcher is hiding). Safe from any thread."""
        with self._lock:
            return {
                "transferred": self._transferred,
                "transfer_s": self._transfer_s,
            }

    def _run(self, it: Iterator[Any]) -> None:
        try:
            for batch in it:
                if self._stop.is_set():
                    return
                # chaos: a producer raise here must ferry to the
                # consumer's next __next__, like any real transfer error
                failpoint("prefetch.producer")
                # host->device transfer time, on the producer thread —
                # beside feed.data_wait it answers "is the input plane
                # keeping up or is the consumer starving"
                t0 = time.perf_counter()
                with obs_spans.span("feed.transfer"):
                    item = (self._transform(batch), None)
                with self._lock:
                    self._transferred += 1
                    self._transfer_s += time.perf_counter() - t0
                while not self._stop.is_set():
                    try:
                        self._queue.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        continue
            self._put_final((_DONE, None))
        except BaseException as e:  # ferry the error to the consumer
            self._put_final((_DONE, e))

    def _put_final(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.2)
                return
            except queue.Full:
                continue

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        if self._stop.is_set():  # exhausted or closed: stay stopped
            raise StopIteration
        # data-wait: how long the training loop sat here is THE
        # input-bound-vs-compute-bound discriminator (tf.data's
        # bottleneck analysis asks exactly this question)
        with obs_spans.span("feed.data_wait"):
            batch, err = self._queue.get()
        if batch is _DONE:
            self._stop.set()
            if err is not None:
                raise err
            raise StopIteration
        return batch

    def close(self) -> None:
        self._stop.set()
        # drain so the producer's blocked put can observe the stop flag
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
